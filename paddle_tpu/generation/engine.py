"""Compile-once generation engine: bucketed prefill + O(1)-cache decode.

The serving batcher bounds the BATCH axis with a powers-of-two bucket
ladder; autoregressive decoding re-opens the same compile-explosion on
the SEQUENCE axis (every prompt length and every growing context is a
new XLA program if shapes are dynamic). The engine closes it with a
prefill/decode split:

- **Prefill** pads the prompt up to a sequence-length bucket ladder
  (``FLAGS_generation_prefill_buckets``) and runs ONE full forward over
  the bucket, writing K/V into the admitted slot of the static ring
  cache — one compile per ladder bucket, ever.
- **Decode** is a single jitted step over ALL decode slots: read last
  tokens ``[S]``, attend the static cache window, sample, write back —
  its shapes never depend on sequence length or slot turnover, so its
  steady-state compile count is exactly 1 (asserted in tests and the
  gen-smoke the same way ``serving/unexpected_compiles`` is).

Compile accounting mirrors the serving pool: every new signature is AOT
lowered/compiled through the cost model (so decode MFU lands in the
``/statz`` ledger) and bumps the ``generation::compile`` profiler
counter — warmup snapshots it, and ``extra_compiles()`` must stay 0
under any traffic mix.

**Speculative decoding** (pass ``draft_model``): decode is memory-bound
and serial — every token pays one full-model dispatch. A small draft
GPT proposes ``k`` greedy tokens per slot (one compiled "draft" program
running the whole chain), and the target model scores all ``k + 1``
positions in ONE batched forward (the "verify" program): the longest
proposal prefix matching the target's own sampled chain is accepted,
and the target sample one past it is emitted as the correction/bonus
token — so every round emits ``1..k+1`` tokens for two dispatches
instead of ``1`` per dispatch. Greedy output is token-identical to the
plain engine by construction (acceptance compares against the target
argmax chain itself); sampled output draws every emitted token from the
target's own distribution. Both programs compile once through the
CompiledStore and the ring cache commit is the same functional index
update discipline — the physical ring simply carries ``draft_k`` extra
scratch entries (see generation/cache.py "store vs window") so the
verify step's in-place span write can never clobber a live window
entry. Rejected-position writes are garbage but provably masked until
the next round overwrites them.

**Disaggregated prefill/decode** (``kind`` warmup + KV handoff): a
prefill-tier engine runs only :meth:`prefill_export` (bucket-ladder
forward into window-width fresh caches, returning the slot's KV slab +
first sampled token), a decode-tier engine admits that slab with
:meth:`admit_prefilled` (pad to the ring store + ``insert_slot_kv``)
and runs only the decode/speculative programs — prefill scales on
compute, decode on HBM, and each tier's warmup compiles exactly its
own program set (``expected_compiles(kind)``).

The engine is single-threaded by design (one decode stream per model
replica); :mod:`paddle_tpu.serving.continuous` drives it from a slot
scheduler for continuous batching, and :meth:`generate` runs the same
slot loop inline for offline use (bench, tests, parity goldens).
"""
from __future__ import annotations

import itertools
import threading
from collections import OrderedDict, deque

import numpy as np

import jax
import jax.numpy as jnp

from ..errors import InvalidArgumentError
from ..flags import flag
from ..framework.jit import functional_call
from ..monitor import flight_recorder as _flight
from ..monitor import tracing as _tracing
from ..profiler import RecordEvent, counters as _counters
from . import cache as _cache
from . import paging as _paging
from .sampling import sample_logits

__all__ = ["GenerationEngine", "COMPILE_COUNTER"]

COMPILE_COUNTER = "generation::compile"

# deterministic engine instance ids (cache-key stability; see __init__)
_engine_counter = itertools.count()


class GenerationEngine:
    """Slot-structured generation over a causal LM.

    ``model`` must expose ``forward(input_ids, position_ids,
    attention_mask, caches) -> (logits, caches)`` with per-layer
    :class:`nn.StaticCache` support plus ``cache_spec()`` (GPTForCausalLM
    is the reference implementation). The engine owns the stacked ring
    cache for ``slots`` concurrent sequences and exposes the two
    scheduler primitives: :meth:`admit` (prefill a prompt into a vacant
    slot, returns the first sampled token) and :meth:`step` (decode one
    token for every slot).
    """

    def __init__(self, model, *, slots=None, cache_len=None,
                 prefill_buckets=None, eos_id=None, pad_id=None,
                 max_new_tokens=None, temperature=None, top_k=None,
                 kv_cache_dtype=None, kv_cache_layout=None,
                 kv_page_size=None, kv_pool_pages=None,
                 draft_model=None, draft_k=None, seed=0):
        # lazy: serving imports generation's scheduler, so module-level
        # imports the other way would cycle
        from ..serving.batcher import parse_buckets

        from ..runtime.compiled import CompiledStore, CompileWatch

        self.model = model
        model.eval()  # generation never wants dropout
        cfg = getattr(model, "config", None)
        self.slots = int(slots if slots is not None
                         else flag("generation_decode_slots"))
        self.cache_len = int(cache_len if cache_len is not None
                             else flag("generation_kv_cache_len"))
        self.prefill_buckets = parse_buckets(
            prefill_buckets if prefill_buckets is not None
            else flag("generation_prefill_buckets"))
        if self.slots <= 0:
            raise InvalidArgumentError(
                f"generation needs at least one decode slot, got {self.slots}")
        if self.prefill_buckets[-1] > self.cache_len:
            raise InvalidArgumentError(
                f"largest prefill bucket {self.prefill_buckets[-1]} exceeds "
                f"the KV cache window {self.cache_len}; prompts must fit "
                "the cache")
        self.eos_id = (eos_id if eos_id is not None
                       else getattr(cfg, "eos_token_id", None))
        self.pad_id = int(pad_id if pad_id is not None
                          else getattr(cfg, "pad_token_id", 0))
        self.max_positions = int(getattr(cfg, "max_position_embeddings",
                                         1 << 30))
        self.default_max_new_tokens = int(
            max_new_tokens if max_new_tokens is not None
            else flag("generation_max_new_tokens"))
        self.default_temperature = float(
            temperature if temperature is not None
            else flag("generation_temperature"))
        # static: a different top_k is a different program (lax.top_k k);
        # per-request temperature stays a traced array and is free
        self.top_k = int(top_k if top_k is not None
                         else flag("generation_top_k"))
        # KV storage dtype: int8 stores the ring cache as int8 + per-head
        # dynamic scales (~4x fewer cache bytes -> ~2x the slots per HBM;
        # quantize on ring write, dequantize in the attention read). The
        # int8 avals change the compiled signature, so each dtype mode
        # gets its own cache keys in the CompiledStore — never a silent
        # reuse of the other mode's program.
        self.kv_cache_dtype = str(
            kv_cache_dtype if kv_cache_dtype is not None
            else flag("generation_kv_cache_dtype"))
        if self.kv_cache_dtype not in _cache.KV_CACHE_DTYPES:
            raise InvalidArgumentError(
                f"generation_kv_cache_dtype must be one of "
                f"{_cache.KV_CACHE_DTYPES}, got {self.kv_cache_dtype!r}")
        spec = model.cache_spec()
        self._num_layers, self._num_heads, self._head_dim = (
            int(spec[0]), int(spec[1]), int(spec[2]))
        # speculative decoding: a draft model makes the engine run
        # draft/verify rounds instead of single-token decode steps. The
        # physical ring store widens by draft_k scratch entries so the
        # verify span's in-place writes stay window-exact (cache.py).
        self.draft_model = draft_model
        self.speculative = draft_model is not None
        self.draft_k = int(draft_k if draft_k is not None
                           else flag("speculative_draft_k"))
        if self.speculative:
            if self.draft_k < 1:
                raise InvalidArgumentError(
                    f"speculative draft_k must be >= 1, got {self.draft_k}")
            draft_model.eval()
            dspec = draft_model.cache_spec()
            self._draft_layers, self._draft_heads, self._draft_dim = (
                int(dspec[0]), int(dspec[1]), int(dspec[2]))
            dcfg = getattr(draft_model, "config", None)
            self._draft_max_positions = int(getattr(
                dcfg, "max_position_embeddings", 1 << 30))
            dvocab = getattr(dcfg, "vocab_size", None)
            tvocab = getattr(cfg, "vocab_size", None)
            if dvocab is not None and tvocab is not None \
                    and int(dvocab) != int(tvocab):
                raise InvalidArgumentError(
                    f"draft model vocab ({dvocab}) must match the target "
                    f"({tvocab}); proposals are target token ids")
            tmax = int(getattr(cfg, "max_position_embeddings", 1 << 30))
            if self._draft_max_positions < tmax:
                # the draft tracks the target's positions exactly; a
                # shorter draft context would silently gather clamped
                # position embeddings past its limit (garbage prompt
                # view, acceptance collapse) — refuse loudly instead
                raise InvalidArgumentError(
                    f"draft max_position_embeddings "
                    f"({self._draft_max_positions}) must cover the "
                    f"target's ({tmax}); the draft decodes the same "
                    "positions")
        self.store_len = self.cache_len + (
            self.draft_k if self.speculative else 0)
        # KV layout: "ring" is the historical per-slot contiguous store;
        # "paged" draws fixed-size pages from a shared pool through
        # per-slot page tables (generation/paging.py) — same logical
        # ring, so greedy output is token-identical, plus copy-on-write
        # prefix reuse across requests.
        self.kv_cache_layout = str(
            kv_cache_layout if kv_cache_layout is not None
            else flag("kv_cache_layout"))
        if self.kv_cache_layout not in ("ring", "paged"):
            raise InvalidArgumentError(
                f"kv_cache_layout must be ring | paged, got "
                f"{self.kv_cache_layout!r}")
        self.paged = self.kv_cache_layout == "paged"
        if self.paged and self.speculative:
            raise InvalidArgumentError(
                "speculative decoding does not compose with "
                "kv_cache_layout=paged yet; run the draft engine on the "
                "ring layout")
        self.page_size = int(kv_page_size if kv_page_size is not None
                             else flag("generation_kv_page_size"))
        if self.paged:
            if self.page_size < 1 or self.cache_len % self.page_size:
                raise InvalidArgumentError(
                    f"generation_kv_page_size {self.page_size} must be "
                    f">= 1 and divide the cache window {self.cache_len}")
            self._pages_per_slot = self.cache_len // self.page_size
            self._pool_pages_cfg = int(
                kv_pool_pages if kv_pool_pages is not None
                else flag("generation_kv_pool_pages"))
            if self._pool_pages_cfg < 0:
                raise InvalidArgumentError(
                    f"generation_kv_pool_pages must be >= 0, got "
                    f"{self._pool_pages_cfg}")
            if self._pool_pages_cfg \
                    and self._pool_pages_cfg < self._pages_per_slot:
                raise InvalidArgumentError(
                    f"generation_kv_pool_pages {self._pool_pages_cfg} "
                    f"cannot hold even one slot's window "
                    f"({self._pages_per_slot} pages)")
        # static capacity admission (FLAGS_memory_budget_check): the
        # slots x cache-len x dtype geometry is budgeted against the
        # device HBM BEFORE the rings allocate — a fleet operator learns
        # "this geometry cannot fit; suggest_decode_slots says N" at
        # boot, not as an allocator OOM mid-warmup
        self.check_memory_budget()
        self._base_key = jax.random.PRNGKey(int(seed))
        self._key_step = 0
        # the sampling-key counter is bumped from every dispatch path and
        # those paths run on different threads (prefill from HTTP handler
        # threads, decode from the batcher loop): every bump goes through
        # _next_key_step, which locks AND returns the snapshot — a bare
        # `+= 1` followed by a re-read hands two threads the same ctr,
        # correlating two requests' samples. The same lock guards the
        # speculative acceptance counters /statz reads.
        self._key_lock = threading.Lock()
        # speculative acceptance accounting (spec_stats / statz)
        self._spec_rounds = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        # prefix sharing is suppressed during warmup (every ladder
        # bucket must compile its own program; a matched prefix would
        # collapse later buckets onto already-compiled suffix shapes)
        self._prefix_enabled = True
        self.reset()
        # eval_step-style snapshot: walk the module tree once, read the
        # live arrays per call (cheap, and parameter updates flow in)
        self._named = None
        self._draft_named = None
        self._prefill_jit = jax.jit(self._prefill_pure)
        self._spec_prefill_jit = jax.jit(self._spec_prefill_pure)
        self._decode_jit = jax.jit(self._decode_pure)
        self._paged_prefill_jit = jax.jit(self._paged_prefill_pure)
        self._paged_decode_jit = jax.jit(self._paged_decode_pure)
        self._prefill_export_jit = jax.jit(self._prefill_export_pure)
        self._draft_jit = jax.jit(self._draft_chain_pure)
        self._verify_jit = jax.jit(self._verify_pure)
        self._draft_prefill_jit = jax.jit(self._draft_prefill_pure)
        # compiled prefill/decode programs live in the SHARED compiled-
        # callable runtime: AOT compile + cost capture (decode MFU in the
        # /statz ledger) + the flag-governed LRU bound, with every new
        # signature counted through ``generation::compile`` — the
        # bounded-compile discipline the batch-bucket ladder established,
        # on the sequence axis
        self._stores = {
            label: CompiledStore(f"generation_{label}",
                                 miss_counter=COMPILE_COUNTER)
            for label in ("prefill", "decode", "draft", "verify")}
        # deterministic per-engine index for the cache signature (stable
        # cache_key across runs, distinct per engine in the CostRecord
        # registry — two engines may share avals but not weights)
        self._instance = next(_engine_counter)
        self.warmed = False
        # the serving-wide warmup-snapshot discipline; the continuous
        # batcher notes growth through this same watch
        self.watch = CompileWatch(
            lambda: _counters().get(COMPILE_COUNTER, 0),
            metric="serving/gen_unexpected_compiles",
            event="generation_unexpected_compile")

    # -- functional state -----------------------------------------------------

    @staticmethod
    def _snapshot_named(model):
        return {
            "params": [(n, p, getattr(p, "trainable", True))
                       for n, p in model.named_parameters()],
            "buffers": [(n, b) for n, b in model.named_buffers()
                        if b is not None],
        }

    @staticmethod
    def _named_state(named):
        params, frozen = OrderedDict(), OrderedDict()
        for n, p, trainable in named["params"]:
            (params if trainable else frozen)[n] = p._array
        return {
            "params": params,
            "frozen": frozen,
            "buffers": OrderedDict(
                (n, b._array) for n, b in named["buffers"]),
        }

    def _state(self):
        if self._named is None:
            self._named = self._snapshot_named(self.model)
        return self._named_state(self._named)

    def _draft_state(self):
        if self._draft_named is None:
            self._draft_named = self._snapshot_named(self.draft_model)
        return self._named_state(self._draft_named)

    def reset(self):
        """Zero every slot (all caches empty, positions 0). A paged
        engine additionally rebuilds the page pool, page tables, and
        prefix index from scratch."""
        from ..monitor import registry as _mon

        ring_slots = getattr(self, "_ring_slots", self.slots)
        if self.paged:
            usable = self._pool_usable(ring_slots)
            self._kv = _paging.init_paged_cache(
                self._num_layers, self._num_heads, self._head_dim,
                self.page_size, usable, ring_slots,
                self._pages_per_slot, dtype=self.kv_cache_dtype)
            self._pool = _paging.PagePool(usable, self.page_size)
            self._index = _paging.PrefixIndex(self._pool)
            self._table_host = np.full(
                (ring_slots, self._pages_per_slot), _paging.TRASH_PAGE,
                np.int32)
            self._pos_host = np.zeros(ring_slots, np.int64)
            self._slot_live = [False] * ring_slots
            self._slot_tenant = ["default"] * ring_slots
            # per-tenant prefix accounting (prompt vs shared tokens)
            self._prefix_tenants = {}
            self._pool_gauges()
        else:
            self._kv = _cache.init_cache(
                self._num_layers, ring_slots, self._num_heads,
                self.store_len, self._head_dim,
                dtype=self.kv_cache_dtype)
        if self.speculative:
            # draft ring arrays only — the draft mirrors the target's
            # committed token history exactly, so ONE shared pos vector
            # (the target kv's) serves both caches
            self._kv_draft = _cache.init_cache(
                self._draft_layers, ring_slots, self._draft_heads,
                self.store_len, self._draft_dim,
                dtype=self.kv_cache_dtype)[:-1]
        # the decode-capacity denominators, as registry gauges: what the
        # KV cache costs in HBM lands in /metrics next to the hbm/*
        # gauges it competes with (int8 mode shows the ~4x cut directly)
        _mon.gauge("generation/kv_cache_bytes").set(
            _cache.cache_nbytes(self._kv))
        _mon.gauge("generation/kv_bytes_per_token").set(
            self.kv_bytes_per_token())
        return self

    def cache_nbytes(self) -> int:
        """Device bytes the whole decode cache occupies (all slots,
        values + scales + positions, plus the draft ring when
        speculative) — the measured side of the int8-vs-f32 HBM claim."""
        n = _cache.cache_nbytes(self._kv)
        if self.speculative:
            n += _cache.cache_nbytes(self._kv_draft)
        return n

    def kv_bytes_per_token(self) -> int:
        """Cache bytes one decoded token occupies across all layers."""
        return _cache.kv_bytes_per_token(
            self._num_layers, self._num_heads, self._head_dim,
            self.kv_cache_dtype)

    # -- static HBM capacity planning -----------------------------------------

    @staticmethod
    def _module_nbytes(model) -> int:
        total = 0
        for _n, p in model.named_parameters():
            a = p._array
            total += int(np.prod(a.shape, dtype=np.int64)) \
                * np.dtype(a.dtype).itemsize
        for _n, b in model.named_buffers():
            if b is None:
                continue
            a = b._array
            total += int(np.prod(a.shape, dtype=np.int64)) \
                * np.dtype(a.dtype).itemsize
        return total

    def param_nbytes(self) -> int:
        """Device bytes the model weights occupy (target + draft when
        speculative) — the fixed term of the capacity plan."""
        total = self._module_nbytes(self.model)
        if self.speculative:
            total += self._module_nbytes(self.draft_model)
        return total

    def _pool_usable(self, slots=None) -> int:
        """Usable pages (excluding trash) the paged pool holds for
        ``slots`` decode slots: the configured override, or slots x
        pages-per-slot (the ring-equivalent no-overcommit default)."""
        n = int(slots if slots is not None else self.slots)
        return self._pool_pages_cfg or n * self._pages_per_slot

    def page_nbytes(self, kv_cache_dtype=None) -> int:
        """Pool bytes ONE page costs across all layers (values + scales
        at int8) — the per-page unit of the paged capacity plan."""
        dtype = str(kv_cache_dtype if kv_cache_dtype is not None
                    else self.kv_cache_dtype)
        return _paging.page_nbytes(
            self._num_layers, self._num_heads, self._head_dim,
            self.page_size, dtype)

    def slot_nbytes(self, kv_cache_dtype=None) -> int:
        """Cache bytes ONE decode slot costs at this engine's geometry.

        Ring: ``store_len x kv_bytes_per_token`` (values + scales at
        int8) plus the slot's position word, plus the draft ring's
        analog when speculative. Paged: the slot's worst-case
        pages-in-flight (``pages_per_slot``) x ``page_nbytes`` plus its
        page-table row and position word — NOT ``store_len x
        kv_bytes_per_token``, which double-counts the speculative
        margin a paged slot never allocates. The per-slot divisor of
        :meth:`suggest_decode_slots`."""
        dtype = str(kv_cache_dtype if kv_cache_dtype is not None
                    else self.kv_cache_dtype)
        if self.paged:
            return (self._pages_per_slot * self.page_nbytes(dtype)
                    + self._pages_per_slot * 4 + 4)
        per = self.store_len * _cache.kv_bytes_per_token(
            self._num_layers, self._num_heads, self._head_dim, dtype) + 4
        if self.speculative:
            per += self.store_len * _cache.kv_bytes_per_token(
                self._draft_layers, self._draft_heads, self._draft_dim,
                dtype)
        return per

    def hbm_required_bytes(self, slots=None, kv_cache_dtype=None) -> int:
        """Predicted device bytes the engine's geometry holds resident:
        weights plus ``slots`` rings (ring layout), or weights plus the
        page pool + trash page + page tables (paged layout) — the
        static plan the capacity admission and
        :meth:`suggest_decode_slots` budget against. Matches
        :meth:`cache_nbytes` on the real arrays BYTE-EXACTLY in both
        layouts (asserted in tests/test_paged_kv.py)."""
        n = int(slots if slots is not None else self.slots)
        if self.paged:
            pnb = self.page_nbytes(kv_cache_dtype)
            pool = (self._pool_pages_cfg
                    or n * self._pages_per_slot)
            return (self.param_nbytes() + (pool + 1) * pnb
                    + n * (self._pages_per_slot * 4 + 4))
        return self.param_nbytes() + n * self.slot_nbytes(kv_cache_dtype)

    def suggest_decode_slots(self, hbm_budget_bytes=None,
                             kv_cache_dtype=None) -> int:
        """Decode slots this model fits in ``hbm_budget_bytes`` (default:
        the device HBM from the cost-model peaks table): ``(budget -
        weights) // slot_nbytes``, with the paged layout additionally
        reserving the trash page before dividing (its pool grows by
        ``pages_per_slot`` pages + one table row per slot).
        ``kv_cache_dtype`` asks the other cache mode's answer (int8
        roughly doubles the count) without rebuilding the engine — the
        serving-capacity recipe in README "Memory planning"."""
        if hbm_budget_bytes is None:
            from ..analysis.memory import hbm_budget_bytes as _budget

            hbm_budget_bytes = _budget()
        avail = int(hbm_budget_bytes) - self.param_nbytes()
        if self.paged:
            avail -= self.page_nbytes(kv_cache_dtype)  # the trash page
        if avail <= 0:
            return 0
        return int(avail // self.slot_nbytes(kv_cache_dtype))

    def check_memory_budget(self, level=None, budget_bytes=None):
        """Refuse (strict) or warn about a slots x cache-len x dtype
        geometry the static plan says cannot fit the device HBM.
        ``level`` defaults to ``FLAGS_memory_budget_check``; returns the
        required bytes when admitted."""
        from ..analysis.memory import (
            MemoryBudgetError,
            _fmt_bytes,
            hbm_budget_bytes as _budget,
        )

        lvl = str(level if level is not None
                  else flag("memory_budget_check")).strip().lower()
        if lvl in ("", "0", "off", "false", "no"):
            return None
        budget = int(budget_bytes if budget_bytes is not None
                     else _budget())
        required = self.hbm_required_bytes()
        if budget <= 0 or required <= budget:
            return required
        fits = self.suggest_decode_slots(budget)
        msg = (
            f"generation geometry cannot fit: {self.slots} slot(s) x "
            f"cache_len {self.cache_len} (store {self.store_len}) x "
            f"{self.kv_cache_dtype} KV needs "
            f"{_fmt_bytes(required)} (weights "
            f"{_fmt_bytes(self.param_nbytes())} + "
            f"{_fmt_bytes(self.slot_nbytes())}/slot) against "
            f"{_fmt_bytes(budget)} HBM; suggest_decode_slots("
            f"{budget}) = {fits}"
            + ("" if self.kv_cache_dtype == "int8" else
               f" (int8 KV would fit "
               f"{self.suggest_decode_slots(budget, 'int8')})"))
        _flight.record_event(
            "memory_budget", scope="generation", verdict="over_budget",
            required_bytes=required, budget_bytes=budget,
            slots=self.slots, cache_len=self.cache_len,
            kv_cache_dtype=self.kv_cache_dtype, suggested_slots=fits)
        if lvl == "strict":
            raise MemoryBudgetError(msg, budget_bytes=budget)
        import warnings

        warnings.warn(f"memory_budget_check={lvl}: {msg}",
                      RuntimeWarning, stacklevel=3)
        return required

    # -- compile accounting ---------------------------------------------------

    def _dispatch(self, label, jitted, args):
        """Run one compiled step through the shared compiled-callable
        runtime: new signatures are AOT-compiled and cost-captured (MFU
        in ``/statz``) under the one policy every dispatch site shares,
        and every compile is COUNTED (``generation::compile``, the
        store's miss counter)."""
        store = self._stores[label]
        leaves = jax.tree_util.tree_leaves(args)
        sig = (self._instance,) + tuple(
            (tuple(x.shape), str(x.dtype)) for x in leaves)
        entry, disposition = store.get_or_build(
            sig, lambda: (jitted, None))
        # the slot-admission / dispatch span (if one is current) learns
        # whether this call compiled — the compile-vs-execute attribution
        # a /tracez reader needs (the runtime adds cache_key + flops)
        _tracing.annotate(program_cache=disposition)
        return store.dispatch(entry, *args)

    def extra_compiles(self) -> int:
        """Compiles since warmup — steady state must keep this at 0."""
        return self.watch.extra()

    def expected_compiles(self, kind="generate") -> int:
        """Exact warmup program count for a backend ``kind``:

        - ``generate`` (unified): one prefill per ladder bucket, plus
          either the single decode program or the draft + verify pair;
        - ``prefill`` (disaggregated prefill tier): one prefill-export
          per bucket, nothing else;
        - ``decode`` (disaggregated decode tier): the decode (or
          draft + verify) program(s); a speculative decode tier also
          compiles one draft-prefill per bucket (the handed-off slab is
          target-only — the draft's view of the prompt is built at
          admission).
        """
        buckets = len(self.prefill_buckets)
        decode = 2 if self.speculative else 1
        if kind == "generate":
            return buckets + decode
        if kind == "prefill":
            return buckets
        if kind == "decode":
            return decode + (buckets if self.speculative else 0)
        raise InvalidArgumentError(
            f"unknown backend kind {kind!r}; expected generate | "
            "prefill | decode")

    def warmup(self, kind="generate"):
        """Compile exactly ``expected_compiles(kind)`` programs ahead
        of traffic, then snapshot the compile counter. Idempotent."""
        if self.warmed:
            return self
        self.expected_compiles(kind)  # validates the kind loudly
        # warmup must compile EVERY ladder bucket: with the prefix index
        # live, bucket N's pad prompt would share bucket N-1's pages and
        # prefill only a suffix — a smaller, already-compiled shape —
        # leaving the big bucket to compile on the first live prompt
        self._prefix_enabled = False
        try:
            self._warmup_drive(kind)
        finally:
            self._prefix_enabled = True
        self.reset()  # warmup traffic must not look like live context
        with self._key_lock:
            self._spec_rounds = 0
            self._spec_proposed = 0
            self._spec_accepted = 0
        self.watch.arm()
        self.warmed = True
        _flight.record_event(
            "generation_warmup", backend_kind=kind,
            prefill_buckets=list(self.prefill_buckets),
            slots=self.slots, cache_len=self.cache_len,
            kv_cache_layout=self.kv_cache_layout,
            speculative=self.speculative,
            programs=self.expected_compiles(kind))
        return self

    def _warmup_drive(self, kind):
        with RecordEvent("generation::warmup"):
            if kind in ("generate",):
                for bucket in self.prefill_buckets:
                    self.admit(0, [self.pad_id] * int(bucket))
            elif kind == "prefill":
                # a prefill tier never decodes: shrink the untouched
                # decode (and draft) rings to one slot — this tier's
                # HBM belongs to prefill activations, not a ring
                # nobody writes (its selling point in disaggregation)
                self._ring_slots = 1
                self.reset()
                for bucket in self.prefill_buckets:
                    self.prefill_export([self.pad_id] * int(bucket))
            elif kind == "decode" and self.speculative:
                for bucket in self.prefill_buckets:
                    self._admit_draft(0, [self.pad_id] * int(bucket))
            if kind != "prefill":
                if kind == "decode":
                    # pre-drive the handoff admission: the eager
                    # pad/insert ops pay their one-time op compiles NOW
                    # (per plane shape), not on the first live slab —
                    # that cold cost is exactly the TTFT tail the
                    # disaggregation bench measures
                    self.admit_prefilled(
                        0, self._fresh_slot_planes(), 1, 0,
                        prompt=[self.pad_id] if self.speculative
                        else None)
                if self.speculative:
                    self.spec_step(np.zeros(self.slots, np.int32),
                                   np.zeros(self.slots, np.float32))
                else:
                    self.step(np.zeros(self.slots, np.int32),
                              np.zeros(self.slots, np.float32))

    def _fresh_slot_planes(self):
        """Zeroed window-width per-slot planes (a synthetic empty slab
        — warmup's stand-in for a real handoff)."""
        return tuple(
            a[:, 0] for a in _cache.init_cache(
                self._num_layers, 1, self._num_heads, self.cache_len,
                self._head_dim, dtype=self.kv_cache_dtype)[:-1])

    # -- pure steps (jitted) --------------------------------------------------

    def _prefill_forward(self, model, state, layers, heads, head_dim,
                         tokens, length):
        """One bucketed prefill forward into window-width fresh caches:
        returns (logits ``[1, P, V]``, per-slot planes ``[L, H, C, D]``
        (+scales)). Shared by target prefill, draft prefill, and the
        prefill-export program."""
        p = tokens.shape[1]
        fresh = _cache.fresh_layer_caches(
            layers, 1, heads, self.cache_len, head_dim,
            dtype=self.kv_cache_dtype)
        mask = _cache.prefill_mask(p, self.cache_len, length)
        pos_ids = jnp.arange(p, dtype=jnp.int32)[None]
        (logits, new_caches), _ = functional_call(
            model, state, tokens,
            position_ids=pos_ids, attention_mask=mask, caches=fresh)
        stacked = _cache.stack_layer_caches(new_caches)
        return logits, tuple(a[:, 0] for a in stacked)

    def _sample_first(self, logits, length, temp, ctr):
        """Sample the first generated token from the last REAL prompt
        position of a prefill's logits."""
        last = jax.lax.dynamic_index_in_dim(
            logits[0], length - 1, axis=0, keepdims=False)
        key = jax.random.fold_in(self._base_key, ctr)
        return sample_logits(last[None], key, temp[None], self.top_k)[0]

    def _prefill_pure(self, state, kv, slot, tokens, length, temp, ctr):
        """Bucketed prefill of ONE prompt into decode slot ``slot``.

        ``tokens [1, P]`` (P = a ladder bucket), ``length`` = true prompt
        length. Runs the full forward over the bucket with fresh
        per-layer caches, installs the K/V (and, at int8, the scale
        planes) into the slot (zero-padded from the window width up to
        the ring store), and samples the first generated token from the
        last REAL prompt position.
        """
        logits, planes = self._prefill_forward(
            self.model, state, self._num_layers, self._num_heads,
            self._head_dim, tokens, length)
        kv = _cache.insert_slot_kv(
            kv, slot, _cache.pad_slot_arrays(planes, self.store_len),
            length)
        tok = self._sample_first(logits, length, temp, ctr)
        return kv, tok

    def _spec_prefill_pure(self, state, dstate, kv, kv_draft, slot,
                           tokens, length, temp, ctr):
        """Speculative twin of :meth:`_prefill_pure`: ONE program
        prefills the prompt through BOTH models — the draft ring must
        hold the same committed history as the target's before the
        first draft chain runs."""
        logits, planes = self._prefill_forward(
            self.model, state, self._num_layers, self._num_heads,
            self._head_dim, tokens, length)
        kv = _cache.insert_slot_kv(
            kv, slot, _cache.pad_slot_arrays(planes, self.store_len),
            length)
        _, dplanes = self._prefill_forward(
            self.draft_model, dstate, self._draft_layers,
            self._draft_heads, self._draft_dim, tokens, length)
        kv_draft = tuple(
            a.at[:, slot].set(n) for a, n in zip(
                kv_draft,
                _cache.pad_slot_arrays(dplanes, self.store_len)))
        tok = self._sample_first(logits, length, temp, ctr)
        return kv, kv_draft, tok

    def _prefill_export_pure(self, state, tokens, length, temp, ctr):
        """Prefill-tier program: the bucketed forward WITHOUT a decode
        ring — returns the window-width per-slot KV planes (the handoff
        slab) and the first sampled token. The decode tier lands the
        slab with :meth:`admit_prefilled`."""
        logits, planes = self._prefill_forward(
            self.model, state, self._num_layers, self._num_heads,
            self._head_dim, tokens, length)
        tok = self._sample_first(logits, length, temp, ctr)
        return planes, tok

    def _draft_prefill_pure(self, dstate, kv_draft, slot, tokens,
                            length):
        """Draft-only prefill into draft slot ``slot`` — a decode-tier
        engine admitting a handed-off TARGET slab still needs the
        draft's view of the prompt before it can speculate on it."""
        _, dplanes = self._prefill_forward(
            self.draft_model, dstate, self._draft_layers,
            self._draft_heads, self._draft_dim, tokens, length)
        return tuple(
            a.at[:, slot].set(n) for a, n in zip(
                kv_draft,
                _cache.pad_slot_arrays(dplanes, self.store_len)))

    def _decode_pure(self, state, kv, tokens, temps, ctr):
        """One decode step for EVERY slot: ``tokens [S]`` (each slot's
        last token) -> next token per slot. Static shapes throughout —
        this is the program whose compile count is exactly 1."""
        caches = _cache.layer_caches(*kv)
        pos = kv[-1]
        pos_ids = jnp.minimum(pos, self.max_positions - 1)[:, None]
        mask = _cache.decode_mask(pos, self.store_len,
                                  window=self.cache_len)
        (logits, new_caches), _ = functional_call(
            self.model, state, tokens[:, None],
            position_ids=pos_ids, attention_mask=mask, caches=caches)
        kv = _cache.stack_layer_caches(new_caches) + (pos + 1,)
        key = jax.random.fold_in(self._base_key, ctr)
        nxt = sample_logits(logits[:, 0], key, temps, self.top_k)
        return kv, nxt

    def _paged_prefill_pure(self, state, kv, slot, tokens, shared_len,
                            suffix_len, total_len, temp, ctr):
        """Unified full/suffix prefill of ONE prompt straight into the
        page pool. ``tokens [1, P]`` are the prompt's SUFFIX (everything
        past the ``shared_len`` tokens whose pages the prefix index
        mapped; ``shared_len == 0`` is a plain full prefill — one
        program per ladder bucket serves both). The forward runs over
        the slot's paged cache view directly: reads gather the shared
        prefix pages through the admitted table row, suffix K/V scatters
        into the slot's newly allocated pages at logical positions
        ``shared_len + t``. Pages 0..m-1 are shared and never written
        (the suffix starts at a page boundary; the bucket cannot wrap —
        admission guarantees ``shared_len + bucket <= cache_len``).
        Samples the first generated token from the last REAL suffix
        position."""
        p = tokens.shape[1]
        table, pos = kv[-2], kv[-1]
        row = table[slot][None]                    # [1, NP]
        caches = _paging.paged_layer_caches(
            kv, table=row, pos=shared_len[None])
        mask = _paging.suffix_prefill_mask(
            p, self.cache_len, shared_len, suffix_len)
        pos_ids = jnp.minimum(
            shared_len + jnp.arange(p, dtype=jnp.int32),
            self.max_positions - 1)[None]
        (logits, new_caches), _ = functional_call(
            self.model, state, tokens,
            position_ids=pos_ids, attention_mask=mask, caches=caches)
        kv = _paging.stack_paged_planes(new_caches) + (
            table, pos.at[slot].set(total_len))
        tok = self._sample_first(logits, suffix_len, temp, ctr)
        return kv, tok

    def _paged_decode_pure(self, state, kv, tokens, temps, ctr):
        """Paged twin of :meth:`_decode_pure`: the identical one-step
        decode over every slot, with reads/writes routed through the
        page tables (store == window == ``cache_len``; the paged layout
        carries no speculative margin). Host-side page management
        (:meth:`_prepare_decode_writes`) already made every busy slot's
        write-target page private, so this program never recompiles and
        never aliases a shared page."""
        caches = _paging.paged_layer_caches(kv)
        table, pos = kv[-2], kv[-1]
        pos_ids = jnp.minimum(pos, self.max_positions - 1)[:, None]
        mask = _cache.decode_mask(pos, self.cache_len)
        (logits, new_caches), _ = functional_call(
            self.model, state, tokens[:, None],
            position_ids=pos_ids, attention_mask=mask, caches=caches)
        kv = _paging.stack_paged_planes(new_caches) + (table, pos + 1)
        key = jax.random.fold_in(self._base_key, ctr)
        nxt = sample_logits(logits[:, 0], key, temps, self.top_k)
        return kv, nxt

    def _draft_chain_pure(self, dstate, kv_draft, pos, tokens):
        """The draft program: ``k`` greedy proposals per slot from one
        dispatch. ``k + 1`` chained single-token draft decode steps —
        step ``j`` writes its input token's K/V at ``pos + j`` (so the
        draft ring ends the round holding the FULL proposed chain,
        including the last proposal: on full acceptance the draft's
        committed history still mirrors the target's) and feeds its
        argmax forward. Returns (draft arrays, proposals ``[S, k]``)."""
        caches = _cache.layer_caches(*(kv_draft + (pos,)))
        cur = tokens
        proposals = []
        for j in range(self.draft_k + 1):
            pj = pos + j
            pos_ids = jnp.minimum(pj, self._draft_max_positions - 1)[:, None]
            mask = _cache.decode_mask(pj, self.store_len,
                                      window=self.cache_len)
            (logits, caches), _ = functional_call(
                self.draft_model, dstate, cur[:, None],
                position_ids=pos_ids, attention_mask=mask, caches=caches)
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            if j < self.draft_k:
                proposals.append(nxt)
            cur = nxt
        return (_cache.stack_layer_caches(caches),
                jnp.stack(proposals, axis=1))

    def _verify_pure(self, state, kv, tokens, proposals, temps, ctr):
        """The verify program: ONE batched target forward over all
        ``k + 1`` in-flight positions of every slot.

        Inputs ``[S, k+1] = [last committed token | k proposals]`` write
        their K/V into the ring span ``pos .. pos+k`` (in place —
        window-exact by the store margin) and produce logits at every
        position; the target's own sampled chain ``ts`` decides
        acceptance: the longest proposal prefix with ``proposal[i] ==
        ts[i]`` is accepted and ``ts[m]`` (the sample one past it) is
        the correction/bonus token, so the round emits ``ts[:, :m+1]``
        — exactly the token sequence the plain engine would have
        produced one dispatch at a time (greedy: ``ts`` IS the argmax
        chain). ``pos`` advances by the emitted count; rejected-position
        ring writes are left as masked garbage for the next round's
        span to overwrite."""
        span = self.draft_k + 1
        seq = jnp.concatenate([tokens[:, None], proposals], axis=1)
        caches = _cache.layer_caches(*kv)
        pos = kv[-1]
        pos_ids = jnp.minimum(
            pos[:, None] + jnp.arange(span, dtype=jnp.int32)[None, :],
            self.max_positions - 1)
        mask = _cache.verify_mask(pos, self.store_len, span,
                                  window=self.cache_len)
        (logits, new_caches), _ = functional_call(
            self.model, state, seq,
            position_ids=pos_ids, attention_mask=mask, caches=caches)
        key = jax.random.fold_in(self._base_key, ctr)
        ts = jnp.stack(
            [sample_logits(logits[:, i], jax.random.fold_in(key, i),
                           temps, self.top_k) for i in range(span)],
            axis=1)
        match = (proposals == ts[:, :self.draft_k]).astype(jnp.int32)
        # cumprod/sum promote int32 -> int64 under x64 mode; the pos
        # vector's dtype is part of every program's signature, so pin
        # it or the second round re-compiles everything downstream
        accepted = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        counts = (accepted + 1).astype(jnp.int32)
        kv = _cache.stack_layer_caches(new_caches) + (
            (pos + counts).astype(jnp.int32),)
        return kv, ts, counts

    # -- scheduler primitives -------------------------------------------------

    def bucket_for(self, prompt_len) -> int:
        """Smallest prefill bucket covering ``prompt_len``."""
        for b in self.prefill_buckets:
            if b >= prompt_len:
                return int(b)
        raise InvalidArgumentError(
            f"prompt of {prompt_len} tokens exceeds the largest prefill "
            f"bucket {self.prefill_buckets[-1]}; raise "
            "FLAGS_generation_prefill_buckets or truncate")

    def validate(self, prompt, max_new_tokens) -> int:
        """Admission checks shared by offline generate and the serving
        scheduler. Returns the prompt length."""
        n = len(prompt)
        if n < 1:
            raise InvalidArgumentError("generation needs a non-empty prompt")
        self.bucket_for(n)  # raises if no bucket covers it
        if max_new_tokens < 1:
            raise InvalidArgumentError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        total = n + int(max_new_tokens)
        if total > self.max_positions:
            raise InvalidArgumentError(
                f"prompt ({n}) + max_new_tokens ({max_new_tokens}) = "
                f"{total} exceeds the model's max_position_embeddings "
                f"{self.max_positions}")
        return n

    def _padded_prompt(self, prompt):
        n = len(prompt)
        bucket = self.bucket_for(n)
        padded = np.full(bucket, self.pad_id, np.int32)
        padded[:n] = np.asarray(prompt, np.int32)
        return padded, n

    def _next_key_step(self) -> int:
        """Bump the sampling-key counter under its lock and return the
        snapshot. Every dispatch site uses the RETURNED value — re-reading
        ``self._key_step`` after an unlocked ``+=`` is the race graphlint's
        ``unlocked-shared-mutation`` rule exists for (two threads sampling
        with the same key)."""
        with self._key_lock:
            self._key_step += 1
            return self._key_step

    def admit(self, slot, prompt, temperature=None, tenant=None) -> int:
        """Prefill ``prompt`` into ``slot`` and return the first sampled
        token. The slot's previous occupant is simply overwritten — a
        vacated slot needs no reset pass (ring), or its pages are
        reclaimed first (paged). Speculative engines prefill the draft
        ring in the same program. ``tenant`` labels the paged layout's
        prefix-reuse observability; the ring layout ignores it."""
        if self.paged:
            return self._admit_paged(slot, prompt, temperature, tenant)
        padded, n = self._padded_prompt(prompt)
        temp = (self.default_temperature if temperature is None
                else float(temperature))
        ctr = self._next_key_step()
        with RecordEvent("generation::prefill"):
            if self.speculative:
                out = self._dispatch("prefill", self._spec_prefill_jit, (
                    self._state(), self._draft_state(), self._kv,
                    self._kv_draft, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(padded[None]), jnp.asarray(n, jnp.int32),
                    jnp.asarray(temp, jnp.float32),
                    jnp.asarray(ctr, jnp.int32)))
                self._kv, self._kv_draft, tok = out
            else:
                out = self._dispatch("prefill", self._prefill_jit, (
                    self._state(), self._kv,
                    jnp.asarray(slot, jnp.int32),
                    jnp.asarray(padded[None]),
                    jnp.asarray(n, jnp.int32),
                    jnp.asarray(temp, jnp.float32),
                    jnp.asarray(ctr, jnp.int32)))
                self._kv, tok = out
        return int(tok)

    # -- paged layout: host-side page management ------------------------------
    #
    # All of this runs BETWEEN compiled steps on the engine's single
    # dispatch thread: page allocation, refcounts, CoW, and the prefix
    # index are plain host bookkeeping; the device pytree keeps its
    # fixed shapes, so no path here can add a compile.

    def _sync_table(self):
        """Push the host page-table mirror into the device pytree."""
        self._kv = self._kv[:-2] + (
            jnp.asarray(self._table_host), self._kv[-1])

    def _copy_page(self, src, dst):
        """Device-copy one pool page (all layers, values + scales) —
        the copy half of copy-on-write."""
        self._kv = tuple(
            a.at[:, dst].set(a[:, src]) for a in self._kv[:-2]
        ) + self._kv[-2:]

    def _alloc_pages(self, need):
        """``need`` private pages off the free list, evicting LRU
        index-only prefix pages when the list runs dry. Raises
        :class:`paging.PagePoolExhaustedError` (slots keep their pages;
        nothing was handed out) when the pool genuinely cannot supply."""
        need = int(need)
        if need > self._pool.free_pages():
            self._index.evict(need - self._pool.free_pages())
        if need > self._pool.free_pages():
            raise _paging.PagePoolExhaustedError(
                f"page pool exhausted: need {need} pages, "
                f"{self._pool.free_pages()} free and nothing evictable "
                f"(pool {self._pool.pages} pages x {self.page_size} "
                "tokens; raise FLAGS_generation_kv_pool_pages or lower "
                "concurrency)")
        return [self._pool.alloc() for _ in range(need)]

    def release_slot(self, slot):
        """Reclaim a vacated slot's pages: drop the slot's reference on
        every mapped page (pages the prefix index also holds survive as
        shared prefix cache; private ones return to the free list) and
        point the table row back at the trash page. No-op on the ring
        layout — ring slots are simply overwritten."""
        if not self.paged:
            return
        slot = int(slot)
        row = self._table_host[slot]
        if not self._slot_live[slot] and not row.any():
            return
        for pid in row:
            if int(pid) != _paging.TRASH_PAGE:
                self._pool.release(int(pid))
        self._table_host[slot] = _paging.TRASH_PAGE
        self._slot_live[slot] = False
        self._pos_host[slot] = 0
        self._sync_table()
        self._pool_gauges()

    def _cap_matched(self, n, m):
        """Cap a prefix match so the suffix's ladder bucket fits the
        window without wrapping into the shared pages (the suffix
        prefill writes ``bucket`` entries starting at ``m * ps``)."""
        while m:
            bucket = self.bucket_for(n - m * self.page_size)
            if m * self.page_size + bucket <= self.cache_len:
                break
            m -= 1
        return m

    def has_capacity(self, prompt_or_length) -> bool:
        """Would :meth:`admit` find pages for this prompt right now?
        Counts free + evictable pages against the pages the prompt
        needs beyond its indexed prefix — the admission gate
        ``serving/continuous.py`` consults INSTEAD of assuming a vacant
        slot implies capacity (pool free pages, not fixed slots)."""
        if not self.paged:
            return True
        ps = self.page_size
        if isinstance(prompt_or_length, int):
            n, m = int(prompt_or_length), 0
        else:
            prompt = list(prompt_or_length)
            n = len(prompt)
            m = self._cap_matched(n, len(self._index.known(
                _paging.chain_hashes(prompt, ps)[:(n - 1) // ps]))) \
                if self._prefix_enabled else 0
        need = -(-n // ps) - m
        return (self._pool.free_pages() + self._index.evictable()
                >= need)

    def _admit_paged(self, slot, prompt, temperature, tenant):
        """Paged admission: map the longest indexed prefix (full pages
        only, capped so the suffix keeps >= 1 real token and its bucket
        cannot wrap), allocate private pages for the rest, register the
        prompt's full pages in the index, and dispatch the unified
        full/suffix prefill program for the suffix's ladder bucket."""
        slot = int(slot)
        n = self.validate(prompt, 1)
        ps = self.page_size
        self.release_slot(slot)
        hashes = _paging.chain_hashes(prompt, ps)
        matched = []
        if self._prefix_enabled:
            # cap at floor((n-1)/ps): the suffix keeps >= 1 token, so
            # there is always a real logit position to sample from
            matched = self._index.match(hashes[:(n - 1) // ps])
            matched = matched[:self._cap_matched(n, len(matched))]
        m = len(matched)
        shared_len = m * ps
        suffix = list(prompt)[shared_len:]
        total_pages = -(-n // ps)
        # retain BEFORE allocating: _alloc_pages may evict ref==1 index
        # pages, and the matched pages are exactly that until retained
        for pid in matched:
            self._pool.retain(pid)
        try:
            new_pages = self._alloc_pages(total_pages - m)
        except _paging.PagePoolExhaustedError:
            for pid in matched:
                self._pool.release(pid)
            raise
        row = np.full(self._pages_per_slot, _paging.TRASH_PAGE, np.int32)
        row[:m] = matched
        row[m:total_pages] = new_pages
        self._table_host[slot] = row
        self._pos_host[slot] = n
        self._slot_live[slot] = True
        t = "default" if tenant is None else str(tenant)
        self._slot_tenant[slot] = t
        if self._prefix_enabled:
            self._index.insert(hashes[:n // ps],
                               [int(p) for p in row[:n // ps]])
        self._sync_table()
        self._note_prefix(t, n, shared_len, m)
        padded = np.full(self.bucket_for(len(suffix)), self.pad_id,
                         np.int32)
        padded[:len(suffix)] = np.asarray(suffix, np.int32)
        temp = (self.default_temperature if temperature is None
                else float(temperature))
        ctr = self._next_key_step()
        with RecordEvent("generation::prefill"):
            out = self._dispatch("prefill", self._paged_prefill_jit, (
                self._state(), self._kv, jnp.asarray(slot, jnp.int32),
                jnp.asarray(padded[None]),
                jnp.asarray(shared_len, jnp.int32),
                jnp.asarray(len(suffix), jnp.int32),
                jnp.asarray(n, jnp.int32),
                jnp.asarray(temp, jnp.float32),
                jnp.asarray(ctr, jnp.int32)))
        self._kv, tok = out
        return int(tok)

    def _note_prefix(self, tenant, prompt_tokens, shared_tokens,
                     matched_pages):
        """Per-tenant prefix-reuse accounting + the labeled gauges and
        the ``prefix_reuse`` flight event (PR 17 labeled families)."""
        from ..monitor import registry as _mon

        st = self._prefix_tenants.setdefault(
            tenant, {"lookups": 0, "hits": 0, "prompt_tokens": 0,
                     "shared_tokens": 0})
        st["lookups"] += 1
        st["prompt_tokens"] += int(prompt_tokens)
        if matched_pages:
            st["hits"] += 1
            st["shared_tokens"] += int(shared_tokens)
            _flight.record_event(
                "prefix_reuse", tenant=tenant,
                matched_tokens=int(shared_tokens),
                matched_pages=int(matched_pages),
                prompt_tokens=int(prompt_tokens))
        _mon.gauge("generation/prefix_hit_rate").labels(
            tenant=tenant).set(
            round(st["shared_tokens"] / st["prompt_tokens"], 4))
        tot_p = sum(s["prompt_tokens"]
                    for s in self._prefix_tenants.values())
        tot_s = sum(s["shared_tokens"]
                    for s in self._prefix_tenants.values())
        _mon.gauge("generation/prefix_hit_rate").set(
            round(tot_s / tot_p, 4) if tot_p else 0.0)
        self._pool_gauges()

    def _pool_gauges(self):
        """Pool occupancy gauges: global free/shared, plus per-tenant
        shared-page children (pages a tenant's live slots map at
        refcount > 1 — its CoW exposure)."""
        from ..monitor import registry as _mon

        _mon.gauge("generation/pages_free").set(self._pool.free_pages())
        _mon.gauge("generation/pages_shared").set(
            self._pool.shared_pages())
        per = {}
        for s, live in enumerate(self._slot_live):
            if not live:
                continue
            t = self._slot_tenant[s]
            per[t] = per.get(t, 0) + sum(
                1 for pid in self._table_host[s]
                if int(pid) != _paging.TRASH_PAGE
                and self._pool.ref[int(pid)] > 1)
        for t in self._prefix_tenants:
            _mon.gauge("generation/pages_shared").labels(
                tenant=t).set(per.get(t, 0))
        for t, n in per.items():
            if t not in self._prefix_tenants:
                _mon.gauge("generation/pages_shared").labels(
                    tenant=t).set(n)

    def _prepare_decode_writes(self):
        """Make every busy slot's next ring write safe BEFORE the
        compiled step runs: the write lands at logical page ``(pos %
        window) // ps`` — if that table entry is still the trash page
        (first visit), allocate; if the mapped page is shared (prefix
        pages after the ring wraps back into them, or pages the index
        retains), COPY it private first (copy-on-write) so the write
        cannot corrupt another slot's — or the index's — view."""
        changed = False
        for s, live in enumerate(self._slot_live):
            if not live:
                continue
            idx = int(self._pos_host[s]) % self.cache_len
            lp = idx // self.page_size
            pid = int(self._table_host[s, lp])
            if pid == _paging.TRASH_PAGE:
                (new,) = self._alloc_pages(1)
                self._table_host[s, lp] = new
                changed = True
            elif self._pool.ref[pid] > 1:
                try:
                    (new,) = self._alloc_pages(1)
                except _paging.PagePoolExhaustedError:
                    # pressure valve: stop caching this chain — forget
                    # the page's subtree so the index's pin drops. If
                    # the page is now private to this slot, write in
                    # place; if another live slot still shares it, the
                    # forget freed enough refs that a copy page exists.
                    self._index.forget_page(pid)
                    if self._pool.ref[pid] == 1:
                        continue
                    (new,) = self._alloc_pages(1)
                self._copy_page(pid, new)
                self._pool.release(pid)
                self._table_host[s, lp] = new
                self._pool.cow_copies += 1
                changed = True
        if changed:
            self._sync_table()
            self._pool_gauges()

    def paging_stats(self) -> dict:
        """The /statz paging block: layout + pool occupancy + prefix-
        index accounting (global and per tenant)."""
        if not self.paged:
            return {"layout": self.kv_cache_layout}
        per = {}
        for t, st in self._prefix_tenants.items():
            per[t] = dict(st, hit_rate=round(
                st["shared_tokens"] / st["prompt_tokens"], 4)
                if st["prompt_tokens"] else None)
        return {
            "layout": self.kv_cache_layout,
            "page_size": self.page_size,
            "pages_per_slot": self._pages_per_slot,
            "pages_total": self._pool.pages,
            "pages_free": self._pool.free_pages(),
            "pages_used": self._pool.used_pages(),
            "pages_shared": self._pool.shared_pages(),
            "peak_pages_used": self._pool.peak_used,
            "cow_copies": self._pool.cow_copies,
            "page_nbytes": self.page_nbytes(),
            "prefix_index": self._index.stats(),
            "per_tenant": per,
        }

    def known_page_hashes(self, hashes):
        """The prefix of ``hashes`` this engine's index already holds —
        a prefill tier (or router) asks before shipping a page-granular
        slab so the wire carries only pages this tier is missing."""
        if not self.paged:
            return set()
        return self._index.known(list(hashes))

    def prefill_export_pages(self, prompt, temperature=None,
                             known_hashes=()):
        """Page-granular :meth:`prefill_export`: runs the same bucketed
        forward, then splits the slab into pages with chain hashes.
        Returns ``(pages, length, first_token)`` where ``pages`` is a
        list of ``{"id", "hash", "planes"}`` dicts — full pages carry
        their chain hash (``hash=None`` for the partial tail), and a
        page whose hash is in ``known_hashes`` ships header-only
        (``planes=None``): the decode tier maps it from its own prefix
        index instead of the wire."""
        planes, n, tok = self.prefill_export(prompt, temperature)
        ps = self.page_size
        per_page = _paging.split_planes(planes, ps)
        hashes = _paging.chain_hashes(prompt, ps)
        known = set(known_hashes)
        pages = []
        for i in range(-(-n // ps)):
            h = hashes[i] if i < len(hashes) else None
            pages.append({
                "id": i, "hash": h,
                "planes": None if (h is not None and h in known)
                else per_page[i]})
        return pages, n, int(tok)

    def admit_prefilled_pages(self, slot, pages, length, first_token,
                              page_size=None, tenant=None) -> int:
        """Land a page-granular handoff in decode slot ``slot``: pages
        shipped on the wire are installed into freshly allocated pool
        pages; header-only pages (``planes is None``) must resolve
        through this engine's own prefix index (the sender asked
        :meth:`known_page_hashes` first) and are mapped copy-on-write —
        refcounted exactly like a local prefix hit. Full shipped pages
        with hashes register in the index, so this decode tier becomes
        a prefix-cache peer for the whole fleet."""
        from .handoff import HandoffError

        if not self.paged:
            raise InvalidArgumentError(
                "page-granular handoff needs kv_cache_layout=paged on "
                "the decode tier (ring tiers speak the slab format)")
        slot = int(slot)
        length = int(length)
        ps = self.page_size
        if page_size is not None and int(page_size) != ps:
            raise HandoffError(
                f"page-granular slab page_size {page_size} does not "
                f"match this engine's {ps}")
        if not 1 <= length <= self.cache_len:
            raise InvalidArgumentError(
                f"handoff length {length} outside [1, {self.cache_len}]")
        npages = -(-length // ps)
        if len(pages) != npages:
            raise HandoffError(
                f"page-granular slab carries {len(pages)} pages; "
                f"length {length} at page size {ps} needs {npages}")
        arity = len(self._kv) - 2
        # resolve absent pages through the index FIRST — nothing is
        # allocated or mutated until the whole slab is provably landable
        hashes = [p.get("hash") for p in pages]
        full = length // ps
        chain = []  # the contiguous hashed prefix — chain hashes only
        for h in hashes[:full]:  # resolve through a prefix walk
            if h is None:
                break
            chain.append(h)
        plan = []
        for i, page in enumerate(pages):
            planes = page.get("planes")
            if planes is None:
                plan.append(("map", i))
            else:
                if len(planes) != arity:
                    raise HandoffError(
                        f"page {i} carries {len(planes)} planes, this "
                        f"engine's {self.kv_cache_dtype} cache needs "
                        f"{arity}")
                for p in planes:
                    if int(p.shape[2]) != ps:
                        raise HandoffError(
                            f"page {i} plane cache axis "
                            f"{tuple(p.shape)} does not match page "
                            f"size {ps}")
                plan.append(("ship", i))
        mapped = self._index.match(chain)
        for kind, i in plan:
            if kind == "map" and i >= len(mapped):
                raise HandoffError(
                    f"page {i} shipped header-only but this tier does "
                    "not hold its hash chain; the sender must ship the "
                    "payload")
        self.release_slot(slot)
        # retain mapped pages BEFORE allocating (allocation may evict
        # ref==1 index pages), then allocate the shipped set atomically
        map_ids = [mapped[i] for k, i in plan if k == "map"]
        for pid in map_ids:
            self._pool.retain(pid)
        try:
            fresh = self._alloc_pages(
                sum(1 for k, _ in plan if k == "ship"))
        except _paging.PagePoolExhaustedError:
            for pid in map_ids:
                self._pool.release(pid)
            raise
        row = np.full(self._pages_per_slot, _paging.TRASH_PAGE, np.int32)
        ship_ids, ship_planes = [], []
        it = iter(fresh)
        for kind, i in plan:
            if kind == "map":
                row[i] = mapped[i]
            else:
                pid = next(it)
                row[i] = pid
                ship_ids.append(pid)
                ship_planes.append(pages[i]["planes"])
        if ship_ids:
            ids = jnp.asarray(np.asarray(ship_ids, np.int32))
            for j in range(arity):
                stack = jnp.asarray(np.stack(
                    [np.asarray(pl[j]) for pl in ship_planes], axis=1))
                self._kv = self._kv[:j] + (
                    self._kv[j].at[:, ids].set(stack),
                ) + self._kv[j + 1:]
        self._table_host[slot] = row
        self._pos_host[slot] = length
        self._slot_live[slot] = True
        t = "default" if tenant is None else str(tenant)
        self._slot_tenant[slot] = t
        if self._prefix_enabled and full and all(
                h is not None for h in hashes[:full]):
            self._index.insert(hashes[:full],
                               [int(p) for p in row[:full]])
        self._sync_table()
        self._kv = self._kv[:-1] + (
            self._kv[-1].at[slot].set(length),)
        shared = sum(1 for kind, i in plan
                     if kind == "map" and i < len(mapped))
        self._note_prefix(t, length, shared * ps, shared)
        return int(first_token)

    def prefill_export(self, prompt, temperature=None):
        """Prefill-tier primitive: run the bucketed forward and return
        ``(planes, length, first_token)`` — the window-width per-slot
        KV planes (``[L, H, C, D]`` values, ``[L, H, C]`` scales at
        int8), the true prompt length, and the first sampled token.
        The slab ships to a decode tier (:mod:`generation.handoff`)
        whose :meth:`admit_prefilled` lands it in a free slot."""
        padded, n = self._padded_prompt(prompt)
        temp = (self.default_temperature if temperature is None
                else float(temperature))
        ctr = self._next_key_step()
        with RecordEvent("generation::prefill_export"):
            planes, tok = self._dispatch(
                "prefill", self._prefill_export_jit, (
                    self._state(), jnp.asarray(padded[None]),
                    jnp.asarray(n, jnp.int32),
                    jnp.asarray(temp, jnp.float32),
                    jnp.asarray(ctr, jnp.int32)))
        return planes, n, int(tok)

    def _admit_draft(self, slot, prompt):
        """Draft-only prefill of ``prompt`` into draft slot ``slot`` —
        the decode-tier half of a speculative handoff admission."""
        padded, n = self._padded_prompt(prompt)
        with RecordEvent("generation::draft_prefill"):
            self._kv_draft = self._dispatch(
                "prefill", self._draft_prefill_jit, (
                    self._draft_state(), self._kv_draft,
                    jnp.asarray(slot, jnp.int32),
                    jnp.asarray(padded[None]),
                    jnp.asarray(n, jnp.int32)))

    def admit_prefilled(self, slot, planes, length, first_token,
                        prompt=None) -> int:
        """Land a handed-off KV slab in decode slot ``slot``: pad the
        window-width planes up to the ring store and commit them with
        the same functional indexed update admission always uses. The
        first token was already sampled by the prefill tier; it is
        returned unchanged for scheduler uniformity. A speculative
        engine additionally needs the PROMPT tokens (the slab is
        target-only) to build the draft's view via a draft prefill."""
        length = int(length)
        if not 1 <= length <= self.cache_len:
            raise InvalidArgumentError(
                f"handoff length {length} outside [1, {self.cache_len}]")
        if self.paged:
            # a v1 (contiguous) slab lands on a paged tier by splitting
            # into anonymous pages — no hashes, so no cross-request
            # sharing, but the decode path is uniform
            arity = len(self._kv) - 2
            if len(planes) != arity:
                raise InvalidArgumentError(
                    f"handoff slab has {len(planes)} planes, this "
                    f"engine's {self.kv_cache_dtype} cache needs "
                    f"{arity} (kv_cache_dtype mismatch between tiers?)")
            per_page = _paging.split_planes(
                tuple(jnp.asarray(p) for p in planes), self.page_size)
            npages = -(-length // self.page_size)
            pages = [{"id": i, "hash": None, "planes": per_page[i]}
                     for i in range(npages)]
            return self.admit_prefilled_pages(
                slot, pages, length, first_token)
        arity = len(self._kv) - 1
        if len(planes) != arity:
            raise InvalidArgumentError(
                f"handoff slab has {len(planes)} planes, this engine's "
                f"{self.kv_cache_dtype} cache needs {arity} "
                "(kv_cache_dtype mismatch between tiers?)")
        padded = _cache.pad_slot_arrays(
            tuple(jnp.asarray(p) for p in planes), self.store_len)
        for a, p in zip(self._kv[:-1], padded):
            if tuple(p.shape) != tuple(a.shape[:1] + a.shape[2:]) \
                    or p.dtype != a.dtype:
                raise InvalidArgumentError(
                    f"handoff slab plane {tuple(p.shape)}/{p.dtype} does "
                    f"not fit this engine's cache "
                    f"{tuple(a.shape)}/{a.dtype}")
        if self.speculative:
            if prompt is None:
                raise InvalidArgumentError(
                    "a speculative decode tier needs the prompt tokens "
                    "with the KV slab (the draft ring must be prefilled)")
            self._admit_draft(slot, prompt)
        with RecordEvent("generation::admit_prefilled"):
            self._kv = _cache.insert_slot_kv(
                self._kv, slot, padded, length)
        return int(first_token)

    def step(self, tokens, temps) -> np.ndarray:
        """Decode one token for every slot. ``tokens``/``temps`` are
        host ``[S]`` arrays (vacant slots: anything — their output is
        ignored and their cache entries are overwritten on admission)."""
        ctr = self._next_key_step()
        if self.paged:
            # CoW/first-visit page turns happen on the host BEFORE the
            # compiled step, so the jitted scatter only ever writes
            # pages private to their slot (or the trash page)
            self._prepare_decode_writes()
            with RecordEvent("generation::decode"):
                out = self._dispatch("decode", self._paged_decode_jit, (
                    self._state(), self._kv,
                    jnp.asarray(np.asarray(tokens, np.int32)),
                    jnp.asarray(np.asarray(temps, np.float32)),
                    jnp.asarray(ctr, jnp.int32)))
            self._kv, nxt = out
            for s, live in enumerate(self._slot_live):
                if live:
                    self._pos_host[s] += 1
            return np.asarray(nxt)
        with RecordEvent("generation::decode"):
            out = self._dispatch("decode", self._decode_jit, (
                self._state(), self._kv,
                jnp.asarray(np.asarray(tokens, np.int32)),
                jnp.asarray(np.asarray(temps, np.float32)),
                jnp.asarray(ctr, jnp.int32)))
        self._kv, nxt = out
        return np.asarray(nxt)

    def spec_step(self, tokens, temps, busy=None):
        """One speculative round for every slot: draft program (k
        proposals per slot) then verify program (one batched target
        forward over all k+1 positions). Returns ``(emitted [S, k+1],
        counts [S])`` — slot ``s`` produced ``emitted[s, :counts[s]]``
        new tokens this round (the caller truncates at EOS/budget).
        ``busy`` (slot indices, or None for all) scopes the acceptance
        accounting to slots actually generating."""
        if not self.speculative:
            raise InvalidArgumentError(
                "spec_step needs a draft model; construct the engine "
                "with draft_model= (FLAGS_speculative_enabled)")
        toks = jnp.asarray(np.asarray(tokens, np.int32))
        pos = self._kv[-1]
        with RecordEvent("generation::draft"):
            self._kv_draft, proposals = self._dispatch(
                "draft", self._draft_jit, (
                    self._draft_state(), self._kv_draft, pos, toks))
        ctr = self._next_key_step()
        with RecordEvent("generation::verify"):
            out = self._dispatch("verify", self._verify_jit, (
                self._state(), self._kv, toks, proposals,
                jnp.asarray(np.asarray(temps, np.float32)),
                jnp.asarray(ctr, jnp.int32)))
        self._kv, ts, counts = out
        counts = np.asarray(counts)
        n_busy = self.slots if busy is None else len(busy)
        if n_busy:
            accepted = int(counts.sum() - self.slots if busy is None
                           else sum(int(counts[s]) - 1 for s in busy))
            with self._key_lock:
                self._spec_rounds += 1
                self._spec_proposed += self.draft_k * n_busy
                self._spec_accepted += accepted
            from ..monitor import counter as _mcounter

            _mcounter("generation/spec_rounds_total").inc()
            _mcounter("generation/spec_proposed_total").inc(
                self.draft_k * n_busy)
            _mcounter("generation/spec_accepted_total").inc(accepted)
        return np.asarray(ts), counts

    def spec_stats(self) -> dict:
        """Speculative acceptance accounting since the last reset/
        warmup: rounds, proposed/accepted draft tokens, acceptance
        rate (the /statz block)."""
        with self._key_lock:  # consistent snapshot vs a concurrent round
            rounds, proposed, accepted = (
                self._spec_rounds, self._spec_proposed, self._spec_accepted)
        return {
            "enabled": self.speculative,
            "draft_k": self.draft_k if self.speculative else 0,
            "rounds": rounds,
            "proposed": proposed,
            "accepted": accepted,
            "acceptance_rate": round(accepted / proposed, 4)
            if proposed else None,
        }

    # -- offline API ----------------------------------------------------------

    def generate(self, prompts, max_new_tokens=None, temperature=None,
                 stop_at_eos=True, continuous=True):
        """Generate for a list of prompts, continuous-batched across the
        engine's slots: a finished sequence vacates its slot and the next
        prompt is admitted at the next step. ``continuous=False`` is the
        static baseline (a new group is admitted only when EVERY slot has
        drained — what tearing the batch down costs; bench.py's
        ``decode_throughput`` row measures the difference). Returns one
        token list per prompt (EOS included when hit)."""
        max_new = (self.default_max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        for prompt in prompts:
            self.validate(prompt, max_new)
        pending = deque(enumerate(prompts))
        results = [None] * len(prompts)
        active = {}  # slot -> (prompt_idx, tokens list)
        last = np.zeros(self.slots, np.int32)
        temps = np.zeros(self.slots, np.float32)
        temp = (self.default_temperature if temperature is None
                else float(temperature))

        def finished(tokens):
            return (len(tokens) >= max_new
                    or (stop_at_eos and self.eos_id is not None
                        and tokens[-1] == self.eos_id))

        while pending or active:
            admit_ok = bool(pending) and (continuous or not active)
            while admit_ok and pending and len(active) < self.slots:
                slot = next(s for s in range(self.slots) if s not in active)
                idx, prompt = pending.popleft()
                tok = self.admit(slot, prompt, temp)
                temps[slot] = temp
                if finished([tok]):
                    results[idx] = [tok]
                    self.release_slot(slot)
                else:
                    active[slot] = (idx, [tok])
                    last[slot] = tok
            if not active:
                continue
            if self.speculative:
                ts, counts = self.spec_step(last, temps,
                                            busy=list(active))
                for slot in list(active):
                    idx, tokens = active[slot]
                    for i in range(int(counts[slot])):
                        tokens.append(int(ts[slot, i]))
                        last[slot] = ts[slot, i]
                        if finished(tokens):
                            break
                    if finished(tokens):
                        results[idx] = tokens
                        del active[slot]
                        self.release_slot(slot)
            else:
                nxt = self.step(last, temps)
                for slot in list(active):
                    idx, tokens = active[slot]
                    tokens.append(int(nxt[slot]))
                    last[slot] = nxt[slot]
                    if finished(tokens):
                        results[idx] = tokens
                        del active[slot]
                        self.release_slot(slot)
        return results
