"""Compile-once generation engine: bucketed prefill + O(1)-cache decode.

The serving batcher bounds the BATCH axis with a powers-of-two bucket
ladder; autoregressive decoding re-opens the same compile-explosion on
the SEQUENCE axis (every prompt length and every growing context is a
new XLA program if shapes are dynamic). The engine closes it with a
prefill/decode split:

- **Prefill** pads the prompt up to a sequence-length bucket ladder
  (``FLAGS_generation_prefill_buckets``) and runs ONE full forward over
  the bucket, writing K/V into the admitted slot of the static ring
  cache — one compile per ladder bucket, ever.
- **Decode** is a single jitted step over ALL decode slots: read last
  tokens ``[S]``, attend the static cache window, sample, write back —
  its shapes never depend on sequence length or slot turnover, so its
  steady-state compile count is exactly 1 (asserted in tests and the
  gen-smoke the same way ``serving/unexpected_compiles`` is).

Compile accounting mirrors the serving pool: every new signature is AOT
lowered/compiled through the cost model (so decode MFU lands in the
``/statz`` ledger) and bumps the ``generation::compile`` profiler
counter — warmup snapshots it, and ``extra_compiles()`` must stay 0
under any traffic mix.

**Speculative decoding** (pass ``draft_model``): decode is memory-bound
and serial — every token pays one full-model dispatch. A small draft
GPT proposes ``k`` greedy tokens per slot (one compiled "draft" program
running the whole chain), and the target model scores all ``k + 1``
positions in ONE batched forward (the "verify" program): the longest
proposal prefix matching the target's own sampled chain is accepted,
and the target sample one past it is emitted as the correction/bonus
token — so every round emits ``1..k+1`` tokens for two dispatches
instead of ``1`` per dispatch. Greedy output is token-identical to the
plain engine by construction (acceptance compares against the target
argmax chain itself); sampled output draws every emitted token from the
target's own distribution. Both programs compile once through the
CompiledStore and the ring cache commit is the same functional index
update discipline — the physical ring simply carries ``draft_k`` extra
scratch entries (see generation/cache.py "store vs window") so the
verify step's in-place span write can never clobber a live window
entry. Rejected-position writes are garbage but provably masked until
the next round overwrites them.

**Disaggregated prefill/decode** (``kind`` warmup + KV handoff): a
prefill-tier engine runs only :meth:`prefill_export` (bucket-ladder
forward into window-width fresh caches, returning the slot's KV slab +
first sampled token), a decode-tier engine admits that slab with
:meth:`admit_prefilled` (pad to the ring store + ``insert_slot_kv``)
and runs only the decode/speculative programs — prefill scales on
compute, decode on HBM, and each tier's warmup compiles exactly its
own program set (``expected_compiles(kind)``).

The engine is single-threaded by design (one decode stream per model
replica); :mod:`paddle_tpu.serving.continuous` drives it from a slot
scheduler for continuous batching, and :meth:`generate` runs the same
slot loop inline for offline use (bench, tests, parity goldens).
"""
from __future__ import annotations

import itertools
import threading
from collections import OrderedDict, deque

import numpy as np

import jax
import jax.numpy as jnp

from ..errors import InvalidArgumentError
from ..flags import flag
from ..framework.jit import functional_call
from ..monitor import flight_recorder as _flight
from ..monitor import tracing as _tracing
from ..profiler import RecordEvent, counters as _counters
from . import cache as _cache
from .sampling import sample_logits

__all__ = ["GenerationEngine", "COMPILE_COUNTER"]

COMPILE_COUNTER = "generation::compile"

# deterministic engine instance ids (cache-key stability; see __init__)
_engine_counter = itertools.count()


class GenerationEngine:
    """Slot-structured generation over a causal LM.

    ``model`` must expose ``forward(input_ids, position_ids,
    attention_mask, caches) -> (logits, caches)`` with per-layer
    :class:`nn.StaticCache` support plus ``cache_spec()`` (GPTForCausalLM
    is the reference implementation). The engine owns the stacked ring
    cache for ``slots`` concurrent sequences and exposes the two
    scheduler primitives: :meth:`admit` (prefill a prompt into a vacant
    slot, returns the first sampled token) and :meth:`step` (decode one
    token for every slot).
    """

    def __init__(self, model, *, slots=None, cache_len=None,
                 prefill_buckets=None, eos_id=None, pad_id=None,
                 max_new_tokens=None, temperature=None, top_k=None,
                 kv_cache_dtype=None, draft_model=None, draft_k=None,
                 seed=0):
        # lazy: serving imports generation's scheduler, so module-level
        # imports the other way would cycle
        from ..serving.batcher import parse_buckets

        from ..runtime.compiled import CompiledStore, CompileWatch

        self.model = model
        model.eval()  # generation never wants dropout
        cfg = getattr(model, "config", None)
        self.slots = int(slots if slots is not None
                         else flag("generation_decode_slots"))
        self.cache_len = int(cache_len if cache_len is not None
                             else flag("generation_kv_cache_len"))
        self.prefill_buckets = parse_buckets(
            prefill_buckets if prefill_buckets is not None
            else flag("generation_prefill_buckets"))
        if self.slots <= 0:
            raise InvalidArgumentError(
                f"generation needs at least one decode slot, got {self.slots}")
        if self.prefill_buckets[-1] > self.cache_len:
            raise InvalidArgumentError(
                f"largest prefill bucket {self.prefill_buckets[-1]} exceeds "
                f"the KV cache window {self.cache_len}; prompts must fit "
                "the cache")
        self.eos_id = (eos_id if eos_id is not None
                       else getattr(cfg, "eos_token_id", None))
        self.pad_id = int(pad_id if pad_id is not None
                          else getattr(cfg, "pad_token_id", 0))
        self.max_positions = int(getattr(cfg, "max_position_embeddings",
                                         1 << 30))
        self.default_max_new_tokens = int(
            max_new_tokens if max_new_tokens is not None
            else flag("generation_max_new_tokens"))
        self.default_temperature = float(
            temperature if temperature is not None
            else flag("generation_temperature"))
        # static: a different top_k is a different program (lax.top_k k);
        # per-request temperature stays a traced array and is free
        self.top_k = int(top_k if top_k is not None
                         else flag("generation_top_k"))
        # KV storage dtype: int8 stores the ring cache as int8 + per-head
        # dynamic scales (~4x fewer cache bytes -> ~2x the slots per HBM;
        # quantize on ring write, dequantize in the attention read). The
        # int8 avals change the compiled signature, so each dtype mode
        # gets its own cache keys in the CompiledStore — never a silent
        # reuse of the other mode's program.
        self.kv_cache_dtype = str(
            kv_cache_dtype if kv_cache_dtype is not None
            else flag("generation_kv_cache_dtype"))
        if self.kv_cache_dtype not in _cache.KV_CACHE_DTYPES:
            raise InvalidArgumentError(
                f"generation_kv_cache_dtype must be one of "
                f"{_cache.KV_CACHE_DTYPES}, got {self.kv_cache_dtype!r}")
        spec = model.cache_spec()
        self._num_layers, self._num_heads, self._head_dim = (
            int(spec[0]), int(spec[1]), int(spec[2]))
        # speculative decoding: a draft model makes the engine run
        # draft/verify rounds instead of single-token decode steps. The
        # physical ring store widens by draft_k scratch entries so the
        # verify span's in-place writes stay window-exact (cache.py).
        self.draft_model = draft_model
        self.speculative = draft_model is not None
        self.draft_k = int(draft_k if draft_k is not None
                           else flag("speculative_draft_k"))
        if self.speculative:
            if self.draft_k < 1:
                raise InvalidArgumentError(
                    f"speculative draft_k must be >= 1, got {self.draft_k}")
            draft_model.eval()
            dspec = draft_model.cache_spec()
            self._draft_layers, self._draft_heads, self._draft_dim = (
                int(dspec[0]), int(dspec[1]), int(dspec[2]))
            dcfg = getattr(draft_model, "config", None)
            self._draft_max_positions = int(getattr(
                dcfg, "max_position_embeddings", 1 << 30))
            dvocab = getattr(dcfg, "vocab_size", None)
            tvocab = getattr(cfg, "vocab_size", None)
            if dvocab is not None and tvocab is not None \
                    and int(dvocab) != int(tvocab):
                raise InvalidArgumentError(
                    f"draft model vocab ({dvocab}) must match the target "
                    f"({tvocab}); proposals are target token ids")
            tmax = int(getattr(cfg, "max_position_embeddings", 1 << 30))
            if self._draft_max_positions < tmax:
                # the draft tracks the target's positions exactly; a
                # shorter draft context would silently gather clamped
                # position embeddings past its limit (garbage prompt
                # view, acceptance collapse) — refuse loudly instead
                raise InvalidArgumentError(
                    f"draft max_position_embeddings "
                    f"({self._draft_max_positions}) must cover the "
                    f"target's ({tmax}); the draft decodes the same "
                    "positions")
        self.store_len = self.cache_len + (
            self.draft_k if self.speculative else 0)
        # static capacity admission (FLAGS_memory_budget_check): the
        # slots x cache-len x dtype geometry is budgeted against the
        # device HBM BEFORE the rings allocate — a fleet operator learns
        # "this geometry cannot fit; suggest_decode_slots says N" at
        # boot, not as an allocator OOM mid-warmup
        self.check_memory_budget()
        self._base_key = jax.random.PRNGKey(int(seed))
        self._key_step = 0
        # the sampling-key counter is bumped from every dispatch path and
        # those paths run on different threads (prefill from HTTP handler
        # threads, decode from the batcher loop): every bump goes through
        # _next_key_step, which locks AND returns the snapshot — a bare
        # `+= 1` followed by a re-read hands two threads the same ctr,
        # correlating two requests' samples. The same lock guards the
        # speculative acceptance counters /statz reads.
        self._key_lock = threading.Lock()
        # speculative acceptance accounting (spec_stats / statz)
        self._spec_rounds = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self.reset()
        # eval_step-style snapshot: walk the module tree once, read the
        # live arrays per call (cheap, and parameter updates flow in)
        self._named = None
        self._draft_named = None
        self._prefill_jit = jax.jit(self._prefill_pure)
        self._spec_prefill_jit = jax.jit(self._spec_prefill_pure)
        self._decode_jit = jax.jit(self._decode_pure)
        self._prefill_export_jit = jax.jit(self._prefill_export_pure)
        self._draft_jit = jax.jit(self._draft_chain_pure)
        self._verify_jit = jax.jit(self._verify_pure)
        self._draft_prefill_jit = jax.jit(self._draft_prefill_pure)
        # compiled prefill/decode programs live in the SHARED compiled-
        # callable runtime: AOT compile + cost capture (decode MFU in the
        # /statz ledger) + the flag-governed LRU bound, with every new
        # signature counted through ``generation::compile`` — the
        # bounded-compile discipline the batch-bucket ladder established,
        # on the sequence axis
        self._stores = {
            label: CompiledStore(f"generation_{label}",
                                 miss_counter=COMPILE_COUNTER)
            for label in ("prefill", "decode", "draft", "verify")}
        # deterministic per-engine index for the cache signature (stable
        # cache_key across runs, distinct per engine in the CostRecord
        # registry — two engines may share avals but not weights)
        self._instance = next(_engine_counter)
        self.warmed = False
        # the serving-wide warmup-snapshot discipline; the continuous
        # batcher notes growth through this same watch
        self.watch = CompileWatch(
            lambda: _counters().get(COMPILE_COUNTER, 0),
            metric="serving/gen_unexpected_compiles",
            event="generation_unexpected_compile")

    # -- functional state -----------------------------------------------------

    @staticmethod
    def _snapshot_named(model):
        return {
            "params": [(n, p, getattr(p, "trainable", True))
                       for n, p in model.named_parameters()],
            "buffers": [(n, b) for n, b in model.named_buffers()
                        if b is not None],
        }

    @staticmethod
    def _named_state(named):
        params, frozen = OrderedDict(), OrderedDict()
        for n, p, trainable in named["params"]:
            (params if trainable else frozen)[n] = p._array
        return {
            "params": params,
            "frozen": frozen,
            "buffers": OrderedDict(
                (n, b._array) for n, b in named["buffers"]),
        }

    def _state(self):
        if self._named is None:
            self._named = self._snapshot_named(self.model)
        return self._named_state(self._named)

    def _draft_state(self):
        if self._draft_named is None:
            self._draft_named = self._snapshot_named(self.draft_model)
        return self._named_state(self._draft_named)

    def reset(self):
        """Zero every slot (all caches empty, positions 0)."""
        from ..monitor import registry as _mon

        ring_slots = getattr(self, "_ring_slots", self.slots)
        self._kv = _cache.init_cache(
            self._num_layers, ring_slots, self._num_heads, self.store_len,
            self._head_dim, dtype=self.kv_cache_dtype)
        if self.speculative:
            # draft ring arrays only — the draft mirrors the target's
            # committed token history exactly, so ONE shared pos vector
            # (the target kv's) serves both caches
            self._kv_draft = _cache.init_cache(
                self._draft_layers, ring_slots, self._draft_heads,
                self.store_len, self._draft_dim,
                dtype=self.kv_cache_dtype)[:-1]
        # the decode-capacity denominators, as registry gauges: what the
        # KV cache costs in HBM lands in /metrics next to the hbm/*
        # gauges it competes with (int8 mode shows the ~4x cut directly)
        _mon.gauge("generation/kv_cache_bytes").set(
            _cache.cache_nbytes(self._kv))
        _mon.gauge("generation/kv_bytes_per_token").set(
            self.kv_bytes_per_token())
        return self

    def cache_nbytes(self) -> int:
        """Device bytes the whole decode cache occupies (all slots,
        values + scales + positions, plus the draft ring when
        speculative) — the measured side of the int8-vs-f32 HBM claim."""
        n = _cache.cache_nbytes(self._kv)
        if self.speculative:
            n += _cache.cache_nbytes(self._kv_draft)
        return n

    def kv_bytes_per_token(self) -> int:
        """Cache bytes one decoded token occupies across all layers."""
        return _cache.kv_bytes_per_token(
            self._num_layers, self._num_heads, self._head_dim,
            self.kv_cache_dtype)

    # -- static HBM capacity planning -----------------------------------------

    @staticmethod
    def _module_nbytes(model) -> int:
        total = 0
        for _n, p in model.named_parameters():
            a = p._array
            total += int(np.prod(a.shape, dtype=np.int64)) \
                * np.dtype(a.dtype).itemsize
        for _n, b in model.named_buffers():
            if b is None:
                continue
            a = b._array
            total += int(np.prod(a.shape, dtype=np.int64)) \
                * np.dtype(a.dtype).itemsize
        return total

    def param_nbytes(self) -> int:
        """Device bytes the model weights occupy (target + draft when
        speculative) — the fixed term of the capacity plan."""
        total = self._module_nbytes(self.model)
        if self.speculative:
            total += self._module_nbytes(self.draft_model)
        return total

    def slot_nbytes(self, kv_cache_dtype=None) -> int:
        """Ring bytes ONE decode slot costs at this engine's geometry:
        ``store_len x kv_bytes_per_token`` (values + scales at int8)
        plus the slot's position word, plus the draft ring's analog when
        speculative — the per-slot divisor of
        :meth:`suggest_decode_slots`."""
        dtype = str(kv_cache_dtype if kv_cache_dtype is not None
                    else self.kv_cache_dtype)
        per = self.store_len * _cache.kv_bytes_per_token(
            self._num_layers, self._num_heads, self._head_dim, dtype) + 4
        if self.speculative:
            per += self.store_len * _cache.kv_bytes_per_token(
                self._draft_layers, self._draft_heads, self._draft_dim,
                dtype)
        return per

    def hbm_required_bytes(self, slots=None, kv_cache_dtype=None) -> int:
        """Predicted device bytes the engine's geometry holds resident:
        weights plus ``slots`` rings — the static plan the capacity
        admission and :meth:`suggest_decode_slots` budget against
        (matches :meth:`cache_nbytes` on the real arrays)."""
        n = int(slots if slots is not None else self.slots)
        return self.param_nbytes() + n * self.slot_nbytes(kv_cache_dtype)

    def suggest_decode_slots(self, hbm_budget_bytes=None,
                             kv_cache_dtype=None) -> int:
        """Decode slots this model fits in ``hbm_budget_bytes`` (default:
        the device HBM from the cost-model peaks table): ``(budget -
        weights) // slot_nbytes``. ``kv_cache_dtype`` asks the other
        cache mode's answer (int8 roughly doubles the count) without
        rebuilding the engine — the serving-capacity recipe in README
        "Memory planning"."""
        if hbm_budget_bytes is None:
            from ..analysis.memory import hbm_budget_bytes as _budget

            hbm_budget_bytes = _budget()
        avail = int(hbm_budget_bytes) - self.param_nbytes()
        if avail <= 0:
            return 0
        return int(avail // self.slot_nbytes(kv_cache_dtype))

    def check_memory_budget(self, level=None, budget_bytes=None):
        """Refuse (strict) or warn about a slots x cache-len x dtype
        geometry the static plan says cannot fit the device HBM.
        ``level`` defaults to ``FLAGS_memory_budget_check``; returns the
        required bytes when admitted."""
        from ..analysis.memory import (
            MemoryBudgetError,
            _fmt_bytes,
            hbm_budget_bytes as _budget,
        )

        lvl = str(level if level is not None
                  else flag("memory_budget_check")).strip().lower()
        if lvl in ("", "0", "off", "false", "no"):
            return None
        budget = int(budget_bytes if budget_bytes is not None
                     else _budget())
        required = self.hbm_required_bytes()
        if budget <= 0 or required <= budget:
            return required
        fits = self.suggest_decode_slots(budget)
        msg = (
            f"generation geometry cannot fit: {self.slots} slot(s) x "
            f"cache_len {self.cache_len} (store {self.store_len}) x "
            f"{self.kv_cache_dtype} KV needs "
            f"{_fmt_bytes(required)} (weights "
            f"{_fmt_bytes(self.param_nbytes())} + "
            f"{_fmt_bytes(self.slot_nbytes())}/slot) against "
            f"{_fmt_bytes(budget)} HBM; suggest_decode_slots("
            f"{budget}) = {fits}"
            + ("" if self.kv_cache_dtype == "int8" else
               f" (int8 KV would fit "
               f"{self.suggest_decode_slots(budget, 'int8')})"))
        _flight.record_event(
            "memory_budget", scope="generation", verdict="over_budget",
            required_bytes=required, budget_bytes=budget,
            slots=self.slots, cache_len=self.cache_len,
            kv_cache_dtype=self.kv_cache_dtype, suggested_slots=fits)
        if lvl == "strict":
            raise MemoryBudgetError(msg, budget_bytes=budget)
        import warnings

        warnings.warn(f"memory_budget_check={lvl}: {msg}",
                      RuntimeWarning, stacklevel=3)
        return required

    # -- compile accounting ---------------------------------------------------

    def _dispatch(self, label, jitted, args):
        """Run one compiled step through the shared compiled-callable
        runtime: new signatures are AOT-compiled and cost-captured (MFU
        in ``/statz``) under the one policy every dispatch site shares,
        and every compile is COUNTED (``generation::compile``, the
        store's miss counter)."""
        store = self._stores[label]
        leaves = jax.tree_util.tree_leaves(args)
        sig = (self._instance,) + tuple(
            (tuple(x.shape), str(x.dtype)) for x in leaves)
        entry, disposition = store.get_or_build(
            sig, lambda: (jitted, None))
        # the slot-admission / dispatch span (if one is current) learns
        # whether this call compiled — the compile-vs-execute attribution
        # a /tracez reader needs (the runtime adds cache_key + flops)
        _tracing.annotate(program_cache=disposition)
        return store.dispatch(entry, *args)

    def extra_compiles(self) -> int:
        """Compiles since warmup — steady state must keep this at 0."""
        return self.watch.extra()

    def expected_compiles(self, kind="generate") -> int:
        """Exact warmup program count for a backend ``kind``:

        - ``generate`` (unified): one prefill per ladder bucket, plus
          either the single decode program or the draft + verify pair;
        - ``prefill`` (disaggregated prefill tier): one prefill-export
          per bucket, nothing else;
        - ``decode`` (disaggregated decode tier): the decode (or
          draft + verify) program(s); a speculative decode tier also
          compiles one draft-prefill per bucket (the handed-off slab is
          target-only — the draft's view of the prompt is built at
          admission).
        """
        buckets = len(self.prefill_buckets)
        decode = 2 if self.speculative else 1
        if kind == "generate":
            return buckets + decode
        if kind == "prefill":
            return buckets
        if kind == "decode":
            return decode + (buckets if self.speculative else 0)
        raise InvalidArgumentError(
            f"unknown backend kind {kind!r}; expected generate | "
            "prefill | decode")

    def warmup(self, kind="generate"):
        """Compile exactly ``expected_compiles(kind)`` programs ahead
        of traffic, then snapshot the compile counter. Idempotent."""
        if self.warmed:
            return self
        self.expected_compiles(kind)  # validates the kind loudly
        with RecordEvent("generation::warmup"):
            if kind in ("generate",):
                for bucket in self.prefill_buckets:
                    self.admit(0, [self.pad_id] * int(bucket))
            elif kind == "prefill":
                # a prefill tier never decodes: shrink the untouched
                # decode (and draft) rings to one slot — this tier's
                # HBM belongs to prefill activations, not a ring
                # nobody writes (its selling point in disaggregation)
                self._ring_slots = 1
                self.reset()
                for bucket in self.prefill_buckets:
                    self.prefill_export([self.pad_id] * int(bucket))
            elif kind == "decode" and self.speculative:
                for bucket in self.prefill_buckets:
                    self._admit_draft(0, [self.pad_id] * int(bucket))
            if kind != "prefill":
                if kind == "decode":
                    # pre-drive the handoff admission: the eager
                    # pad/insert ops pay their one-time op compiles NOW
                    # (per plane shape), not on the first live slab —
                    # that cold cost is exactly the TTFT tail the
                    # disaggregation bench measures
                    self.admit_prefilled(
                        0, self._fresh_slot_planes(), 1, 0,
                        prompt=[self.pad_id] if self.speculative
                        else None)
                if self.speculative:
                    self.spec_step(np.zeros(self.slots, np.int32),
                                   np.zeros(self.slots, np.float32))
                else:
                    self.step(np.zeros(self.slots, np.int32),
                              np.zeros(self.slots, np.float32))
        self.reset()  # warmup traffic must not look like live context
        with self._key_lock:
            self._spec_rounds = 0
            self._spec_proposed = 0
            self._spec_accepted = 0
        self.watch.arm()
        self.warmed = True
        _flight.record_event(
            "generation_warmup", backend_kind=kind,
            prefill_buckets=list(self.prefill_buckets),
            slots=self.slots, cache_len=self.cache_len,
            speculative=self.speculative,
            programs=self.expected_compiles(kind))
        return self

    def _fresh_slot_planes(self):
        """Zeroed window-width per-slot planes (a synthetic empty slab
        — warmup's stand-in for a real handoff)."""
        return tuple(
            a[:, 0] for a in _cache.init_cache(
                self._num_layers, 1, self._num_heads, self.cache_len,
                self._head_dim, dtype=self.kv_cache_dtype)[:-1])

    # -- pure steps (jitted) --------------------------------------------------

    def _prefill_forward(self, model, state, layers, heads, head_dim,
                         tokens, length):
        """One bucketed prefill forward into window-width fresh caches:
        returns (logits ``[1, P, V]``, per-slot planes ``[L, H, C, D]``
        (+scales)). Shared by target prefill, draft prefill, and the
        prefill-export program."""
        p = tokens.shape[1]
        fresh = _cache.fresh_layer_caches(
            layers, 1, heads, self.cache_len, head_dim,
            dtype=self.kv_cache_dtype)
        mask = _cache.prefill_mask(p, self.cache_len, length)
        pos_ids = jnp.arange(p, dtype=jnp.int32)[None]
        (logits, new_caches), _ = functional_call(
            model, state, tokens,
            position_ids=pos_ids, attention_mask=mask, caches=fresh)
        stacked = _cache.stack_layer_caches(new_caches)
        return logits, tuple(a[:, 0] for a in stacked)

    def _sample_first(self, logits, length, temp, ctr):
        """Sample the first generated token from the last REAL prompt
        position of a prefill's logits."""
        last = jax.lax.dynamic_index_in_dim(
            logits[0], length - 1, axis=0, keepdims=False)
        key = jax.random.fold_in(self._base_key, ctr)
        return sample_logits(last[None], key, temp[None], self.top_k)[0]

    def _prefill_pure(self, state, kv, slot, tokens, length, temp, ctr):
        """Bucketed prefill of ONE prompt into decode slot ``slot``.

        ``tokens [1, P]`` (P = a ladder bucket), ``length`` = true prompt
        length. Runs the full forward over the bucket with fresh
        per-layer caches, installs the K/V (and, at int8, the scale
        planes) into the slot (zero-padded from the window width up to
        the ring store), and samples the first generated token from the
        last REAL prompt position.
        """
        logits, planes = self._prefill_forward(
            self.model, state, self._num_layers, self._num_heads,
            self._head_dim, tokens, length)
        kv = _cache.insert_slot_kv(
            kv, slot, _cache.pad_slot_arrays(planes, self.store_len),
            length)
        tok = self._sample_first(logits, length, temp, ctr)
        return kv, tok

    def _spec_prefill_pure(self, state, dstate, kv, kv_draft, slot,
                           tokens, length, temp, ctr):
        """Speculative twin of :meth:`_prefill_pure`: ONE program
        prefills the prompt through BOTH models — the draft ring must
        hold the same committed history as the target's before the
        first draft chain runs."""
        logits, planes = self._prefill_forward(
            self.model, state, self._num_layers, self._num_heads,
            self._head_dim, tokens, length)
        kv = _cache.insert_slot_kv(
            kv, slot, _cache.pad_slot_arrays(planes, self.store_len),
            length)
        _, dplanes = self._prefill_forward(
            self.draft_model, dstate, self._draft_layers,
            self._draft_heads, self._draft_dim, tokens, length)
        kv_draft = tuple(
            a.at[:, slot].set(n) for a, n in zip(
                kv_draft,
                _cache.pad_slot_arrays(dplanes, self.store_len)))
        tok = self._sample_first(logits, length, temp, ctr)
        return kv, kv_draft, tok

    def _prefill_export_pure(self, state, tokens, length, temp, ctr):
        """Prefill-tier program: the bucketed forward WITHOUT a decode
        ring — returns the window-width per-slot KV planes (the handoff
        slab) and the first sampled token. The decode tier lands the
        slab with :meth:`admit_prefilled`."""
        logits, planes = self._prefill_forward(
            self.model, state, self._num_layers, self._num_heads,
            self._head_dim, tokens, length)
        tok = self._sample_first(logits, length, temp, ctr)
        return planes, tok

    def _draft_prefill_pure(self, dstate, kv_draft, slot, tokens,
                            length):
        """Draft-only prefill into draft slot ``slot`` — a decode-tier
        engine admitting a handed-off TARGET slab still needs the
        draft's view of the prompt before it can speculate on it."""
        _, dplanes = self._prefill_forward(
            self.draft_model, dstate, self._draft_layers,
            self._draft_heads, self._draft_dim, tokens, length)
        return tuple(
            a.at[:, slot].set(n) for a, n in zip(
                kv_draft,
                _cache.pad_slot_arrays(dplanes, self.store_len)))

    def _decode_pure(self, state, kv, tokens, temps, ctr):
        """One decode step for EVERY slot: ``tokens [S]`` (each slot's
        last token) -> next token per slot. Static shapes throughout —
        this is the program whose compile count is exactly 1."""
        caches = _cache.layer_caches(*kv)
        pos = kv[-1]
        pos_ids = jnp.minimum(pos, self.max_positions - 1)[:, None]
        mask = _cache.decode_mask(pos, self.store_len,
                                  window=self.cache_len)
        (logits, new_caches), _ = functional_call(
            self.model, state, tokens[:, None],
            position_ids=pos_ids, attention_mask=mask, caches=caches)
        kv = _cache.stack_layer_caches(new_caches) + (pos + 1,)
        key = jax.random.fold_in(self._base_key, ctr)
        nxt = sample_logits(logits[:, 0], key, temps, self.top_k)
        return kv, nxt

    def _draft_chain_pure(self, dstate, kv_draft, pos, tokens):
        """The draft program: ``k`` greedy proposals per slot from one
        dispatch. ``k + 1`` chained single-token draft decode steps —
        step ``j`` writes its input token's K/V at ``pos + j`` (so the
        draft ring ends the round holding the FULL proposed chain,
        including the last proposal: on full acceptance the draft's
        committed history still mirrors the target's) and feeds its
        argmax forward. Returns (draft arrays, proposals ``[S, k]``)."""
        caches = _cache.layer_caches(*(kv_draft + (pos,)))
        cur = tokens
        proposals = []
        for j in range(self.draft_k + 1):
            pj = pos + j
            pos_ids = jnp.minimum(pj, self._draft_max_positions - 1)[:, None]
            mask = _cache.decode_mask(pj, self.store_len,
                                      window=self.cache_len)
            (logits, caches), _ = functional_call(
                self.draft_model, dstate, cur[:, None],
                position_ids=pos_ids, attention_mask=mask, caches=caches)
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            if j < self.draft_k:
                proposals.append(nxt)
            cur = nxt
        return (_cache.stack_layer_caches(caches),
                jnp.stack(proposals, axis=1))

    def _verify_pure(self, state, kv, tokens, proposals, temps, ctr):
        """The verify program: ONE batched target forward over all
        ``k + 1`` in-flight positions of every slot.

        Inputs ``[S, k+1] = [last committed token | k proposals]`` write
        their K/V into the ring span ``pos .. pos+k`` (in place —
        window-exact by the store margin) and produce logits at every
        position; the target's own sampled chain ``ts`` decides
        acceptance: the longest proposal prefix with ``proposal[i] ==
        ts[i]`` is accepted and ``ts[m]`` (the sample one past it) is
        the correction/bonus token, so the round emits ``ts[:, :m+1]``
        — exactly the token sequence the plain engine would have
        produced one dispatch at a time (greedy: ``ts`` IS the argmax
        chain). ``pos`` advances by the emitted count; rejected-position
        ring writes are left as masked garbage for the next round's
        span to overwrite."""
        span = self.draft_k + 1
        seq = jnp.concatenate([tokens[:, None], proposals], axis=1)
        caches = _cache.layer_caches(*kv)
        pos = kv[-1]
        pos_ids = jnp.minimum(
            pos[:, None] + jnp.arange(span, dtype=jnp.int32)[None, :],
            self.max_positions - 1)
        mask = _cache.verify_mask(pos, self.store_len, span,
                                  window=self.cache_len)
        (logits, new_caches), _ = functional_call(
            self.model, state, seq,
            position_ids=pos_ids, attention_mask=mask, caches=caches)
        key = jax.random.fold_in(self._base_key, ctr)
        ts = jnp.stack(
            [sample_logits(logits[:, i], jax.random.fold_in(key, i),
                           temps, self.top_k) for i in range(span)],
            axis=1)
        match = (proposals == ts[:, :self.draft_k]).astype(jnp.int32)
        # cumprod/sum promote int32 -> int64 under x64 mode; the pos
        # vector's dtype is part of every program's signature, so pin
        # it or the second round re-compiles everything downstream
        accepted = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        counts = (accepted + 1).astype(jnp.int32)
        kv = _cache.stack_layer_caches(new_caches) + (
            (pos + counts).astype(jnp.int32),)
        return kv, ts, counts

    # -- scheduler primitives -------------------------------------------------

    def bucket_for(self, prompt_len) -> int:
        """Smallest prefill bucket covering ``prompt_len``."""
        for b in self.prefill_buckets:
            if b >= prompt_len:
                return int(b)
        raise InvalidArgumentError(
            f"prompt of {prompt_len} tokens exceeds the largest prefill "
            f"bucket {self.prefill_buckets[-1]}; raise "
            "FLAGS_generation_prefill_buckets or truncate")

    def validate(self, prompt, max_new_tokens) -> int:
        """Admission checks shared by offline generate and the serving
        scheduler. Returns the prompt length."""
        n = len(prompt)
        if n < 1:
            raise InvalidArgumentError("generation needs a non-empty prompt")
        self.bucket_for(n)  # raises if no bucket covers it
        if max_new_tokens < 1:
            raise InvalidArgumentError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        total = n + int(max_new_tokens)
        if total > self.max_positions:
            raise InvalidArgumentError(
                f"prompt ({n}) + max_new_tokens ({max_new_tokens}) = "
                f"{total} exceeds the model's max_position_embeddings "
                f"{self.max_positions}")
        return n

    def _padded_prompt(self, prompt):
        n = len(prompt)
        bucket = self.bucket_for(n)
        padded = np.full(bucket, self.pad_id, np.int32)
        padded[:n] = np.asarray(prompt, np.int32)
        return padded, n

    def _next_key_step(self) -> int:
        """Bump the sampling-key counter under its lock and return the
        snapshot. Every dispatch site uses the RETURNED value — re-reading
        ``self._key_step`` after an unlocked ``+=`` is the race graphlint's
        ``unlocked-shared-mutation`` rule exists for (two threads sampling
        with the same key)."""
        with self._key_lock:
            self._key_step += 1
            return self._key_step

    def admit(self, slot, prompt, temperature=None) -> int:
        """Prefill ``prompt`` into ``slot`` and return the first sampled
        token. The slot's previous occupant is simply overwritten — a
        vacated slot needs no reset pass. Speculative engines prefill
        the draft ring in the same program."""
        padded, n = self._padded_prompt(prompt)
        temp = (self.default_temperature if temperature is None
                else float(temperature))
        ctr = self._next_key_step()
        with RecordEvent("generation::prefill"):
            if self.speculative:
                out = self._dispatch("prefill", self._spec_prefill_jit, (
                    self._state(), self._draft_state(), self._kv,
                    self._kv_draft, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(padded[None]), jnp.asarray(n, jnp.int32),
                    jnp.asarray(temp, jnp.float32),
                    jnp.asarray(ctr, jnp.int32)))
                self._kv, self._kv_draft, tok = out
            else:
                out = self._dispatch("prefill", self._prefill_jit, (
                    self._state(), self._kv,
                    jnp.asarray(slot, jnp.int32),
                    jnp.asarray(padded[None]),
                    jnp.asarray(n, jnp.int32),
                    jnp.asarray(temp, jnp.float32),
                    jnp.asarray(ctr, jnp.int32)))
                self._kv, tok = out
        return int(tok)

    def prefill_export(self, prompt, temperature=None):
        """Prefill-tier primitive: run the bucketed forward and return
        ``(planes, length, first_token)`` — the window-width per-slot
        KV planes (``[L, H, C, D]`` values, ``[L, H, C]`` scales at
        int8), the true prompt length, and the first sampled token.
        The slab ships to a decode tier (:mod:`generation.handoff`)
        whose :meth:`admit_prefilled` lands it in a free slot."""
        padded, n = self._padded_prompt(prompt)
        temp = (self.default_temperature if temperature is None
                else float(temperature))
        ctr = self._next_key_step()
        with RecordEvent("generation::prefill_export"):
            planes, tok = self._dispatch(
                "prefill", self._prefill_export_jit, (
                    self._state(), jnp.asarray(padded[None]),
                    jnp.asarray(n, jnp.int32),
                    jnp.asarray(temp, jnp.float32),
                    jnp.asarray(ctr, jnp.int32)))
        return planes, n, int(tok)

    def _admit_draft(self, slot, prompt):
        """Draft-only prefill of ``prompt`` into draft slot ``slot`` —
        the decode-tier half of a speculative handoff admission."""
        padded, n = self._padded_prompt(prompt)
        with RecordEvent("generation::draft_prefill"):
            self._kv_draft = self._dispatch(
                "prefill", self._draft_prefill_jit, (
                    self._draft_state(), self._kv_draft,
                    jnp.asarray(slot, jnp.int32),
                    jnp.asarray(padded[None]),
                    jnp.asarray(n, jnp.int32)))

    def admit_prefilled(self, slot, planes, length, first_token,
                        prompt=None) -> int:
        """Land a handed-off KV slab in decode slot ``slot``: pad the
        window-width planes up to the ring store and commit them with
        the same functional indexed update admission always uses. The
        first token was already sampled by the prefill tier; it is
        returned unchanged for scheduler uniformity. A speculative
        engine additionally needs the PROMPT tokens (the slab is
        target-only) to build the draft's view via a draft prefill."""
        length = int(length)
        if not 1 <= length <= self.cache_len:
            raise InvalidArgumentError(
                f"handoff length {length} outside [1, {self.cache_len}]")
        arity = len(self._kv) - 1
        if len(planes) != arity:
            raise InvalidArgumentError(
                f"handoff slab has {len(planes)} planes, this engine's "
                f"{self.kv_cache_dtype} cache needs {arity} "
                "(kv_cache_dtype mismatch between tiers?)")
        padded = _cache.pad_slot_arrays(
            tuple(jnp.asarray(p) for p in planes), self.store_len)
        for a, p in zip(self._kv[:-1], padded):
            if tuple(p.shape) != tuple(a.shape[:1] + a.shape[2:]) \
                    or p.dtype != a.dtype:
                raise InvalidArgumentError(
                    f"handoff slab plane {tuple(p.shape)}/{p.dtype} does "
                    f"not fit this engine's cache "
                    f"{tuple(a.shape)}/{a.dtype}")
        if self.speculative:
            if prompt is None:
                raise InvalidArgumentError(
                    "a speculative decode tier needs the prompt tokens "
                    "with the KV slab (the draft ring must be prefilled)")
            self._admit_draft(slot, prompt)
        with RecordEvent("generation::admit_prefilled"):
            self._kv = _cache.insert_slot_kv(
                self._kv, slot, padded, length)
        return int(first_token)

    def step(self, tokens, temps) -> np.ndarray:
        """Decode one token for every slot. ``tokens``/``temps`` are
        host ``[S]`` arrays (vacant slots: anything — their output is
        ignored and their cache entries are overwritten on admission)."""
        ctr = self._next_key_step()
        with RecordEvent("generation::decode"):
            out = self._dispatch("decode", self._decode_jit, (
                self._state(), self._kv,
                jnp.asarray(np.asarray(tokens, np.int32)),
                jnp.asarray(np.asarray(temps, np.float32)),
                jnp.asarray(ctr, jnp.int32)))
        self._kv, nxt = out
        return np.asarray(nxt)

    def spec_step(self, tokens, temps, busy=None):
        """One speculative round for every slot: draft program (k
        proposals per slot) then verify program (one batched target
        forward over all k+1 positions). Returns ``(emitted [S, k+1],
        counts [S])`` — slot ``s`` produced ``emitted[s, :counts[s]]``
        new tokens this round (the caller truncates at EOS/budget).
        ``busy`` (slot indices, or None for all) scopes the acceptance
        accounting to slots actually generating."""
        if not self.speculative:
            raise InvalidArgumentError(
                "spec_step needs a draft model; construct the engine "
                "with draft_model= (FLAGS_speculative_enabled)")
        toks = jnp.asarray(np.asarray(tokens, np.int32))
        pos = self._kv[-1]
        with RecordEvent("generation::draft"):
            self._kv_draft, proposals = self._dispatch(
                "draft", self._draft_jit, (
                    self._draft_state(), self._kv_draft, pos, toks))
        ctr = self._next_key_step()
        with RecordEvent("generation::verify"):
            out = self._dispatch("verify", self._verify_jit, (
                self._state(), self._kv, toks, proposals,
                jnp.asarray(np.asarray(temps, np.float32)),
                jnp.asarray(ctr, jnp.int32)))
        self._kv, ts, counts = out
        counts = np.asarray(counts)
        n_busy = self.slots if busy is None else len(busy)
        if n_busy:
            accepted = int(counts.sum() - self.slots if busy is None
                           else sum(int(counts[s]) - 1 for s in busy))
            with self._key_lock:
                self._spec_rounds += 1
                self._spec_proposed += self.draft_k * n_busy
                self._spec_accepted += accepted
            from ..monitor import counter as _mcounter

            _mcounter("generation/spec_rounds_total").inc()
            _mcounter("generation/spec_proposed_total").inc(
                self.draft_k * n_busy)
            _mcounter("generation/spec_accepted_total").inc(accepted)
        return np.asarray(ts), counts

    def spec_stats(self) -> dict:
        """Speculative acceptance accounting since the last reset/
        warmup: rounds, proposed/accepted draft tokens, acceptance
        rate (the /statz block)."""
        with self._key_lock:  # consistent snapshot vs a concurrent round
            rounds, proposed, accepted = (
                self._spec_rounds, self._spec_proposed, self._spec_accepted)
        return {
            "enabled": self.speculative,
            "draft_k": self.draft_k if self.speculative else 0,
            "rounds": rounds,
            "proposed": proposed,
            "accepted": accepted,
            "acceptance_rate": round(accepted / proposed, 4)
            if proposed else None,
        }

    # -- offline API ----------------------------------------------------------

    def generate(self, prompts, max_new_tokens=None, temperature=None,
                 stop_at_eos=True, continuous=True):
        """Generate for a list of prompts, continuous-batched across the
        engine's slots: a finished sequence vacates its slot and the next
        prompt is admitted at the next step. ``continuous=False`` is the
        static baseline (a new group is admitted only when EVERY slot has
        drained — what tearing the batch down costs; bench.py's
        ``decode_throughput`` row measures the difference). Returns one
        token list per prompt (EOS included when hit)."""
        max_new = (self.default_max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        for prompt in prompts:
            self.validate(prompt, max_new)
        pending = deque(enumerate(prompts))
        results = [None] * len(prompts)
        active = {}  # slot -> (prompt_idx, tokens list)
        last = np.zeros(self.slots, np.int32)
        temps = np.zeros(self.slots, np.float32)
        temp = (self.default_temperature if temperature is None
                else float(temperature))

        def finished(tokens):
            return (len(tokens) >= max_new
                    or (stop_at_eos and self.eos_id is not None
                        and tokens[-1] == self.eos_id))

        while pending or active:
            admit_ok = bool(pending) and (continuous or not active)
            while admit_ok and pending and len(active) < self.slots:
                slot = next(s for s in range(self.slots) if s not in active)
                idx, prompt = pending.popleft()
                tok = self.admit(slot, prompt, temp)
                temps[slot] = temp
                if finished([tok]):
                    results[idx] = [tok]
                else:
                    active[slot] = (idx, [tok])
                    last[slot] = tok
            if not active:
                continue
            if self.speculative:
                ts, counts = self.spec_step(last, temps,
                                            busy=list(active))
                for slot in list(active):
                    idx, tokens = active[slot]
                    for i in range(int(counts[slot])):
                        tokens.append(int(ts[slot, i]))
                        last[slot] = ts[slot, i]
                        if finished(tokens):
                            break
                    if finished(tokens):
                        results[idx] = tokens
                        del active[slot]
            else:
                nxt = self.step(last, temps)
                for slot in list(active):
                    idx, tokens = active[slot]
                    tokens.append(int(nxt[slot]))
                    last[slot] = nxt[slot]
                    if finished(tokens):
                        results[idx] = tokens
                        del active[slot]
        return results
