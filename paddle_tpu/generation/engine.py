"""Compile-once generation engine: bucketed prefill + O(1)-cache decode.

The serving batcher bounds the BATCH axis with a powers-of-two bucket
ladder; autoregressive decoding re-opens the same compile-explosion on
the SEQUENCE axis (every prompt length and every growing context is a
new XLA program if shapes are dynamic). The engine closes it with a
prefill/decode split:

- **Prefill** pads the prompt up to a sequence-length bucket ladder
  (``FLAGS_generation_prefill_buckets``) and runs ONE full forward over
  the bucket, writing K/V into the admitted slot of the static ring
  cache — one compile per ladder bucket, ever.
- **Decode** is a single jitted step over ALL decode slots: read last
  tokens ``[S]``, attend the static cache window, sample, write back —
  its shapes never depend on sequence length or slot turnover, so its
  steady-state compile count is exactly 1 (asserted in tests and the
  gen-smoke the same way ``serving/unexpected_compiles`` is).

Compile accounting mirrors the serving pool: every new signature is AOT
lowered/compiled through the cost model (so decode MFU lands in the
``/statz`` ledger) and bumps the ``generation::compile`` profiler
counter — warmup snapshots it, and ``extra_compiles()`` must stay 0
under any traffic mix.

The engine is single-threaded by design (one decode stream per model
replica); :mod:`paddle_tpu.serving.continuous` drives it from a slot
scheduler for continuous batching, and :meth:`generate` runs the same
slot loop inline for offline use (bench, tests, parity goldens).
"""
from __future__ import annotations

import itertools
from collections import OrderedDict, deque

import numpy as np

import jax
import jax.numpy as jnp

from ..errors import InvalidArgumentError
from ..flags import flag
from ..framework.jit import functional_call
from ..monitor import flight_recorder as _flight
from ..monitor import tracing as _tracing
from ..profiler import RecordEvent, counters as _counters
from . import cache as _cache
from .sampling import sample_logits

__all__ = ["GenerationEngine", "COMPILE_COUNTER"]

COMPILE_COUNTER = "generation::compile"

# deterministic engine instance ids (cache-key stability; see __init__)
_engine_counter = itertools.count()


class GenerationEngine:
    """Slot-structured generation over a causal LM.

    ``model`` must expose ``forward(input_ids, position_ids,
    attention_mask, caches) -> (logits, caches)`` with per-layer
    :class:`nn.StaticCache` support plus ``cache_spec()`` (GPTForCausalLM
    is the reference implementation). The engine owns the stacked ring
    cache for ``slots`` concurrent sequences and exposes the two
    scheduler primitives: :meth:`admit` (prefill a prompt into a vacant
    slot, returns the first sampled token) and :meth:`step` (decode one
    token for every slot).
    """

    def __init__(self, model, *, slots=None, cache_len=None,
                 prefill_buckets=None, eos_id=None, pad_id=None,
                 max_new_tokens=None, temperature=None, top_k=None,
                 kv_cache_dtype=None, seed=0):
        # lazy: serving imports generation's scheduler, so module-level
        # imports the other way would cycle
        from ..serving.batcher import parse_buckets

        from ..runtime.compiled import CompiledStore, CompileWatch

        self.model = model
        model.eval()  # generation never wants dropout
        cfg = getattr(model, "config", None)
        self.slots = int(slots if slots is not None
                         else flag("generation_decode_slots"))
        self.cache_len = int(cache_len if cache_len is not None
                             else flag("generation_kv_cache_len"))
        self.prefill_buckets = parse_buckets(
            prefill_buckets if prefill_buckets is not None
            else flag("generation_prefill_buckets"))
        if self.slots <= 0:
            raise InvalidArgumentError(
                f"generation needs at least one decode slot, got {self.slots}")
        if self.prefill_buckets[-1] > self.cache_len:
            raise InvalidArgumentError(
                f"largest prefill bucket {self.prefill_buckets[-1]} exceeds "
                f"the KV cache window {self.cache_len}; prompts must fit "
                "the cache")
        self.eos_id = (eos_id if eos_id is not None
                       else getattr(cfg, "eos_token_id", None))
        self.pad_id = int(pad_id if pad_id is not None
                          else getattr(cfg, "pad_token_id", 0))
        self.max_positions = int(getattr(cfg, "max_position_embeddings",
                                         1 << 30))
        self.default_max_new_tokens = int(
            max_new_tokens if max_new_tokens is not None
            else flag("generation_max_new_tokens"))
        self.default_temperature = float(
            temperature if temperature is not None
            else flag("generation_temperature"))
        # static: a different top_k is a different program (lax.top_k k);
        # per-request temperature stays a traced array and is free
        self.top_k = int(top_k if top_k is not None
                         else flag("generation_top_k"))
        # KV storage dtype: int8 stores the ring cache as int8 + per-head
        # dynamic scales (~4x fewer cache bytes -> ~2x the slots per HBM;
        # quantize on ring write, dequantize in the attention read). The
        # int8 avals change the compiled signature, so each dtype mode
        # gets its own cache keys in the CompiledStore — never a silent
        # reuse of the other mode's program.
        self.kv_cache_dtype = str(
            kv_cache_dtype if kv_cache_dtype is not None
            else flag("generation_kv_cache_dtype"))
        if self.kv_cache_dtype not in _cache.KV_CACHE_DTYPES:
            raise InvalidArgumentError(
                f"generation_kv_cache_dtype must be one of "
                f"{_cache.KV_CACHE_DTYPES}, got {self.kv_cache_dtype!r}")
        spec = model.cache_spec()
        self._num_layers, self._num_heads, self._head_dim = (
            int(spec[0]), int(spec[1]), int(spec[2]))
        self._base_key = jax.random.PRNGKey(int(seed))
        self._key_step = 0
        self.reset()
        # eval_step-style snapshot: walk the module tree once, read the
        # live arrays per call (cheap, and parameter updates flow in)
        self._named = None
        self._prefill_jit = jax.jit(self._prefill_pure)
        self._decode_jit = jax.jit(self._decode_pure)
        # compiled prefill/decode programs live in the SHARED compiled-
        # callable runtime: AOT compile + cost capture (decode MFU in the
        # /statz ledger) + the flag-governed LRU bound, with every new
        # signature counted through ``generation::compile`` — the
        # bounded-compile discipline the batch-bucket ladder established,
        # on the sequence axis
        self._stores = {
            label: CompiledStore(f"generation_{label}",
                                 miss_counter=COMPILE_COUNTER)
            for label in ("prefill", "decode")}
        # deterministic per-engine index for the cache signature (stable
        # cache_key across runs, distinct per engine in the CostRecord
        # registry — two engines may share avals but not weights)
        self._instance = next(_engine_counter)
        self.warmed = False
        # the serving-wide warmup-snapshot discipline; the continuous
        # batcher notes growth through this same watch
        self.watch = CompileWatch(
            lambda: _counters().get(COMPILE_COUNTER, 0),
            metric="serving/gen_unexpected_compiles",
            event="generation_unexpected_compile")

    # -- functional state -----------------------------------------------------

    def _state(self):
        if self._named is None:
            self._named = {
                "params": [(n, p, getattr(p, "trainable", True))
                           for n, p in self.model.named_parameters()],
                "buffers": [(n, b) for n, b in self.model.named_buffers()
                            if b is not None],
            }
        params, frozen = OrderedDict(), OrderedDict()
        for n, p, trainable in self._named["params"]:
            (params if trainable else frozen)[n] = p._array
        return {
            "params": params,
            "frozen": frozen,
            "buffers": OrderedDict(
                (n, b._array) for n, b in self._named["buffers"]),
        }

    def reset(self):
        """Zero every slot (all caches empty, positions 0)."""
        from ..monitor import registry as _mon

        self._kv = _cache.init_cache(
            self._num_layers, self.slots, self._num_heads, self.cache_len,
            self._head_dim, dtype=self.kv_cache_dtype)
        # the decode-capacity denominators, as registry gauges: what the
        # KV cache costs in HBM lands in /metrics next to the hbm/*
        # gauges it competes with (int8 mode shows the ~4x cut directly)
        _mon.gauge("generation/kv_cache_bytes").set(
            _cache.cache_nbytes(self._kv))
        _mon.gauge("generation/kv_bytes_per_token").set(
            self.kv_bytes_per_token())
        return self

    def cache_nbytes(self) -> int:
        """Device bytes the whole decode cache occupies (all slots,
        values + scales + positions) — the measured side of the
        int8-vs-f32 HBM claim."""
        return _cache.cache_nbytes(self._kv)

    def kv_bytes_per_token(self) -> int:
        """Cache bytes one decoded token occupies across all layers."""
        return _cache.kv_bytes_per_token(
            self._num_layers, self._num_heads, self._head_dim,
            self.kv_cache_dtype)

    # -- compile accounting ---------------------------------------------------

    def _dispatch(self, label, jitted, args):
        """Run one compiled step through the shared compiled-callable
        runtime: new signatures are AOT-compiled and cost-captured (MFU
        in ``/statz``) under the one policy every dispatch site shares,
        and every compile is COUNTED (``generation::compile``, the
        store's miss counter)."""
        store = self._stores[label]
        leaves = jax.tree_util.tree_leaves(args)
        sig = (self._instance,) + tuple(
            (tuple(x.shape), str(x.dtype)) for x in leaves)
        entry, disposition = store.get_or_build(
            sig, lambda: (jitted, None))
        # the slot-admission / dispatch span (if one is current) learns
        # whether this call compiled — the compile-vs-execute attribution
        # a /tracez reader needs (the runtime adds cache_key + flops)
        _tracing.annotate(program_cache=disposition)
        return store.dispatch(entry, *args)

    def extra_compiles(self) -> int:
        """Compiles since warmup — steady state must keep this at 0."""
        return self.watch.extra()

    def warmup(self):
        """Compile every prefill bucket plus the decode step ahead of
        traffic (exactly ``len(prefill_buckets) + 1`` programs), then
        snapshot the compile counter. Idempotent."""
        if self.warmed:
            return self
        with RecordEvent("generation::warmup"):
            for bucket in self.prefill_buckets:
                self.admit(0, [self.pad_id] * int(bucket))
            self.step(np.zeros(self.slots, np.int32),
                      np.zeros(self.slots, np.float32))
        self.reset()  # warmup traffic must not look like live context
        self.watch.arm()
        self.warmed = True
        _flight.record_event(
            "generation_warmup", prefill_buckets=list(self.prefill_buckets),
            slots=self.slots, cache_len=self.cache_len)
        return self

    # -- pure steps (jitted) --------------------------------------------------

    def _prefill_pure(self, state, kv, slot, tokens, length, temp, ctr):
        """Bucketed prefill of ONE prompt into decode slot ``slot``.

        ``tokens [1, P]`` (P = a ladder bucket), ``length`` = true prompt
        length. Runs the full forward over the bucket with fresh
        per-layer caches, installs the K/V (and, at int8, the scale
        planes) into the slot, and samples the first generated token
        from the last REAL prompt position.
        """
        p = tokens.shape[1]
        fresh = _cache.fresh_layer_caches(
            self._num_layers, 1, self._num_heads, self.cache_len,
            self._head_dim, dtype=self.kv_cache_dtype)
        mask = _cache.prefill_mask(p, self.cache_len, length)
        pos_ids = jnp.arange(p, dtype=jnp.int32)[None]
        (logits, new_caches), _ = functional_call(
            self.model, state, tokens,
            position_ids=pos_ids, attention_mask=mask, caches=fresh)
        stacked = _cache.stack_layer_caches(new_caches)
        kv = _cache.insert_slot_kv(
            kv, slot, tuple(a[:, 0] for a in stacked), length)
        last = jax.lax.dynamic_index_in_dim(
            logits[0], length - 1, axis=0, keepdims=False)
        key = jax.random.fold_in(self._base_key, ctr)
        tok = sample_logits(last[None], key, temp[None], self.top_k)[0]
        return kv, tok

    def _decode_pure(self, state, kv, tokens, temps, ctr):
        """One decode step for EVERY slot: ``tokens [S]`` (each slot's
        last token) -> next token per slot. Static shapes throughout —
        this is the program whose compile count is exactly 1."""
        caches = _cache.layer_caches(*kv)
        pos = kv[-1]
        pos_ids = jnp.minimum(pos, self.max_positions - 1)[:, None]
        mask = _cache.decode_mask(pos, self.cache_len)
        (logits, new_caches), _ = functional_call(
            self.model, state, tokens[:, None],
            position_ids=pos_ids, attention_mask=mask, caches=caches)
        kv = _cache.stack_layer_caches(new_caches) + (pos + 1,)
        key = jax.random.fold_in(self._base_key, ctr)
        nxt = sample_logits(logits[:, 0], key, temps, self.top_k)
        return kv, nxt

    # -- scheduler primitives -------------------------------------------------

    def bucket_for(self, prompt_len) -> int:
        """Smallest prefill bucket covering ``prompt_len``."""
        for b in self.prefill_buckets:
            if b >= prompt_len:
                return int(b)
        raise InvalidArgumentError(
            f"prompt of {prompt_len} tokens exceeds the largest prefill "
            f"bucket {self.prefill_buckets[-1]}; raise "
            "FLAGS_generation_prefill_buckets or truncate")

    def validate(self, prompt, max_new_tokens) -> int:
        """Admission checks shared by offline generate and the serving
        scheduler. Returns the prompt length."""
        n = len(prompt)
        if n < 1:
            raise InvalidArgumentError("generation needs a non-empty prompt")
        self.bucket_for(n)  # raises if no bucket covers it
        if max_new_tokens < 1:
            raise InvalidArgumentError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        total = n + int(max_new_tokens)
        if total > self.max_positions:
            raise InvalidArgumentError(
                f"prompt ({n}) + max_new_tokens ({max_new_tokens}) = "
                f"{total} exceeds the model's max_position_embeddings "
                f"{self.max_positions}")
        return n

    def admit(self, slot, prompt, temperature=None) -> int:
        """Prefill ``prompt`` into ``slot`` and return the first sampled
        token. The slot's previous occupant is simply overwritten — a
        vacated slot needs no reset pass."""
        n = len(prompt)
        bucket = self.bucket_for(n)
        padded = np.full(bucket, self.pad_id, np.int32)
        padded[:n] = np.asarray(prompt, np.int32)
        temp = (self.default_temperature if temperature is None
                else float(temperature))
        self._key_step += 1
        with RecordEvent("generation::prefill"):
            out = self._dispatch("prefill", self._prefill_jit, (
                self._state(), self._kv,
                jnp.asarray(slot, jnp.int32), jnp.asarray(padded[None]),
                jnp.asarray(n, jnp.int32), jnp.asarray(temp, jnp.float32),
                jnp.asarray(self._key_step, jnp.int32)))
        self._kv, tok = out
        return int(tok)

    def step(self, tokens, temps) -> np.ndarray:
        """Decode one token for every slot. ``tokens``/``temps`` are
        host ``[S]`` arrays (vacant slots: anything — their output is
        ignored and their cache entries are overwritten on admission)."""
        self._key_step += 1
        with RecordEvent("generation::decode"):
            out = self._dispatch("decode", self._decode_jit, (
                self._state(), self._kv,
                jnp.asarray(np.asarray(tokens, np.int32)),
                jnp.asarray(np.asarray(temps, np.float32)),
                jnp.asarray(self._key_step, jnp.int32)))
        self._kv, nxt = out
        return np.asarray(nxt)

    # -- offline API ----------------------------------------------------------

    def generate(self, prompts, max_new_tokens=None, temperature=None,
                 stop_at_eos=True, continuous=True):
        """Generate for a list of prompts, continuous-batched across the
        engine's slots: a finished sequence vacates its slot and the next
        prompt is admitted at the next step. ``continuous=False`` is the
        static baseline (a new group is admitted only when EVERY slot has
        drained — what tearing the batch down costs; bench.py's
        ``decode_throughput`` row measures the difference). Returns one
        token list per prompt (EOS included when hit)."""
        max_new = (self.default_max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        for prompt in prompts:
            self.validate(prompt, max_new)
        pending = deque(enumerate(prompts))
        results = [None] * len(prompts)
        active = {}  # slot -> (prompt_idx, tokens list)
        last = np.zeros(self.slots, np.int32)
        temps = np.zeros(self.slots, np.float32)
        temp = (self.default_temperature if temperature is None
                else float(temperature))

        def finished(tokens):
            return (len(tokens) >= max_new
                    or (stop_at_eos and self.eos_id is not None
                        and tokens[-1] == self.eos_id))

        while pending or active:
            admit_ok = bool(pending) and (continuous or not active)
            while admit_ok and pending and len(active) < self.slots:
                slot = next(s for s in range(self.slots) if s not in active)
                idx, prompt = pending.popleft()
                tok = self.admit(slot, prompt, temp)
                temps[slot] = temp
                if finished([tok]):
                    results[idx] = [tok]
                else:
                    active[slot] = (idx, [tok])
                    last[slot] = tok
            if not active:
                continue
            nxt = self.step(last, temps)
            for slot in list(active):
                idx, tokens = active[slot]
                tokens.append(int(nxt[slot]))
                last[slot] = nxt[slot]
                if finished(tokens):
                    results[idx] = tokens
                    del active[slot]
        return results
