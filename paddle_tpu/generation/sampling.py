"""Token sampling and stopping for autoregressive decoding.

Two layers, ONE implementation of each idea in the codebase:

- :func:`sample_logits` — pure jnp, traced into the compiled
  prefill/decode steps. Greedy is temperature == 0 (selected with
  ``jnp.where``, so per-slot greedy/sampled mixes co-batch in one
  program); top-k is a STATIC engine-level knob (the ``top_k`` changes
  the lowered program, so per-request top-k would break the
  compile-once guarantee — per-request temperature is a traced array
  and stays free).
- :func:`decode_loop` — the eager host-side greedy loop every decoder
  model shares (``models/seq2seq.py`` delegates here instead of rolling
  its own), with EOS stopping.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["sample_logits", "top_k_filter", "decode_loop"]


def top_k_filter(logits, k):
    """Mask every logit below the k-th largest to -inf. ``k <= 0``
    disables (full distribution). Pure jnp; ``k`` is static."""
    k = int(k)
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def sample_logits(logits, key, temperature, top_k=0):
    """Draw one token per row from ``logits [B, V]``.

    ``temperature`` is scalar or ``[B]``; rows with ``temperature <= 0``
    take the argmax (greedy), others sample ``softmax(top_k(logits)/T)``
    — both branches are computed and selected with ``where`` so mixed
    batches stay a single program. Returns ``[B] int32``.
    """
    temperature = jnp.asarray(temperature, logits.dtype)
    if temperature.ndim == 0:
        temperature = jnp.broadcast_to(temperature, logits.shape[:1])
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = top_k_filter(logits, top_k) / jnp.maximum(
        temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def decode_loop(next_logits, ys, max_len, eos_id=None):
    """Greedy host-side decode loop (eager models, no KV cache).

    ``next_logits(ys) -> [B, V]`` returns next-token logits given the
    tokens so far (``ys [B, T]``, a Tensor); the loop appends the argmax
    until ``ys`` reaches ``max_len`` columns or (``eos_id`` set) every
    row has emitted EOS. Returns the grown ``ys``. One decode-loop
    implementation for the eager path — the compiled O(1)-cache path
    lives in :mod:`generation.engine`.
    """
    from .. import ops

    b = ys.shape[0]
    done = np.zeros(b, bool)
    for _ in range(int(max_len) - ys.shape[1]):
        logits = next_logits(ys)
        nxt = ops.argmax(logits, axis=-1)
        ys = ops.concat([ys, ops.reshape(nxt, [b, 1]).astype("int64")],
                        axis=1)
        if eos_id is not None:
            done |= np.asarray(nxt.numpy()).reshape(-1) == eos_id
            if done.all():
                break
    return ys
