"""Block-paged KV cache pool + radix prefix index with copy-on-write.

The ring cache (generation/cache.py) gives every decode slot a
contiguous worst-case-window allocation and prefills every prompt from
token 0. This module decomposes the SAME logical ring into fixed-size
pages drawn from one shared pool (``FLAGS_kv_cache_layout=paged``):

- **Page pool** — one pytree of page-major K/V planes per layer
  (``[L, P, H, ps, D]`` values, ``[L, P, H, ps]`` scales at int8,
  mirroring the fp32/int8 arity discipline of ``layer_caches``).
  Physical page 0 is the reserved **trash page**: vacant slots and
  unallocated logical pages point at it, so the compiled decode step
  can write every batch row unconditionally — a vacant row's garbage
  lands in trash instead of a page some other slot owns.
- **Page tables** — per-slot ``[NP]`` int32 rows mapping logical ring
  pages to pool pages. The attention read gathers through the table
  (``nn.PagedStaticCache``); logical index ``pos % (NP*ps)`` splits
  into page ``// ps`` and offset ``% ps``, so every ring mask and the
  wraparound contract carry over unchanged and greedy output is
  token-identical to the ring layout by construction.
- **Radix prefix index** — a trie keyed on CHAIN hashes of full pages
  of prompt tokens (page ``i``'s hash commits to pages ``0..i``). A new
  request maps the longest indexed prefix copy-on-write (refcounted:
  the pool page is retained per mapper, and a ring-wrap write into a
  shared page first copies it private) and prefills only its suffix.
  The index itself holds one refcount per registered page, so prefix
  pages survive slot release — a decode tier doubles as a fleet-wide
  prefix cache — and LRU leaf eviction returns index-only pages to the
  free list under pressure.

All allocation/refcount/CoW bookkeeping here is HOST-side and runs
between compiled steps; the device arrays stay a fixed-shape pytree, so
the compile-once discipline (``extra_compiles() == 0``) is untouched.
"""
from __future__ import annotations

import hashlib

import numpy as np

import jax.numpy as jnp

from ..errors import InvalidArgumentError
from ..nn.transformer import PagedStaticCache, QuantizedPagedCache
from .cache import NEG_INF, kv_bytes_per_token

__all__ = [
    "TRASH_PAGE", "PagePool", "PrefixIndex", "PagePoolExhaustedError",
    "page_nbytes", "chain_hashes", "split_planes", "init_paged_cache",
    "paged_layer_caches", "stack_paged_planes", "suffix_prefill_mask",
]

#: physical page id reserved as the write sink for vacant slots and
#: unallocated logical pages; never allocated, never read unmasked
TRASH_PAGE = 0


class PagePoolExhaustedError(InvalidArgumentError):
    """The pool has no free page and nothing evictable — the admission
    (or a decode-step wrap/CoW) cannot proceed. Size the pool with
    ``FLAGS_generation_kv_pool_pages`` or admit less concurrency."""


def page_nbytes(num_layers, num_heads, head_dim, page_size,
                dtype="float32") -> int:
    """Pool bytes ONE page costs across all layers (values + scales at
    int8) — the per-page unit of the paged capacity plan."""
    return int(page_size) * kv_bytes_per_token(
        num_layers, num_heads, head_dim, dtype)


def chain_hashes(tokens, page_size):
    """Content hashes for every FULL page of ``tokens``: page ``i``'s
    digest chains the parent's (hash of pages ``0..i-1``), so equal
    hashes imply equal full prefixes — the radix index key. Partial
    trailing pages are never hashed (not shareable)."""
    ps = int(page_size)
    toks = np.asarray(list(tokens), np.int64)
    out, parent = [], b""
    for i in range(len(toks) // ps):
        d = hashlib.sha256(
            parent + toks[i * ps:(i + 1) * ps].tobytes()).digest()
        parent = d
        out.append(d.hex()[:32])
    return out


def split_planes(planes, page_size):
    """Slice window-width per-slot planes (``[L, H, C, D]`` values /
    ``[L, H, C]`` scales, ``C % ps == 0``) into per-page plane tuples
    along the cache axis — the host-side page view a page-granular
    handoff ships."""
    ps = int(page_size)
    c = int(planes[0].shape[2])
    if c % ps:
        raise InvalidArgumentError(
            f"cache window {c} is not a multiple of the page size {ps}")
    return [tuple(np.ascontiguousarray(
        np.asarray(p)[:, :, i * ps:(i + 1) * ps]) for p in planes)
        for i in range(c // ps)]


def init_paged_cache(num_layers, num_heads, head_dim, page_size,
                     pool_pages, slots, pages_per_slot, dtype="float32"):
    """Zeroed whole-model paged cache pytree.

    ``dtype="float32"``: ``(k [L, P, H, ps, D], v, table [S, NP], pos
    [S])``; ``dtype="int8"`` additionally carries the scale pools
    ``(k, v, k_scale [L, P, H, ps], v_scale, table, pos)``. ``P`` is
    ``pool_pages + 1`` — the usable pool plus the reserved trash page —
    and every table entry starts at :data:`TRASH_PAGE`."""
    shape = (int(num_layers), int(pool_pages) + 1, int(num_heads),
             int(page_size), int(head_dim))
    table = jnp.full((int(slots), int(pages_per_slot)), TRASH_PAGE,
                     jnp.int32)
    pos = jnp.zeros((int(slots),), jnp.int32)
    if str(dtype) == "int8":
        return (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                jnp.zeros(shape[:-1], jnp.float32),
                jnp.zeros(shape[:-1], jnp.float32), table, pos)
    return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype), table,
            pos)


def paged_layer_caches(kv, table=None, pos=None):
    """Per-layer :class:`nn.PagedStaticCache` /
    :class:`nn.QuantizedPagedCache` views of the stacked pool (the
    paged analog of ``cache.layer_caches``; arity-dispatched). ``table``
    / ``pos`` override the pytree's own (a prefill passes the single
    admitted row)."""
    kv = tuple(kv)
    t = kv[-2] if table is None else table
    p = kv[-1] if pos is None else pos
    arrays = kv[:-2]
    cls = PagedStaticCache if len(arrays) == 2 else QuantizedPagedCache
    return [cls(*(a[i] for a in arrays), t, p)
            for i in range(arrays[0].shape[0])]


def stack_paged_planes(caches):
    """Re-stack per-layer paged caches returned by the model into the
    whole-model pool arrays (``(k, v)`` fp32 / ``(k, v, k_scale,
    v_scale)`` int8) — every layer's cache already holds the FULL
    updated pool for that layer."""
    if isinstance(caches[0], QuantizedPagedCache):
        return (jnp.stack([c.k for c in caches]),
                jnp.stack([c.v for c in caches]),
                jnp.stack([c.k_scale for c in caches]),
                jnp.stack([c.v_scale for c in caches]))
    return (jnp.stack([c.k for c in caches]),
            jnp.stack([c.v for c in caches]))


def suffix_prefill_mask(bucket, store, shared_len, length,
                        dtype="float32"):
    """Additive ``[1, 1, P, store]`` mask for a SUFFIX prefill: the
    bucket's queries sit at absolute positions ``shared_len + t`` over
    a cache whose first ``shared_len`` entries are reused prefix pages
    and whose suffix entries this forward writes. Query ``t`` keeps
    entry ``j`` iff causal (``j <= shared_len + t``) and real
    (``j < shared_len + length`` — bucket padding past the true suffix
    writes garbage that must never be attended). ``shared_len == 0``
    reduces exactly to ``cache.prefill_mask`` — full and suffix prefill
    are ONE compiled program per bucket."""
    t = jnp.arange(int(bucket))[:, None]
    j = jnp.arange(int(store))[None, :]
    keep = (j <= shared_len + t) & (j < shared_len + length)
    return jnp.where(keep, 0.0, NEG_INF).astype(dtype)[None, None]


class PagePool:
    """Host-side allocator over the shared page pool: LIFO free list,
    per-page refcounts, and the alloc/retain/release/CoW bookkeeping
    the engine runs between compiled steps. Page ids are POOL indices
    (1-based; 0 is :data:`TRASH_PAGE`). The device arrays live in the
    engine's cache pytree — this object never touches them."""

    def __init__(self, pages, page_size):
        if int(pages) < 1:
            raise InvalidArgumentError(
                f"a page pool needs at least 1 usable page, got {pages}")
        self.pages = int(pages)
        self.page_size = int(page_size)
        # ref[0] (trash) stays 0 forever; LIFO free list for locality
        self.ref = np.zeros(self.pages + 1, np.int64)
        self._free = list(range(self.pages, 0, -1))
        self.peak_used = 0
        self.cow_copies = 0

    def free_pages(self) -> int:
        return len(self._free)

    def used_pages(self) -> int:
        return self.pages - len(self._free)

    def shared_pages(self) -> int:
        """Pages mapped by more than one holder (slots and/or the
        prefix index) — the copy-on-write exposure."""
        return int(np.sum(self.ref > 1))

    def alloc(self):
        """One free page at refcount 1, or ``None`` when exhausted
        (the caller decides whether to evict or refuse)."""
        if not self._free:
            return None
        pid = self._free.pop()
        self.ref[pid] = 1
        self.peak_used = max(self.peak_used, self.used_pages())
        return pid

    def retain(self, pid):
        """One more holder of ``pid`` (a slot mapping a shared prefix
        page, or the index registering it)."""
        if pid == TRASH_PAGE:
            raise InvalidArgumentError("the trash page cannot be retained")
        if self.ref[pid] <= 0:
            raise InvalidArgumentError(
                f"page {pid} is free; retain() needs a live page")
        self.ref[pid] += 1

    def release(self, pid) -> bool:
        """Drop one holder; returns True when the page went back to the
        free list."""
        if pid == TRASH_PAGE:
            return False
        if self.ref[pid] <= 0:
            raise InvalidArgumentError(
                f"page {pid} released below refcount 0 (double free)")
        self.ref[pid] -= 1
        if self.ref[pid] == 0:
            self._free.append(pid)
            return True
        return False


class _Node:
    __slots__ = ("hash", "page", "parent", "children", "clock")

    def __init__(self, h, page, parent):
        self.hash = h
        self.page = page
        self.parent = parent
        self.children = {}
        self.clock = 0


class PrefixIndex:
    """Radix trie over page chain-hashes -> pool pages.

    Each node is one FULL page of some previously admitted prompt;
    because hashes chain (:func:`chain_hashes`), a root-to-node path is
    exactly a shared full-page prefix. The index RETAINS every page it
    registers, so prefix pages outlive the slot that wrote them (the
    fleet-prefix-cache behavior); :meth:`evict` drops least-recently-
    matched leaves whose page no slot maps, returning those pages to
    the free list when the pool runs dry.
    """

    def __init__(self, pool):
        self._pool = pool
        self._roots = {}
        self._clock = 0
        self.lookups = 0
        self.hits = 0
        self.pages = 0          # nodes (= pages) registered
        self.evictions = 0

    def _walk(self, hashes):
        nodes, children = [], self._roots
        for h in hashes:
            node = children.get(h)
            if node is None:
                break
            nodes.append(node)
            children = node.children
        return nodes

    def match(self, hashes):
        """Pool pages of the longest indexed prefix of ``hashes``
        (possibly empty). Touches the path for LRU and counts the
        lookup as a hit when at least one page matched."""
        nodes = self._walk(hashes)
        self._clock += 1
        for node in nodes:
            node.clock = self._clock
        self.lookups += 1
        if nodes:
            self.hits += 1
        return [node.page for node in nodes]

    def known(self, hashes):
        """The prefix of ``hashes`` this index holds, as a set — the
        handoff negotiation primitive (ship only unknown pages)."""
        return {node.hash for node in self._walk(hashes)}

    def insert(self, hashes, pages):
        """Register a prompt's full-page chain. Existing nodes are
        reused (their pages are canonical for that content); each NEW
        node retains its page — the index's own reference."""
        children, parent = self._roots, None
        self._clock += 1
        for h, page in zip(hashes, pages):
            node = children.get(h)
            if node is None:
                page = int(page)
                if page == TRASH_PAGE:
                    raise InvalidArgumentError(
                        "cannot index the trash page as prefix content")
                node = _Node(h, page, parent)
                self._pool.retain(page)
                children[h] = node
                self.pages += 1
            node.clock = self._clock
            children, parent = node.children, node

    def evictable(self) -> int:
        """Pages eviction could currently free: leaf nodes whose page
        has no holder beyond the index itself."""
        return sum(1 for node in self._iter_nodes()
                   if not node.children and self._pool.ref[node.page] == 1)

    def _iter_nodes(self):
        stack = list(self._roots.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def evict(self, need) -> int:
        """Drop LRU leaves whose pages only the index holds until
        ``need`` pages went back to the free list (or nothing evictable
        remains). Returns the count actually freed."""
        freed = 0
        while freed < int(need):
            victim = None
            for node in self._iter_nodes():
                if node.children or self._pool.ref[node.page] != 1:
                    continue
                if victim is None or node.clock < victim.clock:
                    victim = node
            if victim is None:
                break
            siblings = (victim.parent.children if victim.parent is not None
                        else self._roots)
            del siblings[victim.hash]
            self._pool.release(victim.page)
            self.pages -= 1
            self.evictions += 1
            freed += 1
        return freed

    def forget_page(self, page) -> int:
        """Drop ``page`` (and its whole subtree — descendants are
        unreachable without it) from the index, releasing the index's
        reference on every forgotten page. The memory-pressure valve:
        when a slot's ring wraps into a page the index pins and the
        pool cannot supply a CoW copy, forgetting the chain lets the
        slot write in place. Returns the number of nodes dropped."""
        page = int(page)
        victim = next((n for n in self._iter_nodes() if n.page == page),
                      None)
        if victim is None:
            return 0
        siblings = (victim.parent.children if victim.parent is not None
                    else self._roots)
        del siblings[victim.hash]
        dropped = 0
        stack = [victim]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self._pool.release(node.page)
            self.pages -= 1
            self.evictions += 1
            dropped += 1
        return dropped

    def stats(self) -> dict:
        return {
            "pages": self.pages,
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_rate": round(self.hits / self.lookups, 4)
            if self.lookups else None,
            "evictions": self.evictions,
        }
