"""Generative (autoregressive) inference subsystem.

Turns a causal LM into a compile-bound token stream:

- :mod:`generation.cache` — static-shape ring KV cache pytree + the
  causal/cache mask composition (O(1) memory per sequence, functional
  index-update writes so decode shapes never change).
- :mod:`generation.sampling` — greedy / temperature / top-k sampling,
  pure jnp (traced into the compiled steps), plus the shared eager
  ``decode_loop`` the seq2seq model delegates to.
- :mod:`generation.engine` — :class:`GenerationEngine`: prefill padded
  to a sequence-length bucket ladder, ONE jitted decode step for every
  slot, warmup + compile accounting (``generation::compile`` /
  ``extra_compiles() == 0`` in steady state).
- :mod:`generation.paging` — the paged KV layout
  (``FLAGS_kv_cache_layout=paged``): a fixed-size-page pool shared by
  every slot, per-slot page tables the attention gathers through, a
  refcounted free list with copy-on-write sharing, and a radix prefix
  index over page content hashes so requests sharing a templated
  prompt map its pages instead of re-prefilling them.

Continuous batching over the engine (slot turnover mid-batch, HTTP
``/generate``) lives in :mod:`paddle_tpu.serving.continuous` /
:class:`paddle_tpu.serving.GenerationServer`.

Quickstart::

    from paddle_tpu.models import GPTForCausalLM, gpt_tiny_config
    from paddle_tpu.generation import GenerationEngine

    engine = GenerationEngine(GPTForCausalLM(gpt_tiny_config()),
                              slots=4, cache_len=64).warmup()
    tokens = engine.generate([[5, 6, 7]], max_new_tokens=16)[0]
"""
from __future__ import annotations

from ..nn.transformer import (  # noqa: F401
    PagedStaticCache,
    QuantizedPagedCache,
    QuantizedStaticCache,
    StaticCache,
    causal_mask,
)
from .cache import (  # noqa: F401
    cache_nbytes,
    decode_mask,
    init_cache,
    insert_slot,
    insert_slot_kv,
    kv_bytes_per_token,
    layer_caches,
    prefill_mask,
    stack_layer_caches,
)
from .cache import pad_slot_arrays, verify_mask  # noqa: F401
from .engine import COMPILE_COUNTER, GenerationEngine  # noqa: F401
from .handoff import (  # noqa: F401
    HANDOFF_CONTENT_TYPE,
    HANDOFF_PAGED_CONTENT_TYPE,
    HandoffError,
    PageSlab,
    pack_kv_pages,
    pack_kv_slab,
    unpack_kv_pages,
    unpack_kv_slab,
)
from .paging import (  # noqa: F401
    PagePool,
    PagePoolExhaustedError,
    PrefixIndex,
    TRASH_PAGE,
    chain_hashes,
    init_paged_cache,
    page_nbytes,
    split_planes,
)
from .sampling import decode_loop, sample_logits, top_k_filter  # noqa: F401

__all__ = [
    "GenerationEngine", "COMPILE_COUNTER", "StaticCache",
    "QuantizedStaticCache", "PagedStaticCache", "QuantizedPagedCache",
    "causal_mask",
    "sample_logits", "top_k_filter", "decode_loop",
    "init_cache", "layer_caches", "stack_layer_caches", "insert_slot",
    "insert_slot_kv", "cache_nbytes", "kv_bytes_per_token",
    "decode_mask", "prefill_mask", "verify_mask", "pad_slot_arrays",
    "HandoffError", "pack_kv_slab", "unpack_kv_slab",
    "pack_kv_pages", "unpack_kv_pages", "PageSlab",
    "HANDOFF_CONTENT_TYPE", "HANDOFF_PAGED_CONTENT_TYPE",
    "PagePool", "PagePoolExhaustedError", "PrefixIndex", "TRASH_PAGE",
    "chain_hashes", "init_paged_cache", "page_nbytes", "split_planes",
]
