"""KV-slab wire format for disaggregated prefill -> decode handoff.

A prefill-tier backend runs the bucket-ladder forward and ships the
admitted slot's KV planes to a decode-tier backend over the existing
backend HTTP channel (``POST /generate_kv``, octet-stream body). The
slab is self-describing and paranoid:

``PTKV | version u16 | header_len u32 | header JSON | payload | crc32``

- the header names every plane's shape/dtype plus the cache geometry
  (layers/heads/head_dim/cache_len/kv dtype) and the generation
  parameters riding along (first token, prompt length, max_new_tokens,
  temperature, stream, and — for speculative decode tiers — the prompt
  tokens themselves, since the slab is target-model-only);
- the payload is the planes' raw bytes back to back, C-order;
- the trailing CRC32 covers header + payload, so a truncated or
  corrupted body is REJECTED at unpack (:class:`HandoffError` -> HTTP
  400), never half-inserted into a decode slot.

Both cache modes serialize: fp32 slabs carry 2 planes (k, v — each
``[L, H, C, D]``), int8 slabs carry 4 (int8 k/v + f32 per-head scale
planes ``[L, H, C]``, the :class:`nn.QuantizedStaticCache` layout from
the quantization PR). The decode tier validates arity and geometry
against its OWN engine before ``insert_slot_kv`` commits anything.

Page-granular transfer (the paged-KV subsystem) speaks a sibling
format, magic ``PTKP``: the same framing, but the payload is a LIST of
fixed-size KV pages, each independently described and content-hashed.
A sender that first asked the receiver which chain hashes it already
holds (``GenerationEngine.known_page_hashes``) marks those pages
``present: false`` and ships no payload for them — the receiver maps
them copy-on-write out of its own prefix index, so a fleet of decode
backends doubles as a distributed prefix cache and the wire carries
only pages the far side is missing.
"""
from __future__ import annotations

import hashlib
import json
import struct
import zlib
from typing import NamedTuple

import numpy as np

from ..errors import InvalidArgumentError

__all__ = ["HandoffError", "pack_kv_slab", "unpack_kv_slab",
           "PageSlab", "pack_kv_pages", "unpack_kv_pages",
           "HANDOFF_CONTENT_TYPE", "HANDOFF_PAGED_CONTENT_TYPE"]

_MAGIC = b"PTKV"
_MAGIC_PAGED = b"PTKP"
_VERSION = 1
_HEAD = struct.Struct(">4sHI")  # magic, version, header_len
_CRC = struct.Struct(">I")
_MAX_REFCOUNT = 1 << 31

#: the /generate_kv request body content type
HANDOFF_CONTENT_TYPE = "application/x-ptpu-kv-slab"


class HandoffError(InvalidArgumentError):
    """The slab failed validation (truncated, corrupt, or the wrong
    geometry for the receiving engine). Maps to HTTP 400 — the payload
    is unusable, retrying elsewhere cannot help."""


def pack_kv_slab(planes, length, first_token, meta=None) -> bytes:
    """Serialize one slot's KV planes plus riding metadata.

    ``planes`` are the window-width per-slot arrays from
    ``GenerationEngine.prefill_export`` (jax or numpy; 2 for fp32, 4
    for int8). ``length`` is the true prompt length, ``first_token``
    the prefill tier's sampled token. ``meta`` is an arbitrary
    JSON-able dict (generation params, cache geometry).
    """
    arrs = [np.ascontiguousarray(np.asarray(p)) for p in planes]
    header = {
        "planes": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                   for a in arrs],
        "length": int(length),
        "first_token": int(first_token),
        "meta": dict(meta or {}),
    }
    hbytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    payload = b"".join(a.tobytes() for a in arrs)
    body = _HEAD.pack(_MAGIC, _VERSION, len(hbytes)) + hbytes + payload
    return body + _CRC.pack(zlib.crc32(body) & 0xFFFFFFFF)


def unpack_kv_slab(data: bytes):
    """Parse and VALIDATE a slab: returns ``(planes, length,
    first_token, meta)`` with planes as numpy arrays. Raises
    :class:`HandoffError` on any structural problem — magic, version,
    size arithmetic, or CRC mismatch (truncation and corruption both
    land here)."""
    if len(data) < _HEAD.size + _CRC.size:
        raise HandoffError(
            f"KV slab truncated: {len(data)} bytes is smaller than the "
            "fixed framing")
    magic, version, hlen = _HEAD.unpack_from(data, 0)
    if magic != _MAGIC:
        raise HandoffError("not a KV slab (bad magic)")
    if version != _VERSION:
        raise HandoffError(
            f"KV slab version {version} unsupported (this build speaks "
            f"{_VERSION})")
    body, crc_bytes = data[:-_CRC.size], data[-_CRC.size:]
    (crc,) = _CRC.unpack(crc_bytes)
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise HandoffError(
            "KV slab checksum mismatch (truncated or corrupted payload)")
    if _HEAD.size + hlen > len(body):
        raise HandoffError("KV slab header overruns the payload")
    try:
        header = json.loads(body[_HEAD.size:_HEAD.size + hlen])
        specs = header["planes"]
        length = int(header["length"])
        first_token = int(header["first_token"])
        meta = dict(header.get("meta") or {})
    except (ValueError, KeyError, TypeError) as e:
        raise HandoffError(f"KV slab header malformed: {e}") from None
    off = _HEAD.size + hlen
    planes = []
    for spec in specs:
        try:
            shape = tuple(int(d) for d in spec["shape"])
            dtype = np.dtype(spec["dtype"])
        except (ValueError, KeyError, TypeError) as e:
            raise HandoffError(
                f"KV slab plane spec malformed: {e}") from None
        if dtype.kind not in "fiu" or any(d < 0 for d in shape):
            # only numeric planes can come off a wire buffer — an
            # "object" dtype (CRC-valid header, hostile or buggy
            # sender) would crash frombuffer with a raw ValueError
            # instead of the 400 this module promises
            raise HandoffError(
                f"KV slab plane spec invalid: dtype {dtype}, "
                f"shape {shape}")
        n = int(np.prod(shape)) * dtype.itemsize
        if off + n > len(body):
            raise HandoffError(
                "KV slab payload shorter than its plane specs")
        try:
            planes.append(np.frombuffer(body, dtype=dtype, count=int(
                np.prod(shape)), offset=off).reshape(shape))
        except (ValueError, TypeError) as e:
            raise HandoffError(
                f"KV slab plane unreadable: {e}") from None
        off += n
    if off != len(body):
        raise HandoffError(
            f"KV slab carries {len(body) - off} trailing bytes beyond "
            "its plane specs")
    return tuple(planes), length, first_token, meta


# -- page-granular format (PTKP) ----------------------------------------------

#: the /generate_kv request body content type for page-granular slabs
HANDOFF_PAGED_CONTENT_TYPE = "application/x-ptpu-kv-pages"


class PageSlab(NamedTuple):
    """A parsed page-granular handoff: ``pages`` is a list of dicts
    ``{"id", "hash", "planes"}`` in page order — ``planes`` is the
    page's per-plane array tuple, or ``None`` for a page the sender
    knows the receiver already holds (resolved through its prefix
    index); ``hash`` is the page's CHAIN hash (None for the partial
    tail page, which can never be shared)."""

    pages: list
    length: int
    first_token: int
    page_size: int
    meta: dict


def pack_kv_pages(pages, length, first_token, page_size,
                  meta=None) -> bytes:
    """Serialize a page-granular handoff.

    ``pages`` come from ``GenerationEngine.prefill_export_pages``: a
    list of ``{"id", "hash", "planes"}`` dicts in page order, where
    ``planes is None`` marks a page the receiver already holds (it is
    shipped header-only). Each present page's payload is individually
    SHA-256'd so a flipped bit names the page it corrupted; an optional
    ``"refcount"`` per page (the sender's share count, advisory for
    cache peers) is range-checked on both ends.
    """
    specs, chunks = [], []
    for page in pages:
        planes = page.get("planes")
        rc = int(page.get("refcount", 1))
        if not 0 <= rc < _MAX_REFCOUNT:
            raise HandoffError(
                f"page {page.get('id')} refcount {rc} outside "
                f"[0, {_MAX_REFCOUNT})")
        entry = {"id": int(page["id"]), "hash": page.get("hash"),
                 "refcount": rc}
        if planes is None:
            entry["present"] = False
            entry["planes"] = None
            entry["payload_sha"] = None
        else:
            arrs = [np.ascontiguousarray(np.asarray(p)) for p in planes]
            raw = b"".join(a.tobytes() for a in arrs)
            entry["present"] = True
            entry["planes"] = [
                {"shape": list(a.shape), "dtype": str(a.dtype)}
                for a in arrs]
            entry["payload_sha"] = hashlib.sha256(raw).hexdigest()
            chunks.append(raw)
        specs.append(entry)
    header = {
        "page_size": int(page_size),
        "length": int(length),
        "first_token": int(first_token),
        "pages": specs,
        "meta": dict(meta or {}),
    }
    hbytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    body = (_HEAD.pack(_MAGIC_PAGED, _VERSION, len(hbytes)) + hbytes
            + b"".join(chunks))
    return body + _CRC.pack(zlib.crc32(body) & 0xFFFFFFFF)


def unpack_kv_pages(data: bytes) -> PageSlab:
    """Parse and VALIDATE a page-granular slab. Every structural
    problem raises :class:`HandoffError` (-> HTTP 400) BEFORE anything
    could land in a decode slot: bad magic/version/CRC, a page list
    that does not cover ``length`` (truncated page list), duplicate
    page ids, a refcount outside ``[0, 2^31)`` (overflow), or a page
    whose payload bytes do not hash to its declared ``payload_sha``
    (bit-flip localized to the page)."""
    if len(data) < _HEAD.size + _CRC.size:
        raise HandoffError(
            f"KV page slab truncated: {len(data)} bytes is smaller "
            "than the fixed framing")
    magic, version, hlen = _HEAD.unpack_from(data, 0)
    if magic != _MAGIC_PAGED:
        raise HandoffError("not a KV page slab (bad magic)")
    if version != _VERSION:
        raise HandoffError(
            f"KV page slab version {version} unsupported (this build "
            f"speaks {_VERSION})")
    body, crc_bytes = data[:-_CRC.size], data[-_CRC.size:]
    (crc,) = _CRC.unpack(crc_bytes)
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise HandoffError(
            "KV page slab checksum mismatch (truncated or corrupted "
            "payload)")
    if _HEAD.size + hlen > len(body):
        raise HandoffError("KV page slab header overruns the payload")
    try:
        header = json.loads(body[_HEAD.size:_HEAD.size + hlen])
        page_size = int(header["page_size"])
        length = int(header["length"])
        first_token = int(header["first_token"])
        specs = list(header["pages"])
        meta = dict(header.get("meta") or {})
    except (ValueError, KeyError, TypeError) as e:
        raise HandoffError(
            f"KV page slab header malformed: {e}") from None
    if page_size < 1 or length < 1:
        raise HandoffError(
            f"KV page slab geometry invalid: page_size {page_size}, "
            f"length {length}")
    npages = -(-length // page_size)
    if len(specs) != npages:
        raise HandoffError(
            f"KV page slab page list truncated: {len(specs)} pages "
            f"cannot cover length {length} at page size {page_size} "
            f"({npages} needed)")
    ids = [s.get("id") for s in specs]
    if len(set(ids)) != len(ids):
        raise HandoffError("KV page slab carries duplicate page ids")
    off = _HEAD.size + hlen
    pages = []
    for spec in specs:
        try:
            pid = int(spec["id"])
            present = bool(spec["present"])
            rc = int(spec.get("refcount", 1))
            page_hash = spec.get("hash")
        except (ValueError, KeyError, TypeError) as e:
            raise HandoffError(
                f"KV page slab page spec malformed: {e}") from None
        if not 0 <= rc < _MAX_REFCOUNT:
            raise HandoffError(
                f"page {pid} refcount {rc} overflows [0, "
                f"{_MAX_REFCOUNT})")
        if not present:
            if page_hash is None:
                raise HandoffError(
                    f"page {pid} is absent from the payload but names "
                    "no hash to resolve it by")
            pages.append({"id": pid, "hash": page_hash, "planes": None})
            continue
        plane_specs = spec.get("planes")
        if not plane_specs:
            raise HandoffError(
                f"page {pid} is marked present but names no planes")
        start = off
        planes = []
        for pspec in plane_specs:
            try:
                shape = tuple(int(d) for d in pspec["shape"])
                dtype = np.dtype(pspec["dtype"])
            except (ValueError, KeyError, TypeError) as e:
                raise HandoffError(
                    f"page {pid} plane spec malformed: {e}") from None
            if dtype.kind not in "fiu" or any(d < 0 for d in shape):
                raise HandoffError(
                    f"page {pid} plane spec invalid: dtype {dtype}, "
                    f"shape {shape}")
            n = int(np.prod(shape)) * dtype.itemsize
            if off + n > len(body):
                raise HandoffError(
                    f"KV page slab payload ends inside page {pid}")
            planes.append(np.frombuffer(
                body, dtype=dtype, count=int(np.prod(shape)),
                offset=off).reshape(shape))
            off += n
        want = spec.get("payload_sha")
        got = hashlib.sha256(body[start:off]).hexdigest()
        if want != got:
            raise HandoffError(
                f"page {pid} payload hash mismatch (corrupted in "
                "flight); refusing the whole slab")
        pages.append({"id": pid, "hash": page_hash,
                      "planes": tuple(planes)})
    if off != len(body):
        raise HandoffError(
            f"KV page slab carries {len(body) - off} trailing bytes "
            "beyond its page specs")
    return PageSlab(pages, length, first_token, page_size, meta)
