"""KV-slab wire format for disaggregated prefill -> decode handoff.

A prefill-tier backend runs the bucket-ladder forward and ships the
admitted slot's KV planes to a decode-tier backend over the existing
backend HTTP channel (``POST /generate_kv``, octet-stream body). The
slab is self-describing and paranoid:

``PTKV | version u16 | header_len u32 | header JSON | payload | crc32``

- the header names every plane's shape/dtype plus the cache geometry
  (layers/heads/head_dim/cache_len/kv dtype) and the generation
  parameters riding along (first token, prompt length, max_new_tokens,
  temperature, stream, and — for speculative decode tiers — the prompt
  tokens themselves, since the slab is target-model-only);
- the payload is the planes' raw bytes back to back, C-order;
- the trailing CRC32 covers header + payload, so a truncated or
  corrupted body is REJECTED at unpack (:class:`HandoffError` -> HTTP
  400), never half-inserted into a decode slot.

Both cache modes serialize: fp32 slabs carry 2 planes (k, v — each
``[L, H, C, D]``), int8 slabs carry 4 (int8 k/v + f32 per-head scale
planes ``[L, H, C]``, the :class:`nn.QuantizedStaticCache` layout from
the quantization PR). The decode tier validates arity and geometry
against its OWN engine before ``insert_slot_kv`` commits anything.
"""
from __future__ import annotations

import json
import struct
import zlib

import numpy as np

from ..errors import InvalidArgumentError

__all__ = ["HandoffError", "pack_kv_slab", "unpack_kv_slab",
           "HANDOFF_CONTENT_TYPE"]

_MAGIC = b"PTKV"
_VERSION = 1
_HEAD = struct.Struct(">4sHI")  # magic, version, header_len
_CRC = struct.Struct(">I")

#: the /generate_kv request body content type
HANDOFF_CONTENT_TYPE = "application/x-ptpu-kv-slab"


class HandoffError(InvalidArgumentError):
    """The slab failed validation (truncated, corrupt, or the wrong
    geometry for the receiving engine). Maps to HTTP 400 — the payload
    is unusable, retrying elsewhere cannot help."""


def pack_kv_slab(planes, length, first_token, meta=None) -> bytes:
    """Serialize one slot's KV planes plus riding metadata.

    ``planes`` are the window-width per-slot arrays from
    ``GenerationEngine.prefill_export`` (jax or numpy; 2 for fp32, 4
    for int8). ``length`` is the true prompt length, ``first_token``
    the prefill tier's sampled token. ``meta`` is an arbitrary
    JSON-able dict (generation params, cache geometry).
    """
    arrs = [np.ascontiguousarray(np.asarray(p)) for p in planes]
    header = {
        "planes": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                   for a in arrs],
        "length": int(length),
        "first_token": int(first_token),
        "meta": dict(meta or {}),
    }
    hbytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    payload = b"".join(a.tobytes() for a in arrs)
    body = _HEAD.pack(_MAGIC, _VERSION, len(hbytes)) + hbytes + payload
    return body + _CRC.pack(zlib.crc32(body) & 0xFFFFFFFF)


def unpack_kv_slab(data: bytes):
    """Parse and VALIDATE a slab: returns ``(planes, length,
    first_token, meta)`` with planes as numpy arrays. Raises
    :class:`HandoffError` on any structural problem — magic, version,
    size arithmetic, or CRC mismatch (truncation and corruption both
    land here)."""
    if len(data) < _HEAD.size + _CRC.size:
        raise HandoffError(
            f"KV slab truncated: {len(data)} bytes is smaller than the "
            "fixed framing")
    magic, version, hlen = _HEAD.unpack_from(data, 0)
    if magic != _MAGIC:
        raise HandoffError("not a KV slab (bad magic)")
    if version != _VERSION:
        raise HandoffError(
            f"KV slab version {version} unsupported (this build speaks "
            f"{_VERSION})")
    body, crc_bytes = data[:-_CRC.size], data[-_CRC.size:]
    (crc,) = _CRC.unpack(crc_bytes)
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise HandoffError(
            "KV slab checksum mismatch (truncated or corrupted payload)")
    if _HEAD.size + hlen > len(body):
        raise HandoffError("KV slab header overruns the payload")
    try:
        header = json.loads(body[_HEAD.size:_HEAD.size + hlen])
        specs = header["planes"]
        length = int(header["length"])
        first_token = int(header["first_token"])
        meta = dict(header.get("meta") or {})
    except (ValueError, KeyError, TypeError) as e:
        raise HandoffError(f"KV slab header malformed: {e}") from None
    off = _HEAD.size + hlen
    planes = []
    for spec in specs:
        try:
            shape = tuple(int(d) for d in spec["shape"])
            dtype = np.dtype(spec["dtype"])
        except (ValueError, KeyError, TypeError) as e:
            raise HandoffError(
                f"KV slab plane spec malformed: {e}") from None
        if dtype.kind not in "fiu" or any(d < 0 for d in shape):
            # only numeric planes can come off a wire buffer — an
            # "object" dtype (CRC-valid header, hostile or buggy
            # sender) would crash frombuffer with a raw ValueError
            # instead of the 400 this module promises
            raise HandoffError(
                f"KV slab plane spec invalid: dtype {dtype}, "
                f"shape {shape}")
        n = int(np.prod(shape)) * dtype.itemsize
        if off + n > len(body):
            raise HandoffError(
                "KV slab payload shorter than its plane specs")
        try:
            planes.append(np.frombuffer(body, dtype=dtype, count=int(
                np.prod(shape)), offset=off).reshape(shape))
        except (ValueError, TypeError) as e:
            raise HandoffError(
                f"KV slab plane unreadable: {e}") from None
        off += n
    if off != len(body):
        raise HandoffError(
            f"KV slab carries {len(body) - off} trailing bytes beyond "
            "its plane specs")
    return tuple(planes), length, first_token, meta
