"""Static-shape ring KV cache plumbing for compile-once decoding.

The per-layer cache itself is :class:`paddle_tpu.nn.StaticCache`
(``nn/transformer.py``): fixed ``[B, H, C, D]`` K/V arrays written by
functional index updates, ring-wrapping at capacity ``C``. This module
holds the ENGINE-side pieces — the stacked whole-model cache pytree and
the mask composition that makes the static window numerically exact:

- an all-layers cache is a ``[L, B, H, C, D]`` pair plus one shared
  ``pos [B]`` vector, so slot-level operations (insert a prefilled
  sequence, reset a vacated slot) are single indexed updates;
- ``decode_mask``/``prefill_mask`` compose the causal constraint with
  cache validity (entries beyond ``pos`` are zeros, never attended) into
  one additive mask per step. Because the ring keeps exactly the last
  ``C`` tokens, decoding with the cache equals a FULL forward under a
  sliding window of width ``C`` (``nn.causal_mask(T, window=C)``) —
  the parity contract the goldens in tests/test_generation.py pin,
  including wraparound past the window.

Everything here is shape-static: the same jitted program serves every
sequence length, so steady-state generation is compile-bound at
1 decode compile + one prefill compile per ladder bucket.

**Store vs window** (speculative decoding): the physical ring STORE may
be wider than the attention WINDOW. A speculative verify step writes
``k+1`` new entries before attending; with ``store == window`` those
writes would clobber ring entries still inside an early query's
sliding window once the ring has wrapped. With ``store >= window + k``
a write at position ``p`` clobbers position ``p - store <= p - window -
k``, which no query of the round can still attend — so in-place ring
writes stay exact. The masks therefore take the physical ``store``
width and an optional logical ``window`` (default: the store itself,
the historical behavior), and :func:`verify_mask` composes causality
across the ``k+1`` in-flight positions with the window constraint.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..nn.transformer import QuantizedStaticCache, StaticCache

__all__ = [
    "init_cache", "layer_caches", "stack_layer_caches", "insert_slot",
    "insert_slot_kv", "fresh_layer_caches", "cache_nbytes",
    "kv_bytes_per_token", "decode_mask", "prefill_mask", "verify_mask",
    "pad_slot_arrays",
]

NEG_INF = -1e9

#: storage dtypes the KV cache supports (FLAGS_generation_kv_cache_dtype)
KV_CACHE_DTYPES = ("float32", "int8")


def init_cache(num_layers, batch, num_heads, cache_len, head_dim,
               dtype="float32"):
    """Zeroed whole-model cache.

    ``dtype="float32"``: ``(k [L,B,H,C,D], v [...], pos [B])`` — the
    historical 3-tuple. ``dtype="int8"``: a 5-tuple that additionally
    carries the per-head dynamic scale planes ``(k, v, k_scale
    [L,B,H,C], v_scale [...], pos)`` with int8 K/V storage
    (:class:`nn.QuantizedStaticCache` per layer). Every helper below
    dispatches on the tuple arity, so engine code is dtype-agnostic.
    """
    shape = (int(num_layers), int(batch), int(num_heads), int(cache_len),
             int(head_dim))
    pos = jnp.zeros((int(batch),), jnp.int32)
    if str(dtype) == "int8":
        return (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                jnp.zeros(shape[:-1], jnp.float32),
                jnp.zeros(shape[:-1], jnp.float32), pos)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype), pos


def layer_caches(*kv):
    """Slice the stacked cache into per-layer views (``pos`` is shared —
    every layer writes the same step): :class:`StaticCache` for the
    3-tuple form, :class:`nn.QuantizedStaticCache` for the 5-tuple."""
    if len(kv) == 1:  # whole-cache tuple passed as one argument
        kv = tuple(kv[0])
    pos, arrays = kv[-1], kv[:-1]
    cls = StaticCache if len(arrays) == 2 else QuantizedStaticCache
    return [cls(*(a[i] for a in arrays), pos)
            for i in range(arrays[0].shape[0])]


def stack_layer_caches(caches):
    """Re-stack per-layer caches returned by the model into the
    whole-model arrays: ``(k, v)`` for :class:`StaticCache` layers,
    ``(k, v, k_scale, v_scale)`` for quantized ones."""
    if isinstance(caches[0], QuantizedStaticCache):
        return (jnp.stack([c.k for c in caches]),
                jnp.stack([c.v for c in caches]),
                jnp.stack([c.k_scale for c in caches]),
                jnp.stack([c.v_scale for c in caches]))
    return (jnp.stack([c.k for c in caches]),
            jnp.stack([c.v for c in caches]))


def fresh_layer_caches(num_layers, batch, num_heads, cache_len, head_dim,
                       dtype="float32"):
    """Zeroed per-layer cache list for a prefill forward (the engine
    prefills ONE sequence into fresh caches, then installs the result
    into the admitted slot)."""
    return layer_caches(*init_cache(num_layers, batch, num_heads,
                                    cache_len, head_dim, dtype))


def insert_slot(ck, cv, pos, slot, new_k, new_v, length):
    """Install one prefilled sequence (``new_k/new_v [L, H, C, D]``)
    into decode slot ``slot`` and set its position to ``length`` — the
    admission write of continuous batching, a functional indexed update
    so the batch program never recompiles when a slot turns over."""
    ck = ck.at[:, slot].set(new_k)
    cv = cv.at[:, slot].set(new_v)
    return ck, cv, pos.at[slot].set(length)


def insert_slot_kv(kv, slot, new_arrays, length):
    """Arity-generic :func:`insert_slot`: ``kv`` is the whole-model
    cache tuple (3 or 5 arrays, ``pos`` last) and ``new_arrays`` the
    matching per-slot planes (``[L, H, C, D]`` values, ``[L, H, C]``
    scales) from a prefill's :func:`stack_layer_caches`."""
    pos = kv[-1]
    updated = tuple(a.at[:, slot].set(n)
                    for a, n in zip(kv[:-1], new_arrays))
    return updated + (pos.at[slot].set(length),)


def cache_nbytes(kv) -> int:
    """Device bytes the whole-model cache occupies (values + scales +
    positions) — the numerator of the int8-vs-f32 HBM claim, measured
    on the REAL arrays rather than derived."""
    return int(sum(int(np.prod(a.shape)) * jnp.dtype(a.dtype).itemsize
                   for a in kv))


def kv_bytes_per_token(num_layers, num_heads, head_dim,
                       dtype="float32") -> int:
    """Cache bytes one decoded token occupies across all layers: K + V
    values (+ their scale entries at int8). The ``decode_throughput``
    bench row reports this per mode; slots-at-equal-HBM is its ratio."""
    per_vec = (int(head_dim) + 4 if str(dtype) == "int8"
               else int(head_dim) * 4)
    return 2 * int(num_layers) * int(num_heads) * per_vec


def decode_mask(pos, cache_len, window=None, dtype="float32"):
    """Additive ``[B, 1, 1, store]`` mask for one decode step.

    The step's query (absolute position ``pos``) may attend every cache
    entry already written INCLUDING itself and no further back than
    ``window`` positions. ``cache_len`` is the physical STORE width;
    ``window`` defaults to it (the historical store-equals-window
    behavior: entry count after the write is ``min(pos + 1, C)`` and a
    wrapped ring holds exactly the last ``C`` tokens). With a wider
    store (speculative decoding) entry ``j`` holds absolute position
    ``pos - ((pos - j) mod store)`` — kept iff that distance is inside
    the window and the entry was ever written.
    """
    store = int(cache_len)
    w = store if window is None else int(window)
    dd = jnp.mod(pos[:, None] - jnp.arange(store)[None, :], store)
    keep = (dd < w) & (dd <= pos[:, None])
    return jnp.where(keep, 0.0, NEG_INF).astype(dtype)[:, None, None, :]


def verify_mask(pos, cache_len, span, window=None, dtype="float32"):
    """Additive ``[B, 1, span, store]`` mask for a speculative verify
    step: ``span = k + 1`` queries at absolute positions ``pos .. pos +
    k``, attending a ring the forward has ALREADY written all ``span``
    new entries into.

    Query ``i`` keeps entry ``j`` iff the token it holds is causally
    visible (``dd <= pos + i``, which also hides the q > i in-flight
    writes: their ring distance is ``store - (q - i) >= window`` by the
    ``store >= window + k`` allocation) and inside the sliding window
    (``dd < window``). Row 0 of the span reduces exactly to
    :func:`decode_mask`.
    """
    store = int(cache_len)
    w = store if window is None else int(window)
    q = pos[:, None, None] + jnp.arange(int(span))[None, :, None]
    dd = jnp.mod(q - jnp.arange(store)[None, None, :], store)
    keep = (dd < w) & (dd <= q)
    return jnp.where(keep, 0.0, NEG_INF).astype(dtype)[:, None]


def pad_slot_arrays(arrays, store):
    """Zero-pad per-slot cache planes (``[L, H, C, D]`` values /
    ``[L, H, C]`` scales) from window width ``C`` up to a wider ring
    ``store`` along the cache axis — a prefill tier's KV slab (always
    window-wide) landing in a decode tier whose ring carries the
    speculative scratch margin. Entries past the prompt are never-
    written zeros on both sides, so padding is exact."""
    out = []
    for a in arrays:
        c = a.shape[2]
        if c > int(store):
            raise ValueError(
                f"slot plane cache axis {c} exceeds the target store "
                f"{store}")
        if c < int(store):
            pad = [(0, 0)] * a.ndim
            pad[2] = (0, int(store) - c)
            a = jnp.pad(a, pad)
        out.append(a)
    return tuple(out)


def prefill_mask(bucket, cache_len, length, dtype="float32"):
    """Additive ``[1, 1, P, C]`` mask for a bucketed prefill.

    Query ``t`` keeps cache entry ``j`` iff causal (``j <= t``) and the
    entry holds a REAL prompt token (``j < length`` — bucket padding
    beyond the true prompt writes garbage K/V that must never be
    attended; decode later overwrites those entries in ring order before
    each becomes valid). Padding QUERIES (``t >= length``) produce
    garbage logits the engine never reads.
    """
    t = jnp.arange(int(bucket))[:, None]
    j = jnp.arange(int(cache_len))[None, :]
    keep = (j <= t) & (j < length)
    return jnp.where(keep, 0.0, NEG_INF).astype(dtype)[None, None]
