"""Static-shape ring KV cache plumbing for compile-once decoding.

The per-layer cache itself is :class:`paddle_tpu.nn.StaticCache`
(``nn/transformer.py``): fixed ``[B, H, C, D]`` K/V arrays written by
functional index updates, ring-wrapping at capacity ``C``. This module
holds the ENGINE-side pieces — the stacked whole-model cache pytree and
the mask composition that makes the static window numerically exact:

- an all-layers cache is a ``[L, B, H, C, D]`` pair plus one shared
  ``pos [B]`` vector, so slot-level operations (insert a prefilled
  sequence, reset a vacated slot) are single indexed updates;
- ``decode_mask``/``prefill_mask`` compose the causal constraint with
  cache validity (entries beyond ``pos`` are zeros, never attended) into
  one additive mask per step. Because the ring keeps exactly the last
  ``C`` tokens, decoding with the cache equals a FULL forward under a
  sliding window of width ``C`` (``nn.causal_mask(T, window=C)``) —
  the parity contract the goldens in tests/test_generation.py pin,
  including wraparound past the window.

Everything here is shape-static: the same jitted program serves every
sequence length, so steady-state generation is compile-bound at
1 decode compile + one prefill compile per ladder bucket.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.transformer import StaticCache

__all__ = [
    "init_cache", "layer_caches", "stack_layer_caches", "insert_slot",
    "decode_mask", "prefill_mask",
]

NEG_INF = -1e9


def init_cache(num_layers, batch, num_heads, cache_len, head_dim,
               dtype="float32"):
    """Zeroed whole-model cache: ``(k [L,B,H,C,D], v [...], pos [B])``."""
    shape = (int(num_layers), int(batch), int(num_heads), int(cache_len),
             int(head_dim))
    return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
            jnp.zeros((int(batch),), jnp.int32))


def layer_caches(ck, cv, pos):
    """Slice the stacked cache into per-layer :class:`StaticCache` views
    (``pos`` is shared — every layer writes the same step)."""
    return [StaticCache(ck[i], cv[i], pos) for i in range(ck.shape[0])]


def stack_layer_caches(caches):
    """Re-stack per-layer caches returned by the model into the
    ``(k, v)`` whole-model arrays."""
    return (jnp.stack([c.k for c in caches]),
            jnp.stack([c.v for c in caches]))


def insert_slot(ck, cv, pos, slot, new_k, new_v, length):
    """Install one prefilled sequence (``new_k/new_v [L, H, C, D]``)
    into decode slot ``slot`` and set its position to ``length`` — the
    admission write of continuous batching, a functional indexed update
    so the batch program never recompiles when a slot turns over."""
    ck = ck.at[:, slot].set(new_k)
    cv = cv.at[:, slot].set(new_v)
    return ck, cv, pos.at[slot].set(length)


def decode_mask(pos, cache_len, dtype="float32"):
    """Additive ``[B, 1, 1, C]`` mask for one decode step.

    The step's query (absolute position ``pos``) may attend every cache
    entry already written INCLUDING itself — entry count after the write
    is ``min(pos + 1, C)``; once the ring has wrapped, all ``C`` entries
    are live and hold exactly the last ``C`` tokens (the sliding
    window).
    """
    c = int(cache_len)
    keep = jnp.arange(c)[None, :] < jnp.minimum(pos + 1, c)[:, None]
    return jnp.where(keep, 0.0, NEG_INF).astype(dtype)[:, None, None, :]


def prefill_mask(bucket, cache_len, length, dtype="float32"):
    """Additive ``[1, 1, P, C]`` mask for a bucketed prefill.

    Query ``t`` keeps cache entry ``j`` iff causal (``j <= t``) and the
    entry holds a REAL prompt token (``j < length`` — bucket padding
    beyond the true prompt writes garbage K/V that must never be
    attended; decode later overwrites those entries in ring order before
    each becomes valid). Padding QUERIES (``t >= length``) produce
    garbage logits the engine never reads.
    """
    t = jnp.arange(int(bucket))[:, None]
    j = jnp.arange(int(cache_len))[None, :]
    keep = (j <= t) & (j < length)
    return jnp.where(keep, 0.0, NEG_INF).astype(dtype)[None, None]
