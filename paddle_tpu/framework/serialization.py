"""paddle.save / paddle.load — object serialization.

Reference parity: python/paddle/fluid/dygraph/checkpoint.py (save_dygraph/
load_dygraph state dicts), fluid/io.py (save/load_persistables via
save_op/load_op, save_combine), framework/io/fs.cc (LocalFS).

Format: a single .npz-style archive per call (one file, like
save_combine_op) holding arrays + a pickled structure manifest. Sharded
jax arrays are gathered to host before writing (checkpointing of
distributed state is per-host in multi-host mode — orbax-style layouts
can be layered on later without changing this API).
"""
from __future__ import annotations

import io as _io
import os
import pickle

import numpy as np

__all__ = ["save", "load"]

_MAGIC = b"PTPU1\n"


def _to_host(obj):
    """Convert Tensors/jax arrays to numpy, recursively."""
    import jax

    from .tensor import Tensor

    if isinstance(obj, Tensor):
        return np.asarray(obj._array)
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v) for v in obj)
    return obj


def dumps(obj, protocol=4) -> bytes:
    """Checkpoint bytes (magic + payload) without touching disk — the
    buffer the encrypted-save path feeds straight into the cipher."""
    host = _to_host(obj)
    buf = _io.BytesIO()
    buf.write(_MAGIC)
    pickle.dump(host, buf, protocol=protocol)
    return buf.getvalue()


def loads(data: bytes, return_numpy=False):
    """Inverse of dumps."""
    if not data.startswith(_MAGIC):
        raise ValueError(
            f"not a paddle_tpu checkpoint (bad magic {data[:8]!r})"
        )
    obj = pickle.loads(data[len(_MAGIC):])
    return obj if return_numpy else _to_tensor(obj)


def save(obj, path, protocol=4):
    """Serialize a (nested) state dict / object to ``path``.

    Accepts what paddle.save accepts: Layer.state_dict(), optimizer
    state_dict(), nested dicts/lists of tensors and plain values.
    """
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(dumps(obj, protocol=protocol))


def _to_tensor(obj):
    """Wrap ndarray leaves back into (device-backed) Tensors, recursively."""
    import jax.numpy as jnp

    from .tensor import Tensor

    if isinstance(obj, np.ndarray):
        return Tensor._from_array(jnp.asarray(obj))
    if isinstance(obj, dict):
        return {k: _to_tensor(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_tensor(v) for v in obj)
    return obj


def load(path, return_numpy=False):
    """Load an object saved by ``save``.

    Matching paddle.load semantics: by default array leaves come back as
    Tensors; ``return_numpy=True`` keeps them as numpy arrays.
    """
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        if head != _MAGIC:
            raise ValueError(
                f"{path} is not a paddle_tpu checkpoint (bad magic {head!r})"
            )
        obj = pickle.load(f)
    if return_numpy:
        return obj
    return _to_tensor(obj)
