"""Eager autograd engine.

Reference parity: paddle/fluid/imperative/tracer.cc:46 (TraceOp: record a
grad node per executed op) and imperative/basic_engine.cc:161 (dependency-
counted reverse sweep). TPU-native design: instead of per-op hand-written
grad kernels, each executed op captures a `jax.vjp` closure of its pure JAX
kernel — gradients are exact by construction and trace cleanly under
`jax.jit` (the whole tape, forward and backward, composes into one XLA
module when run inside a functionalized train step; see framework/jit.py).
"""
from __future__ import annotations

import contextlib
import threading
from functools import partial

import jax
import numpy as np

_state = threading.local()


def _grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def is_grad_enabled() -> bool:
    return _grad_enabled()


@contextlib.contextmanager
def no_grad():
    prev = _grad_enabled()
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _grad_enabled()
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = prev


class GradNode:
    """One executed op on the tape."""

    __slots__ = (
        "op_type",
        "vjp_fn",
        "inputs",
        "out_avals",
        "out_grads",
        "weak_outputs",
    )

    def __init__(self, op_type, vjp_fn, inputs, out_avals):
        self.op_type = op_type
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list[Tensor] (strong refs keep graph alive)
        self.out_avals = out_avals  # list[(shape, dtype)]
        self.out_grads = [None] * len(out_avals)

    def release(self):
        self.vjp_fn = None
        self.inputs = ()
        self.out_grads = [None] * len(self.out_avals)


def _is_floating(dtype) -> bool:
    # complex counts: jax reverse-mode handles complex64/128 (Wirtinger
    # convention), matching the reference's ComplexVariable grads
    return jax.numpy.issubdtype(dtype, np.floating) or jax.numpy.issubdtype(
        dtype, np.complexfloating
    )


# AMP autocast hook (imperative/amp_auto_cast.cc equivalent): installed by
# paddle_tpu.amp; consulted on every eager op dispatch.
_amp_hook = None


def set_amp_hook(fn):
    global _amp_hook
    _amp_hook = fn


def apply_op(op_type, fn, tensors, attrs, num_outputs=None):
    """Execute a registered op kernel on Tensor inputs, recording a grad node.

    `fn(*arrays, **attrs)` must be a pure JAX function returning an array or
    a tuple of arrays. Returns a Tensor or tuple of Tensors.
    """
    from .tensor import Tensor  # circular-safe at call time

    arrays = [t._array for t in tensors]
    if _amp_hook is not None:
        arrays = _amp_hook(op_type, arrays)
    requires_grad = _grad_enabled() and any(
        (not t.stop_gradient) and _is_floating(t.dtype) for t in tensors
    )

    bound = partial(fn, **attrs) if attrs else fn
    if requires_grad:
        outs, vjp_fn = jax.vjp(bound, *arrays)
    else:
        outs = bound(*arrays)

    multi = isinstance(outs, (tuple, list))
    out_list = list(outs) if multi else [outs]

    # Only track if at least one output can carry gradient.
    if requires_grad and any(_is_floating(o.dtype) for o in out_list):
        node = GradNode(
            op_type,
            vjp_fn,
            list(tensors),
            [(o.shape, o.dtype) for o in out_list],
        )
        out_tensors = [
            Tensor._from_array(o, stop_gradient=not _is_floating(o.dtype))
            for o in out_list
        ]
        for i, t in enumerate(out_tensors):
            if not t.stop_gradient:
                t._node = node
                t._out_index = i
    else:
        out_tensors = [Tensor._from_array(o, stop_gradient=True) for o in out_list]

    return tuple(out_tensors) if multi else out_tensors[0]


def _zero_cotangent(shape, dtype):
    import jax.numpy as jnp

    if _is_floating(dtype):
        return jnp.zeros(shape, dtype)
    # Non-differentiable output: JAX expects a float0 cotangent.
    return np.zeros(shape, dtype=jax.dtypes.float0)


def backward(tensor, grad=None, retain_graph=False):
    """Reverse sweep from `tensor`, accumulating `.grad` on leaf tensors.

    Mirrors BasicEngine::Execute (imperative/basic_engine.cc:161): topological
    traversal with per-node pending-gradient accumulation.
    """
    import jax.numpy as jnp

    from .tensor import Tensor

    root_node = tensor._node
    if root_node is None:
        if not tensor.stop_gradient:
            seed = (
                grad._array if grad is not None else jnp.ones(tensor.shape, tensor.dtype)
            )
            _accumulate_leaf(tensor, seed)
        return

    if root_node.vjp_fn is None:
        raise RuntimeError(
            "trying to backward through the graph a second time; "
            "set retain_graph=True on the first backward"
        )

    # Seed the root output gradient.
    if grad is None:
        if tensor.size != 1:
            raise RuntimeError(
                "grad can be implicitly created only for scalar outputs; "
                f"got shape {tensor.shape}"
            )
        seed = jnp.ones(tensor.shape, tensor.dtype)
    else:
        seed = grad._array if isinstance(grad, Tensor) else jnp.asarray(grad)
    _add_out_grad(root_node, tensor._out_index, seed)

    # Topological order (DFS post-order over nodes).
    order = []
    seen = set()
    stack = [(root_node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            if t._node is not None and id(t._node) not in seen:
                stack.append((t._node, False))

    # Reverse sweep.
    for node in reversed(order):
        if all(g is None for g in node.out_grads):
            continue
        cotangents = [
            g if g is not None else _zero_cotangent(shape, dtype)
            for g, (shape, dtype) in zip(node.out_grads, node.out_avals)
        ]
        cot = tuple(cotangents) if len(cotangents) > 1 else cotangents[0]
        in_grads = node.vjp_fn(cot)
        node.out_grads = [None] * len(node.out_avals)  # reset for any next pass
        for t, g in zip(node.inputs, in_grads):
            if t.stop_gradient or g is None:
                continue
            if g.dtype == jax.dtypes.float0:
                continue
            if t._node is not None:
                _add_out_grad(t._node, t._out_index, g)
            else:
                _accumulate_leaf(t, g)
        if not retain_graph:
            node.release()


def _add_out_grad(node, index, g):
    cur = node.out_grads[index]
    node.out_grads[index] = g if cur is None else cur + g


def _accumulate_leaf(tensor, g):
    from .tensor import Tensor

    if tensor.grad is None:
        tensor.grad = Tensor._from_array(g, stop_gradient=True)
    else:
        tensor.grad = Tensor._from_array(tensor.grad._array + g, stop_gradient=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=False, allow_unused=False):
    """paddle.grad equivalent (imperative/partial_grad_engine.cc)."""
    from .tensor import Tensor

    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    saved = [t.grad for t in inputs]
    for t in inputs:
        t.grad = None
    try:
        for i, o in enumerate(outputs):
            go = None
            if grad_outputs is not None and grad_outputs[i] is not None:
                go = grad_outputs[i]
            backward(o, grad=go, retain_graph=retain_graph)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        "one of the inputs has no gradient; pass allow_unused=True"
                    )
                results.append(None)
            else:
                results.append(t.grad)
        return results
    finally:
        for t, s in zip(inputs, saved):
            t.grad = s
