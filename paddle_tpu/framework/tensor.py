"""Eager Tensor.

Reference parity: paddle/fluid/framework/tensor.h:37 (dense tensor over an
Allocation) + imperative VarBase (imperative/layer.cc). TPU-native design:
storage IS a jax.Array — XLA owns device memory (SURVEY.md §7 step 1), so
there is no separate Allocation; a Tensor adds autograd metadata
(stop_gradient/grad/tape node), Paddle tensor-method surface, and place
handling on top. Tensors transparently wrap JAX tracers, which is what lets
the whole eager API run under jax.jit when functionalized.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import autograd
from .dtype import convert_dtype, get_default_dtype, is_floating
from .place import CPUPlace, Place, TPUPlace, _default_place

_tensor_id = [0]


class Tensor:
    __slots__ = (
        "_array",
        "stop_gradient",
        "grad",
        "name",
        "persistable",
        "_node",
        "_out_index",
        "__weakref__",
    )

    def __init__(self, data=None, dtype=None, place=None, stop_gradient=True):
        if data is None:
            arr = jnp.zeros((), convert_dtype(dtype))
        else:
            arr = _to_array(data, dtype, place)
        self._array = arr
        self.stop_gradient = stop_gradient
        self.grad = None
        self.persistable = False
        _tensor_id[0] += 1
        self.name = f"generated_tensor_{_tensor_id[0]}"
        self._node = None
        self._out_index = 0

    # -- construction -------------------------------------------------------
    @classmethod
    def _from_array(cls, arr, stop_gradient=True, name=None):
        t = cls.__new__(cls)
        t._array = arr
        t.stop_gradient = stop_gradient
        t.grad = None
        t.persistable = False
        _tensor_id[0] += 1
        t.name = name or f"generated_tensor_{_tensor_id[0]}"
        t._node = None
        t._out_index = 0
        return t

    # -- properties ---------------------------------------------------------
    @property
    def shape(self):
        return list(self._array.shape)

    @property
    def ndim(self):
        return self._array.ndim

    @property
    def size(self):
        return int(np.prod(self._array.shape)) if self._array.shape else 1

    @property
    def dtype(self):
        return jnp.dtype(self._array.dtype)

    @property
    def place(self) -> Place:
        try:
            dev = next(iter(self._array.devices()))
            return CPUPlace() if dev.platform == "cpu" else TPUPlace(dev.id)
        except Exception:
            return _default_place()

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def T(self):
        from .. import ops

        return ops.transpose(self, list(range(self.ndim))[::-1])

    # -- data access --------------------------------------------------------
    def numpy(self):
        return np.asarray(self._array)

    def __array__(self, dtype=None, copy=None):
        # numpy interop for lazily-fetched tensors (Executor.run
        # return_numpy=False): np.asarray(t) is the explicit sync point.
        # numpy>=2 passes copy= and hard-errors on signatures without it
        a = np.asarray(self._array)
        if dtype is not None:
            a = a.astype(dtype, copy=False)
        return a.copy() if copy else a

    def item(self):
        return self._array.item()

    def tolist(self):
        return np.asarray(self._array).tolist()

    def astype(self, dtype):
        from .. import ops

        return ops.cast(self, dtype)

    cast = astype

    def detach(self):
        t = Tensor._from_array(self._array, stop_gradient=True, name=self.name)
        t.persistable = self.persistable
        return t

    def clone(self):
        from .. import ops

        return ops.assign(self)

    def to(self, place):
        if isinstance(place, str):
            name, _, idx = place.partition(":")
            idx = int(idx) if idx else 0
            place = CPUPlace() if name == "cpu" else TPUPlace(idx)
        arr = jax.device_put(self._array, place.jax_device())
        t = Tensor._from_array(arr, stop_gradient=self.stop_gradient, name=self.name)
        t.persistable = self.persistable
        return t

    def cpu(self):
        return self.to(CPUPlace())

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward(self, grad=grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        raise NotImplementedError("tensor hooks land with the hook subsystem")

    # -- in-place-ish mutation (parameter updates) --------------------------
    def set_value(self, value):
        """Replace underlying storage (used by optimizers / state loading)."""
        arr = value._array if isinstance(value, Tensor) else _to_array(value, self.dtype, None)
        if tuple(arr.shape) != tuple(self._array.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self._array.shape}"
            )
        self._array = arr

    def copy_(self, value):
        self.set_value(value)
        return self

    def fill_(self, value):
        self._array = jnp.full(self._array.shape, value, self._array.dtype)
        return self

    def zero_(self):
        return self.fill_(0)

    # -- operator sugar (dispatch to ops layer) -----------------------------
    def _binary(self, op, other, reverse=False):
        from .. import ops

        fn = getattr(ops, op)
        other = other if isinstance(other, Tensor) else to_tensor_like(other, self)
        return fn(other, self) if reverse else fn(self, other)

    def __add__(self, o):
        return self._binary("add", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary("subtract", o)

    def __rsub__(self, o):
        return self._binary("subtract", o, reverse=True)

    def __mul__(self, o):
        return self._binary("multiply", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary("divide", o)

    def __rtruediv__(self, o):
        return self._binary("divide", o, reverse=True)

    def __pow__(self, o):
        return self._binary("elementwise_pow", o)

    def __rpow__(self, o):
        return self._binary("elementwise_pow", o, reverse=True)

    def __mod__(self, o):
        return self._binary("remainder", o)

    def __floordiv__(self, o):
        return self._binary("floor_divide", o)

    def __matmul__(self, o):
        from .. import ops

        return ops.matmul(self, o)

    def __neg__(self):
        from .. import ops

        return ops.scale(self, scale=-1.0)

    def __abs__(self):
        from .. import ops

        return ops.abs(self)

    # comparisons (non-differentiable)
    def __eq__(self, o):
        return self._binary("equal", o)

    def __ne__(self, o):
        return self._binary("not_equal", o)

    def __lt__(self, o):
        return self._binary("less_than", o)

    def __le__(self, o):
        return self._binary("less_equal", o)

    def __gt__(self, o):
        return self._binary("greater_than", o)

    def __ge__(self, o):
        return self._binary("greater_equal", o)

    __hash__ = object.__hash__

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._array.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("truth value of a multi-element Tensor is ambiguous")
        return bool(self._array)

    def __float__(self):
        # paddle allows float() on any single-element tensor; jax only on
        # 0-d — squeeze first
        return float(self._array.reshape(()))

    def __int__(self):
        return int(self._array.reshape(()))

    def __getitem__(self, idx):
        from .. import ops

        return ops.getitem(self, idx)

    def __setitem__(self, idx, value):
        value = value if isinstance(value, Tensor) else to_tensor_like(value, self)
        self._array = self._array.at[idx].set(value._array.astype(self._array.dtype))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
            f"{grad_info},\n       {np.asarray(self._array)})"
        )

    # -- reduction / method sugar ------------------------------------------
    def sum(self, axis=None, keepdim=False):
        from .. import ops

        return ops.sum(self, axis=axis, keepdim=keepdim)

    def mean(self, axis=None, keepdim=False):
        from .. import ops

        return ops.mean(self, axis=axis, keepdim=keepdim)

    def max(self, axis=None, keepdim=False):
        from .. import ops

        return ops.max(self, axis=axis, keepdim=keepdim)

    def min(self, axis=None, keepdim=False):
        from .. import ops

        return ops.min(self, axis=axis, keepdim=keepdim)

    def prod(self, axis=None, keepdim=False):
        from .. import ops

        return ops.prod(self, axis=axis, keepdim=keepdim)

    def reshape(self, shape):
        from .. import ops

        return ops.reshape(self, shape)

    def transpose(self, perm):
        from .. import ops

        return ops.transpose(self, perm)

    def flatten(self, start_axis=0, stop_axis=-1):
        from .. import ops

        return ops.flatten(self, start_axis, stop_axis)

    def squeeze(self, axis=None):
        from .. import ops

        return ops.squeeze(self, axis)

    def unsqueeze(self, axis):
        from .. import ops

        return ops.unsqueeze(self, axis)

    def argmax(self, axis=None, keepdim=False):
        from .. import ops

        return ops.argmax(self, axis=axis, keepdim=keepdim)

    def matmul(self, o, transpose_x=False, transpose_y=False):
        from .. import ops

        return ops.matmul(self, o, transpose_x, transpose_y)

    def exp(self):
        from .. import ops

        return ops.exp(self)

    def log(self):
        from .. import ops

        return ops.log(self)

    def sqrt(self):
        from .. import ops

        return ops.sqrt(self)

    def tanh(self):
        from .. import ops

        return ops.tanh(self)

    def abs(self):
        from .. import ops

        return ops.abs(self)

    def clip(self, min=None, max=None):
        from .. import ops

        return ops.clip(self, min, max)

    def pow(self, y):
        return self.__pow__(y)

    def norm(self, p=2, axis=None, keepdim=False):
        from .. import ops

        return ops.p_norm(self, p, axis, keepdim)


def _to_array(data, dtype, place):
    if isinstance(data, Tensor):
        arr = data._array
    elif isinstance(data, jax.Array):
        arr = data
    else:
        npd = np.asarray(data)
        if dtype is None and npd.dtype == np.float64:
            npd = npd.astype(np.float32)  # paddle default: fp32
        arr = npd
    target_dtype = convert_dtype(dtype) if dtype is not None else None
    dev = (place or _default_place()).jax_device()
    arr = jax.device_put(jnp.asarray(arr), dev)
    if target_dtype is not None and arr.dtype != target_dtype:
        arr = arr.astype(target_dtype)
    return arr


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def to_tensor_like(value, ref: Tensor):
    """Convert a python scalar / ndarray to a Tensor matching ref's dtype
    promotion rules (scalars adopt ref dtype when ref is floating)."""
    if isinstance(value, Tensor):
        return value
    if isinstance(value, (int, float, bool)) and is_floating(ref.dtype):
        return Tensor._from_array(jnp.asarray(value, ref.dtype))
    if isinstance(value, float):
        return Tensor._from_array(jnp.asarray(value, jnp.float32))
    return Tensor(value)


class Parameter(Tensor):
    """Trainable tensor (python/paddle/fluid/framework.py Parameter)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip")

    def __init__(self, data, name=None, trainable=True):
        super().__init__(data, stop_gradient=not trainable)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        if name:
            self.name = name

    @classmethod
    def from_array(cls, arr, name=None, trainable=True):
        p = cls.__new__(cls)
        p._array = jnp.asarray(arr)
        p.stop_gradient = not trainable
        p.grad = None
        p.persistable = True
        _tensor_id[0] += 1
        p.name = name or f"param_{_tensor_id[0]}"
        p._node = None
        p._out_index = 0
        p.trainable = trainable
        p.optimize_attr = {"learning_rate": 1.0}
        p.regularizer = None
        p.need_clip = True
        return p
