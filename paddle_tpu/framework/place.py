"""Device places.

Reference parity: paddle/fluid/platform/place.h:26-123 — the `Place` variant
(CPUPlace/CUDAPlace/XPUPlace). Here TPUPlace is the first-class accelerator
place; device memory itself is managed by XLA, so a Place only selects a
jax.Device for tensor placement and compilation targets.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Base place. Equality is by (kind, device_id)."""

    kind = "undefined"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self.kind, self._device_id))

    def __repr__(self):
        return f"Place({self.kind}:{self._device_id})"

    # -- jax integration ----------------------------------------------------
    def jax_device(self) -> jax.Device:
        devs = _devices_for_kind(self.kind)
        if self._device_id >= len(devs):
            raise RuntimeError(
                f"{self!r}: only {len(devs)} {self.kind} device(s) visible"
            )
        return devs[self._device_id]


class CPUPlace(Place):
    kind = "cpu"

    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    kind = "tpu"


class CUDAPlace(Place):
    """Accepted for script compatibility; resolves to the accelerator."""

    kind = "tpu"


@functools.cache
def _devices_for_kind(kind: str):
    if kind == "cpu":
        try:
            return jax.devices("cpu")
        except RuntimeError:
            # cpu backend hidden (e.g. JAX_PLATFORMS=tpu); fall back to default
            return jax.devices()
    # Any accelerator backend counts as "tpu" (axon tunnels report platform
    # names like 'tpu' or 'axon'); prefer non-cpu devices.
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    return devs if devs else jax.devices()


def is_compiled_with_tpu() -> bool:
    return any(d.platform != "cpu" for d in jax.devices())


# paddle.device API ---------------------------------------------------------
_expected_place: Place | None = None


def _default_place() -> Place:
    global _expected_place
    if _expected_place is None:
        _expected_place = TPUPlace(0) if is_compiled_with_tpu() else CPUPlace()
    return _expected_place


def set_device(device: str | Place) -> Place:
    """set_device("tpu") / set_device("tpu:1") / set_device("cpu")."""
    global _expected_place
    if isinstance(device, Place):
        _expected_place = device
        return device
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    if name == "cpu":
        _expected_place = CPUPlace()
    elif name in ("tpu", "xpu", "gpu", "cuda"):
        _expected_place = TPUPlace(idx)
    else:
        raise ValueError(f"unknown device {device!r}")
    return _expected_place


def get_device() -> str:
    p = _default_place()
    return p.kind if p.kind == "cpu" else f"{p.kind}:{p.get_device_id()}"
