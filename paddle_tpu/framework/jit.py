"""Functionalization + compiled train steps.

Reference parity: the role played by ParallelExecutor/CompiledProgram
(paddle/fluid/framework/parallel_executor.cc, python/paddle/fluid/compiler.py:87)
— turning a model + optimizer into an efficient multi-device executable — and
by dygraph-to-static (python/paddle/fluid/dygraph/jit.py).

TPU-native design: instead of rewriting a program IR, we *functionalize* the
eager objects. A Layer's parameters/buffers and an Optimizer's accumulators
are extracted as pytrees of jax arrays; the eager forward/step code is run
once under JAX tracing with traced arrays swapped into the live objects,
yielding a single pure function

    step(state, batch, lr, rng) -> (state', metrics)

that XLA compiles (and, under a Mesh, partitions via GSPMD). The eager code
is the single source of truth — the same optimizer math runs eagerly and
compiled.
"""
from __future__ import annotations

import contextlib
import itertools
from collections import OrderedDict

import jax
import jax.numpy as jnp

# deterministic TrainStepFn instance ids (cache-key stability; see
# TrainStepFn.__init__)
_step_fn_counter = itertools.count()

from . import autograd
from .random import default_generator
from .tensor import Tensor

__all__ = [
    "capture_state",
    "functional_call",
    "TrainStepFn",
    "train_step",
    "eval_step",
]


# ---------------------------------------------------------------------------
# state extraction / swapping
# ---------------------------------------------------------------------------


def capture_state(model, optimizer=None):
    """Extract the functional state of a model (+ optional optimizer).

    Returns a dict pytree:
      params  — trainable parameter arrays (name -> array)
      frozen  — non-trainable parameter arrays
      buffers — persistable buffers (batchnorm stats, ...)
      opt     — optimizer accumulators + step count (if optimizer given)
    """
    params = OrderedDict()
    frozen = OrderedDict()
    for name, p in model.named_parameters():
        (params if getattr(p, "trainable", True) else frozen)[name] = p._array
    buffers = OrderedDict(
        (name, b._array) for name, b in model.named_buffers() if b is not None
    )
    state = {"params": params, "frozen": frozen, "buffers": buffers}
    if optimizer is not None:
        state["opt"] = {
            "accums": {k: list(v) for k, v in optimizer._accumulators.items()},
            "step": jnp.asarray(optimizer._global_step, jnp.int32),
        }
    return state


def restore_state(model, state, optimizer=None):
    """Write a state pytree back into the live eager objects."""
    named = dict(model.named_parameters())
    for name, arr in list(state["params"].items()) + list(state["frozen"].items()):
        named[name]._array = arr
    named_buf = dict(model.named_buffers())
    for name, arr in state["buffers"].items():
        named_buf[name]._array = arr
    if optimizer is not None and "opt" in state:
        optimizer._accumulators = {
            k: list(v) for k, v in state["opt"]["accums"].items()
        }
        optimizer._global_step = state["opt"]["step"]


@contextlib.contextmanager
def _swapped_model(model, state, rng_key=None):
    """Swap state arrays into the model's live tensors for the duration.

    On exit, the (possibly updated, e.g. batchnorm) buffer arrays are written
    into ``state["buffers"]`` and originals restored.
    """
    named = dict(model.named_parameters())
    named_buf = {n: b for n, b in model.named_buffers() if b is not None}
    saved_p = {n: t._array for n, t in named.items()}
    saved_b = {n: t._array for n, t in named_buf.items()}
    gen = default_generator()
    saved_key = gen.get_state()
    try:
        for name, arr in state["params"].items():
            named[name]._array = arr
        for name, arr in state["frozen"].items():
            named[name]._array = arr
        for name, arr in state["buffers"].items():
            named_buf[name]._array = arr
        if rng_key is not None:
            gen.set_state(rng_key)
        yield
        state["buffers"] = OrderedDict(
            (n, named_buf[n]._array) for n in state["buffers"]
        )
        state["rng"] = gen.get_state() if rng_key is not None else None
    finally:
        gen.set_state(saved_key)
        for n, a in saved_p.items():
            named[n]._array = a
        for n, a in saved_b.items():
            named_buf[n]._array = a


def functional_call(model, state, *args, rng=None, **kwargs):
    """Run ``model(*args)`` as a pure function of ``state``.

    ``args`` may be jax arrays or Tensors. Returns (outputs, new_state) where
    outputs have been unwrapped to jax arrays.
    """
    state = dict(state)
    state["buffers"] = OrderedDict(state["buffers"])
    wrapped = [
        a if isinstance(a, Tensor) else Tensor._from_array(jnp.asarray(a))
        for a in args
    ]
    with _swapped_model(model, state, rng_key=rng):
        with autograd.no_grad():
            out = model(*wrapped, **kwargs)
    out = jax.tree_util.tree_map(
        lambda x: x._array if isinstance(x, Tensor) else x,
        out,
        is_leaf=lambda x: isinstance(x, Tensor),
    )
    return out, state


# ---------------------------------------------------------------------------
# optimizer functionalization
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def _swapped_opt(optimizer, opt_state, lr):
    saved_acc = optimizer._accumulators
    saved_step = optimizer._global_step
    saved_lr = optimizer._lr_override
    try:
        optimizer._accumulators = {
            k: list(v) for k, v in opt_state["accums"].items()
        }
        optimizer._global_step = opt_state["step"]
        optimizer._lr_override = lr
        yield
        opt_state["accums"] = {
            k: list(v) for k, v in optimizer._accumulators.items()
        }
        opt_state["step"] = jnp.asarray(optimizer._global_step, jnp.int32)
    finally:
        optimizer._accumulators = saved_acc
        optimizer._global_step = saved_step
        optimizer._lr_override = saved_lr


def _apply_optimizer(model, optimizer, state, grads, lr):
    """Run optimizer.step() purely: returns (new_params, new_opt_state)."""
    named = dict(model.named_parameters())
    saved = {n: t._array for n, t in named.items()}
    saved_grads = {n: t.grad for n, t in named.items()}
    opt_state = {
        "accums": dict(state["opt"]["accums"]),
        "step": state["opt"]["step"],
    }
    try:
        for name, arr in state["params"].items():
            named[name]._array = arr
            g = grads.get(name)
            named[name].grad = Tensor._from_array(g) if g is not None else None
        for name, arr in state["frozen"].items():
            named[name]._array = arr
            named[name].grad = None
        with _swapped_opt(optimizer, opt_state, lr):
            optimizer.step()
        new_params = OrderedDict(
            (n, named[n]._array) for n in state["params"]
        )
        return new_params, opt_state
    finally:
        for n, a in saved.items():
            named[n]._array = a
            named[n].grad = saved_grads[n]


def init_opt_state(model, optimizer, state=None):
    """Materialize optimizer accumulators without advancing real state.

    Accumulator layout differs per optimizer class and is created lazily by
    eager ``step()``; we discover it with ``jax.eval_shape`` (abstract trace,
    no FLOPs) and allocate concrete zeros. This keeps the step function's
    input pytree structure stable from the very first compiled step.
    """
    if state is None:
        state = capture_state(model, optimizer)
    if optimizer._accumulators:
        return state  # already materialized (e.g. loaded from checkpoint)

    def probe(params):
        zero_grads = {n: jnp.zeros_like(a) for n, a in params.items()}
        st = {
            "params": params,
            "frozen": state["frozen"],
            "opt": {"accums": {}, "step": jnp.asarray(0, jnp.int32)},
        }
        _, opt_state = _apply_optimizer(model, optimizer, st, zero_grads, 0.0)
        return opt_state["accums"]

    shapes = jax.eval_shape(probe, state["params"])
    accums = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )
    # optimizers whose accumulators must not start at zero (e.g. Lookahead
    # slow weights = initial fast weights) expose concrete initial values
    init_hook = getattr(optimizer, "_init_accumulator_values", None)
    if init_hook is not None:
        accums = {**accums, **init_hook()}
    optimizer._accumulators = {k: list(v) for k, v in accums.items()}
    state["opt"] = {
        "accums": accums,
        "step": jnp.asarray(optimizer._global_step, jnp.int32),
    }
    return state


# ---------------------------------------------------------------------------
# compiled train / eval steps
# ---------------------------------------------------------------------------


class TrainStepFn:
    """A compiled training step bound to live eager objects.

    ``self.pure`` is the pure function
        pure(state, batch, lr, rng) -> (state', metrics)
    usable directly under jax.jit / pjit / shard_map.  Calling the object
    runs one step, keeping state on device; ``sync()`` writes state back
    into the eager model/optimizer (for checkpointing etc).
    """

    def __init__(self, model, optimizer, loss_fn, jit=True, donate=True,
                 recompute=False, grad_accum_steps=1, grad_accum_avg=True):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        # DistributedStrategy-driven behaviors (fleet meta-optimizer parity,
        # python/paddle/fluid/optimizer.py:4685 RecomputeOptimizer and
        # distributed/fleet/meta_optimizers/gradient_merge_optimizer.py):
        # recompute → jax.checkpoint over the forward (trade FLOPs for HBM);
        # grad_accum_steps=k → k-step gradient accumulation inside the
        # compiled step, optimizer applied every k-th call.
        self.recompute = bool(recompute)
        self.grad_accum_steps = int(grad_accum_steps)
        self.grad_accum_avg = bool(grad_accum_avg)
        self.state = init_opt_state(model, optimizer)
        if self.grad_accum_steps > 1:
            self.state["gm"] = {
                "acc": OrderedDict(
                    (n, jnp.zeros_like(a))
                    for n, a in self.state["params"].items()
                ),
                "count": jnp.asarray(0, jnp.int32),
            }
        if donate:
            # the initial state aliases the live model's arrays; donation
            # would invalidate them on TPU — copy once so the eager objects
            # stay readable until sync()
            self.state = jax.tree_util.tree_map(jnp.copy, self.state)
        self.pure = self._build_pure()
        self._jit = bool(jit)
        if jit:
            self.compiled = jax.jit(
                self.pure, donate_argnums=(0,) if donate else ()
            )
        else:
            self.compiled = self.pure
        # per-batch-signature executables through the SHARED compiled-
        # callable runtime (runtime/compiled.py): AOT compile + cost
        # capture + LRU bound (FLAGS_compiled_cache_capacity — the same
        # knob the executor obeys; the old hardcoded 16 here silently
        # evicted/recompiled under many batch signatures) + the
        # donation-safe demote-to-jit fallback, all one policy.
        from ..runtime.compiled import CompiledStore

        self._exec = CompiledStore(
            "train_step", cost_label="train_step",
            hit_counter="train_step::exec_cache_hit",
            miss_counter="train_step::exec_cache_miss")
        # deterministic per-instance index (not id()): the derived
        # cache_key must be stable across runs for log correlation, yet
        # distinct per step fn so two models with identical batch avals
        # don't collide in the global CostRecord registry
        self._instance = next(_step_fn_counter)
        self._rng = default_generator().split()

    def _build_pure(self):
        model, optimizer, loss_fn = self.model, self.optimizer, self.loss_fn
        recompute = getattr(self, "recompute", False)
        k = getattr(self, "grad_accum_steps", 1)
        avg = getattr(self, "grad_accum_avg", True)
        # FLAGS_quantized_allreduce, read at step CONSTRUCTION (like
        # donate): gradients route through the int8-with-per-block-scales
        # sync (distributed/quantized.py) — on a bound-axis SPMD world
        # the real quantized collectives, under GSPMD/single-controller
        # the same two quantization hops with the wire bytes accounted in
        # the collective ledger. Capturing the flag here (not at trace
        # time) keeps a compiled step's behavior fixed: flipping the flag
        # later builds a NEW step fn with its own cache keys.
        from ..flags import flag as _flag

        quantized_sync = bool(_flag("quantized_allreduce"))

        def pure(state, batch, lr, rng):
            frozen, buffers = state["frozen"], state["buffers"]

            def loss_of(params):
                st = {
                    "params": params,
                    "frozen": frozen,
                    "buffers": OrderedDict(buffers),
                }
                wrapped = [Tensor._from_array(a) for a in batch]
                was_training = model.training
                model.train()  # a train step always traces in train mode
                try:
                    with _swapped_model(model, st, rng_key=rng):
                        with autograd.no_grad():
                            loss = loss_fn(model, *wrapped)
                finally:
                    if not was_training:
                        model.eval()
                loss_arr = loss._array if isinstance(loss, Tensor) else loss
                return loss_arr, st["buffers"]

            if recompute:
                # RecomputeOptimizer equivalent (fluid/optimizer.py:4685):
                # forward activations are not saved for backward — XLA
                # rematerializes them, trading MXU FLOPs for HBM.
                loss_of = jax.checkpoint(loss_of)

            (loss, new_buffers), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(state["params"])

            if quantized_sync:
                # int8 gradient sync: under GSPMD the grads at this point
                # are already the global mean, so the hook applies the
                # wire-precision rounding (and books the quantized wire
                # bytes); in a bound-axis SPMD body it IS the all-reduce.
                from ..distributed import quantized as _qar

                # quantized=True pins the construction-time capture: the
                # default would re-read the flag at trace time, and a
                # flag flip before a retrace would silently swap modes
                grads = _qar.sync_grads(grads, average=False,
                                        quantized=True)

            if k <= 1:
                new_params, new_opt = _apply_optimizer(
                    model, optimizer, state, grads, lr
                )
                new_state = {
                    "params": new_params,
                    "frozen": frozen,
                    "buffers": new_buffers,
                    "opt": new_opt,
                }
                return new_state, {"loss": loss}

            # gradient merge (meta_optimizers/gradient_merge_optimizer.py):
            # accumulate k micro-grads, apply the optimizer on the k-th.
            acc = jax.tree_util.tree_map(jnp.add, state["gm"]["acc"], grads)
            count = state["gm"]["count"] + 1

            def apply_branch(_):
                g = (
                    jax.tree_util.tree_map(lambda a: a / k, acc)
                    if avg
                    else acc
                )
                new_params, new_opt = _apply_optimizer(
                    model, optimizer, state, g, lr
                )
                zeros = jax.tree_util.tree_map(jnp.zeros_like, acc)
                return new_params, new_opt, zeros, jnp.asarray(0, jnp.int32)

            def skip_branch(_):
                opt_state = {
                    "accums": {
                        kk: list(v)
                        for kk, v in state["opt"]["accums"].items()
                    },
                    "step": jnp.asarray(state["opt"]["step"], jnp.int32),
                }
                return (
                    OrderedDict(state["params"]),
                    opt_state,
                    acc,
                    jnp.asarray(count, jnp.int32),
                )

            new_params, new_opt, new_acc, new_count = jax.lax.cond(
                count >= k, apply_branch, skip_branch, None
            )
            new_state = {
                "params": new_params,
                "frozen": frozen,
                "buffers": new_buffers,
                "opt": new_opt,
                "gm": {"acc": new_acc, "count": new_count},
            }
            return new_state, {"loss": loss}

        return pure

    def __call__(self, *batch):
        batch = tuple(
            b._array if isinstance(b, Tensor) else jnp.asarray(b) for b in batch
        )
        if not getattr(self, "_usage_checked", False):
            self._freeze_unused_params(batch)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        self._rng, sub = jax.random.split(self._rng)
        from ..flags import flag

        if flag("check_nan_inf"):
            # FLAGS_check_nan_inf (platform/flags.cc:44 →
            # details/nan_inf_utils_detail.cc): the reference scans every
            # op's outputs post-run; the XLA-native equivalent is checkify
            # float_checks — every primitive inside the compiled step gets
            # an instrumented NaN check that reports the producing
            # operation's source location.
            metrics = self._run_checked(batch, lr, sub)
        else:
            metrics = self._dispatch(batch, lr, sub)
        if flag("benchmark"):
            # FLAGS_benchmark: synchronous dispatch for exact timings
            jax.block_until_ready(metrics)
        # NOTE: LR schedulers keep eager semantics — the user calls
        # scheduler.step() (per epoch or per batch) exactly as in eager mode;
        # the current value is read and fed in as a traced scalar each step.
        return metrics

    def _dispatch(self, batch, lr, sub):
        """Run one step through the shared compiled-callable runtime:
        per-batch-signature AOT compile (the same single XLA compile
        jax.jit's first call would pay, captured for the utilization
        accounting), LRU caching, and the donation-safe demote-to-jit
        fallback all follow the one policy in runtime/compiled.py."""
        if not self._jit:
            self.state, metrics = self.compiled(self.state, batch, lr, sub)
            return metrics
        # params can migrate to frozen (_freeze_unused_params) and the
        # gradient-merge slot changes the state pytree — both change the
        # compiled signature, so they key the executable cache alongside
        # the batch avals
        sig = (self._instance, len(self.state["params"]),
               "gm" in self.state) + tuple(
            (tuple(b.shape), str(b.dtype)) for b in batch)
        entry, _ = self._exec.get_or_build(
            sig, lambda: (self.compiled, None))
        new_state, metrics = self._exec.dispatch(
            entry, self.state, batch, lr, sub,
            donated=lambda: jax.tree_util.tree_leaves(self.state))
        self.state = new_state
        return metrics

    def _run_checked(self, batch, lr, sub):
        from jax.experimental import checkify

        from ..errors import FatalError

        if not hasattr(self, "_checked_fn"):
            # no donation: on error the pre-step state must stay valid
            self._checked_fn = jax.jit(
                checkify.checkify(self.pure, errors=checkify.float_checks)
            )
        err, (new_state, metrics) = self._checked_fn(
            self.state, batch, lr, sub
        )
        try:
            err.throw()
        except Exception as e:  # checkify.JaxRuntimeError
            # FLAGS_check_nan_inf_action, shared policy with the executor
            # scan (flight_recorder.nan_event_action): warn counts + logs
            # and keeps training, dump writes the flight-recorder
            # snapshot before raising, raise is the default
            from ..monitor import flight_recorder as _flight

            if _flight.nan_event_action(
                    "train_step",
                    f"non-finite value produced inside the train step: "
                    f"{e}") is not None:
                raise FatalError(
                    f"check_nan_inf: non-finite value produced inside the "
                    f"train step: {e}"
                ) from e
        self.state = new_state
        return metrics

    def _freeze_unused_params(self, batch):
        """Move params the loss never reads into the frozen group.

        Eager-parity: eager step() skips params with grad None, but
        value_and_grad returns *zeros* for unused params, which would
        wrongly apply weight decay / advance accumulators on them. A
        one-time abstract trace finds the truly-unused leaves (an outer
        jaxpr invar unused at the top level cannot be consumed by any
        nested jaxpr either — nested use passes through call-eqn invars).
        """
        self._usage_checked = True
        names = list(self.state["params"].keys())

        def probe(params, batch, rng):
            (loss, _), grads = _noop_grads_probe(
                self.model, self.loss_fn, params,
                self.state["frozen"], self.state["buffers"], batch, rng,
            )
            return loss

        try:
            jaxpr = jax.make_jaxpr(probe)(
                self.state["params"], batch, self._rng
            ).jaxpr
        except Exception:
            return  # fail open: keep zero-grad behavior
        n = len(names)
        invars = jaxpr.invars[:n]
        used = set()
        for eqn in jaxpr.eqns:
            used.update(map(id, eqn.invars))
        used.update(map(id, jaxpr.outvars))
        unused = [nm for nm, v in zip(names, invars) if id(v) not in used]
        if not unused:
            return
        for nm in unused:
            self.state["frozen"][nm] = self.state["params"].pop(nm)
            if "gm" in self.state:
                self.state["gm"]["acc"].pop(nm, None)
        # rebuild: the pure fn closes over nothing stateful, but the pytree
        # structure of `state` changed, so recompilation happens naturally

    def save_checkpoint(self, path, step=None, async_=None, keep=None):
        """Snapshot the on-device state crash-consistently (async by
        default — FLAGS_checkpoint_async); distributed/checkpoint.py."""
        from ..distributed import checkpoint as _ckpt

        return _ckpt.save_train_step(self, path, step=step, async_=async_,
                                     keep=keep)

    def load_checkpoint(self, path):
        """Restore a snapshot written by ``save_checkpoint`` (also
        accepts one saved from a sharded/multi-rank world — the global
        arrays are reassembled from all shards). Returns the manifest."""
        from ..distributed import checkpoint as _ckpt

        return _ckpt.restore_train_step(self, path)

    def sync(self):
        # copy before restoring: restore_state aliases state arrays into
        # the live objects, and the next step() donates self.state — without
        # the copy, donation would invalidate the model's own parameters
        state = jax.tree_util.tree_map(jnp.copy, self.state)
        restore_state(self.model, state, self.optimizer)
        return self


def _noop_grads_probe(model, loss_fn, params, frozen, buffers, batch, rng):
    """Forward-only probe used by _freeze_unused_params."""
    def loss_of(p):
        st = {
            "params": p,
            "frozen": frozen,
            "buffers": OrderedDict(buffers),
        }
        wrapped = [Tensor._from_array(a) for a in batch]
        with _swapped_model(model, st, rng_key=rng):
            with autograd.no_grad():
                loss = loss_fn(model, *wrapped)
        loss_arr = loss._array if isinstance(loss, Tensor) else loss
        return loss_arr, st["buffers"]

    out = loss_of(params)
    return out, None


def train_step(model, optimizer, loss_fn, jit=True, donate=True,
               recompute=False, grad_accum_steps=1, grad_accum_avg=True):
    """Build a compiled train step.

    ``loss_fn(model, *batch) -> scalar loss Tensor`` runs the eager forward.
    """
    return TrainStepFn(
        model, optimizer, loss_fn, jit=jit, donate=donate,
        recompute=recompute, grad_accum_steps=grad_accum_steps,
        grad_accum_avg=grad_accum_avg,
    )


def eval_step(model, fn=None, jit=True):
    """Compile an inference step: returns callable(batch...) -> arrays.

    ``fn(model, *batch)`` customizes the computation (e.g. decode instead of
    raw logits); by default the model's forward is used. The model is run in
    eval mode regardless of its current training flag.
    """

    def pure(state, *batch):
        state = dict(state)
        state["buffers"] = OrderedDict(state["buffers"])
        wrapped = [
            a if isinstance(a, Tensor) else Tensor._from_array(jnp.asarray(a))
            for a in batch
        ]
        with _swapped_model(model, state):
            with autograd.no_grad():
                out = fn(model, *wrapped) if fn is not None else model(*wrapped)
        return jax.tree_util.tree_map(
            lambda x: x._array if isinstance(x, Tensor) else x,
            out,
            is_leaf=lambda x: isinstance(x, Tensor),
        )

    compiled = jax.jit(pure) if jit else pure

    # the module-tree walk (named_parameters/named_buffers recursion) runs
    # once; per call only the current arrays are read off the cached
    # Tensor objects — fresh values with no per-step tree traversal
    # (training mutates t._array in place, never the Tensor identities)
    cached = {}

    def snapshot():
        if not cached:
            cached["params"] = [
                (n, p, getattr(p, "trainable", True))
                for n, p in model.named_parameters()
            ]
            cached["buffers"] = [
                (n, b) for n, b in model.named_buffers() if b is not None
            ]
        params, frozen = OrderedDict(), OrderedDict()
        for n, p, trainable in cached["params"]:
            (params if trainable else frozen)[n] = p._array
        return {
            "params": params,
            "frozen": frozen,
            "buffers": OrderedDict(
                (n, b._array) for n, b in cached["buffers"]
            ),
        }

    def run(*batch):
        arrs = tuple(
            b._array if isinstance(b, Tensor) else jnp.asarray(b) for b in batch
        )
        was_training = model.training
        model.eval()
        try:
            return compiled(snapshot(), *arrs)
        finally:
            if was_training:
                model.train()

    run.pure = pure
    return run
