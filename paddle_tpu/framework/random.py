"""RNG state.

Reference parity: paddle/fluid/framework/generator.h + pybind/generator_py.cc
(global generator with seed control). TPU-native design: state is a JAX PRNG
key. Eager ops split the global key statefully; functionalized/jitted train
steps swap the key for a traced one so randomness threads through the
compiled step as data (see framework/jit.py).
"""
from __future__ import annotations

import contextlib
import os

import jax

_PRNG_IMPL = None


def prng_impl() -> str:
    """PRNG implementation for all framework keys.

    TPU default is ``rbg`` (XLA's counter-based hardware RNG): dropout-heavy
    steps (BERT pretraining has 25+ dropout sites) are ~25% faster end to
    end than with threefry, measured on v5e. CPU keeps ``threefry2x32`` so
    test vectors stay stable. Override with PADDLE_TPU_PRNG=threefry2x32
    (e.g. for bit-exact cross-platform reproducibility studies).
    """
    global _PRNG_IMPL
    if _PRNG_IMPL is None:
        env = os.environ.get("PADDLE_TPU_PRNG", "")
        if env:
            _PRNG_IMPL = env
        else:
            try:
                backend = jax.default_backend()
            except Exception:
                backend = "cpu"
            # any accelerator backend (tpu, or a remote-TPU plugin like
            # axon) gets rbg; only plain CPU keeps threefry — same
            # convention as framework/place.py
            _PRNG_IMPL = "threefry2x32" if backend == "cpu" else "rbg"
    return _PRNG_IMPL


class Generator:
    """Stateful wrapper over a jax PRNG key.

    Key creation is lazy: the impl (and thus the backend query) resolves on
    first RNG use, not at `import paddle_tpu` — user code gets a chance to
    call jax.config.update("jax_platforms", ...) / set PADDLE_TPU_PRNG
    after import (see the axon bootstrap-race note in prng_impl).
    """

    def __init__(self, seed: int = 0):
        self._key = None
        self._seed = seed

    def _ensure(self):
        if self._key is None:
            self._key = jax.random.key(self._seed, impl=prng_impl())

    def manual_seed(self, seed: int):
        self._seed = seed
        self._key = None
        return self

    def initial_seed(self) -> int:
        return self._seed

    def split(self):
        """Return a fresh subkey, advancing internal state."""
        self._ensure()
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- functionalization hooks (used by jit/train-step capture) ----------
    def get_state(self):
        self._ensure()
        return self._key

    def set_state(self, key):
        self._key = key


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(value: int):
    """Set the global RNG seed (paddle.seed)."""
    _default_generator.manual_seed(int(value))
    return _default_generator


def split_key():
    return _default_generator.split()


@contextlib.contextmanager
def fork_rng(seed_value: int | None = None):
    """Temporarily fork RNG state (deterministic scope)."""
    saved = _default_generator.get_state()
    if seed_value is not None:
        _default_generator.manual_seed(seed_value)
    try:
        yield
    finally:
        _default_generator.set_state(saved)
