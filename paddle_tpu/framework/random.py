"""RNG state.

Reference parity: paddle/fluid/framework/generator.h + pybind/generator_py.cc
(global generator with seed control). TPU-native design: state is a JAX PRNG
key. Eager ops split the global key statefully; functionalized/jitted train
steps swap the key for a traced one so randomness threads through the
compiled step as data (see framework/jit.py).
"""
from __future__ import annotations

import contextlib

import jax


class Generator:
    """Stateful wrapper over a jax PRNG key."""

    def __init__(self, seed: int = 0):
        self._key = jax.random.key(seed)
        self._seed = seed

    def manual_seed(self, seed: int):
        self._key = jax.random.key(seed)
        self._seed = seed
        return self

    def initial_seed(self) -> int:
        return self._seed

    def split(self):
        """Return a fresh subkey, advancing internal state."""
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- functionalization hooks (used by jit/train-step capture) ----------
    def get_state(self):
        return self._key

    def set_state(self, key):
        self._key = key


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(value: int):
    """Set the global RNG seed (paddle.seed)."""
    _default_generator.manual_seed(int(value))
    return _default_generator


def split_key():
    return _default_generator.split()


@contextlib.contextmanager
def fork_rng(seed_value: int | None = None):
    """Temporarily fork RNG state (deterministic scope)."""
    saved = _default_generator.get_state()
    if seed_value is not None:
        _default_generator.manual_seed(seed_value)
    try:
        yield
    finally:
        _default_generator.set_state(saved)
