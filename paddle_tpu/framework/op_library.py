"""Runtime-loadable custom op libraries.

Reference parity: LoadOpLib (framework/load_op_lib.h:45 — dlopen a user
.so and merge its OpInfoMap into the registry), the C plugin ABI
(framework/c/c_api.h) and paddle.fluid.load_op_library
(pybind/pybind.cc:1654); example+test
python/paddle/fluid/tests/custom_op/relu_op.cc / test_custom_op.py.

TPU-native split of responsibilities:
- device kernels are written in Python (JAX/Pallas) and registered with
  ops.registry.register_op — no ABI needed, they compile into the XLA
  module like built-ins;
- NATIVE (C++) custom kernels are host kernels, reached through
  jax.pure_callback — exactly the reference's CPU-kernel role. The .so
  implements the C ABI below; each op becomes a registered kernel usable
  eagerly, under jit (as a host callback), and in static programs. A
  library may export gradients (PD_OpRunGrad), wired via jax.custom_vjp
  (the GradOpDescMaker analog, framework/c/c_api.h PD_GetGradOpDescStrs).

C ABI (all symbols optional except the first four):

    int         PD_NumOps(void);
    const char* PD_OpName(int op);
    int         PD_OpNumInputs(int op);
    int         PD_OpNumOutputs(int op);
    // shapes flattened with stride MAX_RANK (8)
    int PD_OpInferShape(int op, int n_in, const int64_t* in_shapes,
                        const int32_t* in_ndims, int64_t* out_shapes,
                        int32_t* out_ndims);
    int PD_OpRun(int op, int n_in, const float** in_bufs,
                 const int64_t* in_shapes, const int32_t* in_ndims,
                 float** out_bufs);
    int PD_OpHasGrad(int op);
    // grad: inputs ++ output cotangents -> input gradients
    int PD_OpRunGrad(int op, int n_in, const float** in_bufs,
                     const int64_t* in_shapes, const int32_t* in_ndims,
                     float** grad_bufs);

float32 buffers in v1 (the reference example ops are float too); rank is
capped at MAX_RANK = 8.
"""
from __future__ import annotations

import ctypes

import numpy as np

import jax
import jax.numpy as jnp

MAX_RANK = 8

__all__ = ["load_op_library"]

_loaded: dict[str, list] = {}


def _shapes_buf(arrays):
    n = len(arrays)
    shapes = (ctypes.c_int64 * (n * MAX_RANK))()
    ndims = (ctypes.c_int32 * n)()
    for i, a in enumerate(arrays):
        ndims[i] = a.ndim
        for d, s in enumerate(a.shape):
            shapes[i * MAX_RANK + d] = s
    return shapes, ndims


def _infer(lib, op_idx, in_specs, n_out):
    shapes = (ctypes.c_int64 * (len(in_specs) * MAX_RANK))()
    ndims = (ctypes.c_int32 * len(in_specs))()
    for i, shp in enumerate(in_specs):
        ndims[i] = len(shp)
        for d, s in enumerate(shp):
            shapes[i * MAX_RANK + d] = s
    out_shapes = (ctypes.c_int64 * (n_out * MAX_RANK))()
    out_ndims = (ctypes.c_int32 * n_out)()
    rc = lib.PD_OpInferShape(op_idx, len(in_specs), shapes, ndims,
                             out_shapes, out_ndims)
    if rc != 0:
        raise RuntimeError(f"custom op infer_shape failed (rc={rc})")
    return [
        tuple(out_shapes[i * MAX_RANK + d] for d in range(out_ndims[i]))
        for i in range(n_out)
    ]


def _run_c(lib, fn, op_idx, arrays, out_shapes):
    arrays = [np.ascontiguousarray(a, dtype=np.float32) for a in arrays]
    shapes, ndims = _shapes_buf(arrays)
    in_ptrs = (ctypes.POINTER(ctypes.c_float) * len(arrays))(
        *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for a in arrays]
    )
    outs = [np.empty(s, np.float32) for s in out_shapes]
    out_ptrs = (ctypes.POINTER(ctypes.c_float) * len(outs))(
        *[o.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for o in outs]
    )
    rc = fn(op_idx, len(arrays), in_ptrs, shapes, ndims, out_ptrs)
    if rc != 0:
        raise RuntimeError(f"custom op run failed (rc={rc})")
    return outs


def _make_kernel(lib, op_idx, name, n_in, n_out, has_grad):
    def infer_out_shapes(args):
        return _infer(lib, op_idx, [tuple(a.shape) for a in args], n_out)

    def host_run(*args):
        outs = _run_c(lib, lib.PD_OpRun, op_idx, args,
                      infer_out_shapes(args))
        return tuple(outs) if n_out > 1 else outs[0]

    def callback(*args):
        out_shapes = infer_out_shapes(args)
        result_spec = [
            jax.ShapeDtypeStruct(s, jnp.float32) for s in out_shapes
        ]
        if n_out == 1:
            result_spec = result_spec[0]
        return jax.pure_callback(host_run, result_spec, *args,
                                 vmap_method="sequential")

    if not has_grad:
        def fn(*args, **kw):
            args = [jnp.asarray(a, jnp.float32) for a in args]
            return callback(*args)
        fn.__name__ = name
        return fn

    if n_out != 1:
        raise NotImplementedError(
            f"custom op {name!r}: gradients are supported for "
            "single-output ops in v1"
        )

    @jax.custom_vjp
    def fn(*args):
        return callback(*args)

    def fwd(*args):
        return fn(*args), args

    def bwd(res, gy):
        args = list(res) + [gy]

        def host_grad(*all_args):
            grads = _run_c(
                lib, lib.PD_OpRunGrad, op_idx, all_args,
                [tuple(a.shape) for a in all_args[:n_in]],
            )
            return tuple(grads)

        spec = tuple(
            jax.ShapeDtypeStruct(a.shape, jnp.float32) for a in res
        )
        return jax.pure_callback(host_grad, spec, *args,
                                 vmap_method="sequential")

    fn.defvjp(fwd, bwd)

    def wrapper(*args, **kw):
        args = [jnp.asarray(a, jnp.float32) for a in args]
        return fn(*args)

    wrapper.__name__ = name
    return wrapper


def load_op_library(so_path: str):
    """dlopen a custom-op library and register its ops (LoadOpLib,
    framework/load_op_lib.h:45). Returns the list of op names added.

    Each op becomes callable as ``ops.registry.kernel(name)`` / through
    the mode-aware dispatch, like any built-in kernel.
    """
    from ..ops.registry import register_op

    if so_path in _loaded:
        return list(_loaded[so_path])
    lib = ctypes.CDLL(so_path)
    lib.PD_NumOps.restype = ctypes.c_int
    lib.PD_OpName.restype = ctypes.c_char_p
    lib.PD_OpName.argtypes = [ctypes.c_int]
    for sym in ("PD_OpNumInputs", "PD_OpNumOutputs"):
        getattr(lib, sym).restype = ctypes.c_int
        getattr(lib, sym).argtypes = [ctypes.c_int]
    lib.PD_OpInferShape.restype = ctypes.c_int
    lib.PD_OpRun.restype = ctypes.c_int
    has_grad_fn = getattr(lib, "PD_OpHasGrad", None)
    if has_grad_fn is not None:
        has_grad_fn.restype = ctypes.c_int
        has_grad_fn.argtypes = [ctypes.c_int]

    names = []
    for i in range(lib.PD_NumOps()):
        name = lib.PD_OpName(i).decode()
        n_in = lib.PD_OpNumInputs(i)
        n_out = lib.PD_OpNumOutputs(i)
        has_grad = bool(has_grad_fn(i)) if has_grad_fn is not None else False
        k = _make_kernel(lib, i, name, n_in, n_out, has_grad)
        register_op(name, num_outputs=n_out)(k)
        names.append(name)
    _loaded[so_path] = names
    return names
