"""Dtype system.

Reference parity: paddle/fluid/framework/framework.proto:104 (VarType.Type
dtype enum) and python/paddle/fluid/data_feeder.py dtype conversion. On TPU
the canonical compute dtype is bfloat16-first (MXU native); float32 remains
the default user-facing dtype.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical name -> jnp dtype
_NAME_TO_DTYPE = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
}

# Expose dtype singletons at module level (paddle.float32 style).
bool_ = jnp.dtype(jnp.bool_)
uint8 = jnp.dtype(jnp.uint8)
int8 = jnp.dtype(jnp.int8)
int16 = jnp.dtype(jnp.int16)
int32 = jnp.dtype(jnp.int32)
int64 = jnp.dtype(jnp.int64)
float16 = jnp.dtype(jnp.float16)
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype(jnp.float32)
float64 = jnp.dtype(jnp.float64)
complex64 = jnp.dtype(jnp.complex64)
complex128 = jnp.dtype(jnp.complex128)

_DEFAULT_DTYPE = float32


# x64-off canonicalization: TPUs have no 64-bit compute units; when JAX
# x64 mode is disabled (the TPU-normal configuration) a requested 64-bit
# dtype deliberately means its 32-bit counterpart. Doing this here — at
# the single dtype chokepoint — keeps the paddle API surface (which
# advertises int64 labels everywhere, framework.proto:104) while emitting
# zero per-op truncation warnings from JAX.
_X64_NARROW = {
    "int64": "int32",
    "uint64": "uint32",
    "float64": "float32",
    "complex128": "complex64",
}


def _x64_enabled() -> bool:
    import jax

    return bool(jax.config.jax_enable_x64)


def convert_dtype(dtype) -> jnp.dtype:
    """Normalize any dtype spec (str, np dtype, jnp dtype) to a jnp.dtype.

    With x64 disabled, 64-bit requests narrow to 32-bit silently (the
    TPU-first contract; see _X64_NARROW above)."""
    if dtype is None:
        return _DEFAULT_DTYPE
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name not in _NAME_TO_DTYPE:
            raise ValueError(f"unsupported dtype string: {dtype!r}")
    else:
        name = jnp.dtype(dtype).name
    if name in _X64_NARROW and not _x64_enabled():
        name = _X64_NARROW[name]
    return jnp.dtype(_NAME_TO_DTYPE.get(name, name))


def dtype_name(dtype) -> str:
    return jnp.dtype(dtype).name


def set_default_dtype(dtype):
    global _DEFAULT_DTYPE
    d = convert_dtype(dtype)
    if d not in (float16, bfloat16, float32, float64):
        raise ValueError("default dtype must be a floating dtype")
    _DEFAULT_DTYPE = d


def get_default_dtype():
    return _DEFAULT_DTYPE


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), np.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), np.integer)
