"""Dtype system.

Reference parity: paddle/fluid/framework/framework.proto:104 (VarType.Type
dtype enum) and python/paddle/fluid/data_feeder.py dtype conversion. On TPU
the canonical compute dtype is bfloat16-first (MXU native); float32 remains
the default user-facing dtype.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical name -> jnp dtype
_NAME_TO_DTYPE = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
}

# Expose dtype singletons at module level (paddle.float32 style).
bool_ = jnp.dtype(jnp.bool_)
uint8 = jnp.dtype(jnp.uint8)
int8 = jnp.dtype(jnp.int8)
int16 = jnp.dtype(jnp.int16)
int32 = jnp.dtype(jnp.int32)
int64 = jnp.dtype(jnp.int64)
float16 = jnp.dtype(jnp.float16)
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype(jnp.float32)
float64 = jnp.dtype(jnp.float64)
complex64 = jnp.dtype(jnp.complex64)
complex128 = jnp.dtype(jnp.complex128)

_DEFAULT_DTYPE = float32


def convert_dtype(dtype) -> jnp.dtype:
    """Normalize any dtype spec (str, np dtype, jnp dtype) to a jnp.dtype."""
    if dtype is None:
        return _DEFAULT_DTYPE
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name in _NAME_TO_DTYPE:
            return jnp.dtype(_NAME_TO_DTYPE[name])
        raise ValueError(f"unsupported dtype string: {dtype!r}")
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    return jnp.dtype(dtype).name


def set_default_dtype(dtype):
    global _DEFAULT_DTYPE
    d = convert_dtype(dtype)
    if d not in (float16, bfloat16, float32, float64):
        raise ValueError("default dtype must be a floating dtype")
    _DEFAULT_DTYPE = d


def get_default_dtype():
    return _DEFAULT_DTYPE


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), np.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), np.integer)
