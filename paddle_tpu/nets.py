"""Composite network helpers (fluid/nets.py).

Reference parity: python/paddle/fluid/nets.py:29 (simple_img_conv_pool),
:141 (img_conv_group), :256 (sequence_conv_pool), :328 (glu), :372
(scaled_dot_product_attention). Pure composition over existing ops /
static.nn builders — the mode-aware ``ops`` dispatch makes glu and
single-head scaled_dot_product_attention work in BOTH dygraph and static
graph; the conv/sequence composites and the multi-head projection path
create implicit parameters and therefore follow the reference's
static-graph contract (use nn.Conv2D / nn.MultiHeadAttention in
dygraph).

Ragged design note: the reference's sequence_conv_pool consumes an
LoDTensor; our sequence ops use the padded+lengths representation
(ops/sequence.py), so it takes an explicit ``lengths`` operand.
"""
from __future__ import annotations

from . import ops
from .static import nn as static_nn

__all__ = [
    "simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
    "glu", "scaled_dot_product_attention",
]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    """conv2d + pool2d (fluid/nets.py:29). ``use_cudnn`` accepted for
    signature parity; XLA owns the lowering."""
    conv_out = static_nn.conv2d(
        input, num_filters, filter_size, stride=conv_stride,
        padding=conv_padding, dilation=conv_dilation, groups=conv_groups,
        weight_attr=param_attr, bias_attr=bias_attr, activation=act,
    )
    if global_pooling:
        pool = (ops.adaptive_max_pool2d if pool_type == "max"
                else ops.adaptive_avg_pool2d)
        return pool(conv_out, output_size=1)
    pool = ops.max_pool2d if pool_type == "max" else ops.avg_pool2d
    return pool(conv_out, kernel_size=pool_size, stride=pool_stride,
                padding=pool_padding)


def _per_layer(value, n, name):
    if isinstance(value, (list, tuple)):
        if len(value) != n:
            raise ValueError(
                f"{name} list length {len(value)} != number of conv "
                f"layers {n}")
        return list(value)
    return [value] * n


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """Serial conv(+bn)(+dropout) stack closed by one pool
    (fluid/nets.py:141 — the VGG building block)."""
    if not isinstance(conv_num_filter, (list, tuple)):
        raise TypeError("conv_num_filter must be a list or tuple")
    n = len(conv_num_filter)
    paddings = _per_layer(conv_padding, n, "conv_padding")
    filter_sizes = _per_layer(conv_filter_size, n, "conv_filter_size")
    with_bn = _per_layer(conv_with_batchnorm, n, "conv_with_batchnorm")
    drop_rates = _per_layer(conv_batchnorm_drop_rate, n,
                            "conv_batchnorm_drop_rate")
    attrs = _per_layer(param_attr, n, "param_attr")

    tmp = input
    for i in range(n):
        # conv act is deferred to after BN when BN follows (reference
        # local_conv_act logic)
        local_act = None if with_bn[i] else conv_act
        tmp = static_nn.conv2d(
            tmp, conv_num_filter[i], filter_sizes[i], padding=paddings[i],
            weight_attr=attrs[i],
            bias_attr=False if with_bn[i] else None,
            activation=local_act,
        )
        if with_bn[i]:
            tmp = static_nn.batch_norm(tmp)
            if conv_act:
                tmp = getattr(ops, conv_act)(tmp)
            if drop_rates[i]:
                tmp = static_nn.dropout(tmp, dropout_prob=drop_rates[i])
    pool = ops.max_pool2d if pool_type == "max" else ops.avg_pool2d
    return pool(tmp, kernel_size=pool_size, stride=pool_stride)


def sequence_conv_pool(input, lengths, num_filters, filter_size,
                       param_attr=None, act="sigmoid", pool_type="max",
                       bias_attr=None):
    """sequence_conv + sequence_pool (fluid/nets.py:256).

    input: [B, T, H] padded batch; lengths: [B] valid lengths (the ragged
    redesign of the reference's LoDTensor input).
    """
    in_hidden = input.shape[-1]
    w = static_nn.create_parameter(
        [filter_size * in_hidden, num_filters], str(input.dtype),
        initializer=param_attr)
    conv_out = ops.sequence_conv(input, lengths, w,
                                 context_length=filter_size)
    if bias_attr is not False:
        b = static_nn.create_parameter(
            [num_filters], str(input.dtype), initializer=bias_attr,
            is_bias=True)
        conv_out = ops.add(conv_out, b)
    if act:
        conv_out = getattr(ops, act)(conv_out)
    return ops.sequence_pool(conv_out, lengths, pooltype=pool_type.upper())


def glu(input, dim=-1):
    """Gated linear unit: split in half along ``dim``, a * sigmoid(b)
    (fluid/nets.py:328)."""
    a, b = ops.split(input, 2, axis=dim)
    return ops.multiply(a, ops.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Batched multi-head scaled-dot-product attention
    (fluid/nets.py:372). q [N, Lq, dk*h], k [N, Lk, dk*h],
    v [N, Lk, dv*h] -> [N, Lq, dv*h]. With num_heads == 1 no projection
    is applied, exactly like the reference.
    """
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError(
            "queries and keys must have the same feature size, got "
            f"{queries.shape[-1]} vs {keys.shape[-1]}")
    if keys.shape[1] != values.shape[1]:
        raise ValueError(
            "keys and values must have the same sequence length, got "
            f"{keys.shape[1]} vs {values.shape[1]}")
    if queries.shape[-1] % num_heads or values.shape[-1] % num_heads:
        raise ValueError(
            f"hidden sizes (q {queries.shape[-1]}, v {values.shape[-1]}) "
            f"must be divisible by num_heads ({num_heads})")
    q, k, v = queries, keys, values
    if num_heads > 1:
        from .static.program import in_static_mode

        if not in_static_mode():
            raise RuntimeError(
                "scaled_dot_product_attention(num_heads > 1) creates "
                "implicit projection parameters and is static-graph only "
                "(matching the reference, fluid/nets.py:372); in dygraph "
                "use nn.MultiHeadAttention instead")
        q = static_nn.fc(q, q.shape[-1], num_flatten_dims=2,
                         bias_attr=False)
        k = static_nn.fc(k, k.shape[-1], num_flatten_dims=2,
                         bias_attr=False)
        v = static_nn.fc(v, v.shape[-1], num_flatten_dims=2,
                         bias_attr=False)

    def split_heads(x):
        b, l, hd = x.shape
        b = -1 if b is None else b  # static data vars declare batch None
        x = ops.reshape(x, [b, l, num_heads, hd // num_heads])
        return ops.transpose(x, [0, 2, 1, 3])  # [B, H, L, D]

    def merge_heads(x):
        x = ops.transpose(x, [0, 2, 1, 3])
        b, l, h, d = x.shape
        b = -1 if b is None else b
        return ops.reshape(x, [b, l, h * d])

    qh, kh, vh = split_heads(q), split_heads(k), split_heads(v)
    dk = qh.shape[-1]
    scores = ops.matmul(qh, kh, transpose_y=True)
    scores = ops.scale(scores, scale=float(dk) ** -0.5)
    weights = ops.softmax(scores)
    if dropout_rate:
        weights = ops.dropout(weights, p=dropout_rate)
    return merge_heads(ops.matmul(weights, vh))
