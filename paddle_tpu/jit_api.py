"""paddle.jit — dygraph-to-static + save/load.

Reference parity: python/paddle/fluid/dygraph/jit.py (@declarative /
to_static, jit.save, jit.load) and dygraph_to_static/ (22 files of AST
rewriting).

TPU-native collapse: the reference rewrites Python AST into a ProgramDesc
because its eager mode can't be captured; our eager API is mode-aware
(paddle_tpu.ops._run) and traceable, so
- to_static == compile the eager callable with the functionalization layer
  (no AST surgery; python control flow is handled by JAX tracing rules),
- save == run the callable once in static mode over symbolic Variables,
  which *is* the program capture, then save_inference_model,
- load == load_inference_model wrapped back into a callable layer.
"""
from __future__ import annotations

import numpy as np

from .framework import jit as fjit
from .framework.tensor import Tensor
from .nn.layer_base import Layer

__all__ = ["to_static", "save", "load", "InputSpec", "TranslatedLayer"]


class InputSpec:
    """paddle.static.InputSpec (fluid/dygraph/static_runner InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    @classmethod
    def from_tensor(cls, t, name=None):
        return cls(list(t.shape), str(t.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


class StaticFunction:
    """@to_static wrapper: jit-compiles the eager callable per signature."""

    def __init__(self, function, input_spec=None):
        # AST pass (dygraph_to_static/): rewrite value-dependent python
        # control flow into lax.cond/while_loop converter calls so
        # data-dependent if/while compiles instead of failing the trace
        from .dygraph_to_static import convert_to_static

        bound_self = getattr(function, "__self__", None)
        base = getattr(function, "__func__", function)
        transformed = convert_to_static(base)
        if transformed is not base:
            function = (
                transformed.__get__(bound_self)
                if bound_self is not None else transformed
            )
        self._function = function
        self._input_spec = input_spec
        self._compiled = {}

    def __call__(self, *args, **kwargs):
        import jax

        layer = getattr(self._function, "__self__", None)
        if isinstance(layer, Layer):
            model, fwd = layer, type(layer).forward
        else:
            model, fwd = None, self._function

        arrays = tuple(
            a._array if isinstance(a, Tensor) else a for a in args
        )
        if model is None:
            key = "fn"
            if key not in self._compiled:
                def pure(*arrs):
                    wrapped = [
                        Tensor._from_array(a) if hasattr(a, "dtype") else a
                        for a in arrs
                    ]
                    out = fwd(*wrapped, **kwargs)
                    import jax as _jax

                    return _jax.tree_util.tree_map(
                        lambda x: x._array if isinstance(x, Tensor) else x,
                        out,
                        is_leaf=lambda x: isinstance(x, Tensor),
                    )

                self._compiled[key] = jax.jit(pure)
            out = self._compiled[key](*arrays)
        else:
            if "model" not in self._compiled:
                orig_forward = self._function
                # bypass Layer.__call__ → our own wrapper recursion: call
                # the captured original bound forward
                self._compiled["model"] = fjit.eval_step(
                    model, fn=lambda m, *a: orig_forward(*a)
                )
            out = self._compiled["model"](*arrays)
        return jax.tree_util.tree_map(Tensor._from_array, out)


def to_static(function=None, input_spec=None, **kwargs):
    """@paddle.jit.to_static decorator."""
    def deco(fn):
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward, input_spec)
            return fn
        return StaticFunction(fn, input_spec)

    if function is not None:
        return deco(function)
    return deco


def save(layer, path, input_spec=None):
    """paddle.jit.save: capture the layer as a static inference program.

    Runs the forward once in static mode over symbolic Variables — the
    mode-aware op API appends the program — then saves model+params in the
    inference-model layout loadable by paddle.jit.load AND the inference
    Predictor (analysis_predictor parity).
    """
    from . import static

    if input_spec is None:
        raise ValueError("jit.save requires input_spec")
    was_training = getattr(layer, "training", False)
    if isinstance(layer, Layer):
        layer.eval()
    # a to_static-wrapped layer: capture through the original forward
    call = layer
    if isinstance(layer, Layer) and isinstance(
        getattr(layer, "forward", None), StaticFunction
    ):
        call = layer.forward._function
    prog = static.Program()
    startup = static.Program()
    feed_names = []
    try:
        with static.program_guard(prog, startup):
            static.enable_static()
            feeds = []
            for i, spec in enumerate(input_spec):
                name = spec.name or f"x{i}"
                feed_names.append(name)
                shape = [d if d is not None else -1 for d in spec.shape]
                feeds.append(static.data(name, shape, spec.dtype))
            outs = call(*feeds)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
    finally:
        static.disable_static()
        if isinstance(layer, Layer) and was_training:
            layer.train()

    exe = static.Executor()
    import os

    dirname = path if os.path.isdir(path) or not os.path.splitext(path)[1] else os.path.dirname(path)
    static.save_inference_model(
        dirname or path, feed_names, list(outs), exe, main_program=prog
    )
    return dirname or path


class TranslatedLayer(Layer):
    """jit.load result: a Layer running a saved inference program."""

    def __init__(self, dirname):
        super().__init__()
        from . import static

        self._exe = static.Executor()
        self._program, self._feed_names, self._fetch_names = (
            static.load_inference_model(dirname, self._exe)
        )

    def forward(self, *args):
        feed = {}
        for name, a in zip(self._feed_names, args):
            feed[name] = a.numpy() if isinstance(a, Tensor) else np.asarray(a)
        outs = self._exe.run(
            self._program, feed=feed, fetch_list=self._fetch_names,
            return_numpy=False,
        )
        return outs[0] if len(outs) == 1 else tuple(outs)


def load(path):
    return TranslatedLayer(path)
