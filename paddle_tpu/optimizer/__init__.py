"""Optimizers.

Reference parity: python/paddle/fluid/optimizer.py:56 (Optimizer base,
minimize) + operators/optimizers/*.cc update kernels (sgd, momentum, adam,
adamax, adagrad, adadelta, rmsprop, lamb). TPU-native: each update rule is a
pure jnp function over (param, grad, accumulators) — applied eagerly per
tensor, or traced into the one fused XLA module when the train step is
functionalized (framework/jit.py). Optimizer state is exposed as arrays so
jitted steps can thread it as data.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..framework.autograd import no_grad
from ..framework.tensor import Tensor
from . import lr as lr  # noqa: F401
from .lr import LRScheduler

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Adadelta",
    "RMSProp", "Adamax", "Lamb", "lr",
    "ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
    "L1Decay", "L2Decay",
]


# -- gradient clipping (fluid/clip.py) --------------------------------------


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        return [(p, jnp.clip(g, self.min, self.max)) for p, g in params_grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            norm = jnp.sqrt(jnp.sum(g * g))
            factor = jnp.where(norm > self.clip_norm, self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, g * factor))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        if not params_grads:
            return params_grads
        global_sq = sum(jnp.sum(g.astype(jnp.float32) ** 2) for _, g in params_grads)
        gnorm = jnp.sqrt(global_sq)
        factor = jnp.where(
            gnorm > self.clip_norm, self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0
        )
        return [(p, g * factor.astype(g.dtype)) for p, g in params_grads]


# -- regularizers (fluid/regularizer.py) ------------------------------------


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, param, grad):
        return grad + self.coeff * param


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, param, grad):
        return grad + self.coeff * jnp.sign(param)


def _resolve_weight_decay(weight_decay):
    if weight_decay is None:
        return None
    if isinstance(weight_decay, (int, float)):
        return L2Decay(float(weight_decay))
    return weight_decay


# -- base -------------------------------------------------------------------


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if parameters is None:
            raise ValueError("parameters must be provided (dygraph mode)")
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._weight_decay = _resolve_weight_decay(weight_decay)
        self._grad_clip = grad_clip
        # accumulators: name -> list of jnp arrays aligned with parameters
        self._accumulators: dict[str, list] = {}
        self._global_step = 0
        # set by framework/jit.py to thread a traced lr through a compiled
        # step instead of baking a python float into the XLA module
        self._lr_override = None

    # accumulator helpers ---------------------------------------------------
    def _ensure_accumulator(self, name, like_fn=None):
        if name not in self._accumulators:
            self._accumulators[name] = [
                (like_fn(p) if like_fn else jnp.zeros(p._array.shape, p._array.dtype))
                for p in self._parameter_list
            ]
        return self._accumulators[name]

    def get_lr(self):
        if self._lr_override is not None:
            return self._lr_override
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def clear_grad(self):
        for p in self._parameter_list:
            p.grad = None

    clear_gradients = clear_grad

    def _fused_decay_coeff(self):
        """L2-decay coefficient an optimizer's fused update kernel will
        fold in itself (``None``: decay is pre-applied to the grad here
        in ``step()``, the historical path). Only optimizers with a
        fused pallas update override this (Momentum)."""
        return None

    # main entry points -----------------------------------------------------
    @no_grad()
    def step(self):
        # when the update kernel fuses L2 decay (Momentum on the fused
        # path), skip the separate decay pass here — but only for params
        # without a per-param regularizer (those keep their own)
        fused_wd = self._fused_decay_coeff()
        params_grads = []
        for i, p in enumerate(self._parameter_list):
            if p.grad is None or not getattr(p, "trainable", True):
                continue
            g = p.grad._array.astype(p._array.dtype)
            if self._weight_decay is not None and getattr(p, "regularizer", None) is None \
                    and not isinstance(self, AdamW):
                if fused_wd is None:
                    g = self._weight_decay(p._array, g)
            elif getattr(p, "regularizer", None) is not None:
                g = p.regularizer(p._array, g)
            params_grads.append(((i, p), g))
        if self._grad_clip is not None:
            clipped = self._grad_clip([(ip, g) for ip, g in params_grads])
            params_grads = clipped
        lr_value = self.get_lr()
        self._global_step += 1
        for (i, p), g in params_grads:
            new_param = self._apply_one(i, p._array, g, lr_value)
            # keep the param dtype stable: scalar math (e.g. beta**t under
            # x64) must not silently upcast master weights
            if new_param.dtype != p._array.dtype:
                new_param = new_param.astype(p._array.dtype)
            p._array = new_param

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def _apply_one(self, index, param, grad, lr):
        raise NotImplementedError

    # state dict ------------------------------------------------------------
    def state_dict(self):
        out = {"global_step": self._global_step}
        for name, accs in self._accumulators.items():
            for i, a in enumerate(accs):
                out[f"{name}_{i}"] = np.asarray(a)
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        self._global_step = int(state.get("global_step", 0))
        names = {k.rsplit("_", 1)[0] for k in state if k not in ("global_step", "LR_Scheduler")}
        for name in names:
            accs = []
            i = 0
            while f"{name}_{i}" in state:
                accs.append(jnp.asarray(state[f"{name}_{i}"]))
                i += 1
            if accs:
                self._accumulators[name] = accs
        if "LR_Scheduler" in state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])


# -- concrete optimizers ----------------------------------------------------


class SGD(Optimizer):
    """operators/optimizers/sgd_op.cc"""

    def _apply_one(self, index, param, grad, lr):
        return param - lr * grad


class Momentum(Optimizer):
    """operators/optimizers/momentum_op.cc (+ use_nesterov).

    The update runs through the fused pallas momentum/weight-decay
    kernel (``ops/pallas/optimizer_update.py``) behind
    ``FLAGS_use_fused_optimizer``: one VMEM pass, param/velocity updated
    in place on TPU; the jnp fallback computes the identical expression
    (bit-compatible), so eager and compiled steps agree everywhere.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _fused_decay_coeff(self):
        from ..flags import flag

        # decay folds into the kernel only when it is a plain L2Decay
        # and no grad clip exists (clipping must see the decayed grad —
        # deferring decay past the clip would change numerics)
        if (not flag("use_fused_optimizer") or self._grad_clip is not None
                or type(self._weight_decay) is not L2Decay
                or not self._weight_decay.coeff):
            return None
        return self._weight_decay.coeff

    def _apply_one(self, index, param, grad, lr):
        from ..flags import flag

        vel = self._ensure_accumulator("velocity")
        if flag("use_fused_optimizer"):
            from ..ops.pallas import fused_momentum_update

            wd = self._fused_decay_coeff() or 0.0
            if wd and getattr(self._parameter_list[index], "regularizer",
                              None) is not None:
                wd = 0.0  # per-param regularizer already applied in step()
            new_p, vel[index] = fused_momentum_update(
                param, grad, vel[index], lr, momentum=self._momentum,
                weight_decay=wd, use_nesterov=self._use_nesterov)
            return new_p
        v = self._momentum * vel[index] + grad
        vel[index] = v
        if self._use_nesterov:
            return param - lr * (grad + self._momentum * v)
        return param - lr * v


class Adam(Optimizer):
    """operators/optimizers/adam_op.cc"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _apply_one(self, index, param, grad, lr):
        m = self._ensure_accumulator("moment1")
        v = self._ensure_accumulator("moment2")
        t = self._global_step
        m[index] = self._beta1 * m[index] + (1 - self._beta1) * grad
        v[index] = self._beta2 * v[index] + (1 - self._beta2) * grad * grad
        mhat = m[index] / (1 - self._beta1**t)
        vhat = v[index] / (1 - self._beta2**t)
        return param - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)


class AdamW(Adam):
    """Decoupled weight decay (reference: fluid AdamW via optimizer.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, grad_clip=None, name=None,
                 apply_decay_param_fun=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, name=name)
        self._wd_coeff = float(weight_decay) if isinstance(weight_decay, (int, float)) \
            else getattr(weight_decay, "coeff", 0.0)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _apply_one(self, index, param, grad, lr):
        p = self._parameter_list[index]
        decay = True
        if self._apply_decay_param_fun is not None:
            decay = self._apply_decay_param_fun(p.name)
        new_param = super()._apply_one(index, param, grad, lr)
        if decay and self._wd_coeff:
            new_param = new_param - lr * self._wd_coeff * param
        return new_param


class Adagrad(Optimizer):
    """operators/optimizers/adagrad_op.cc"""

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _apply_one(self, index, param, grad, lr):
        acc = self._ensure_accumulator(
            "moment", lambda p: jnp.full(p._array.shape, self._init_acc, p._array.dtype))
        acc[index] = acc[index] + grad * grad
        return param - lr * grad / (jnp.sqrt(acc[index]) + self._epsilon)


class Adadelta(Optimizer):
    """operators/optimizers/adadelta_op.cc"""

    def __init__(self, learning_rate=1.0, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _apply_one(self, index, param, grad, lr):
        avg_sq = self._ensure_accumulator("avg_squared_grad")
        avg_up = self._ensure_accumulator("avg_squared_update")
        avg_sq[index] = self._rho * avg_sq[index] + (1 - self._rho) * grad * grad
        update = -jnp.sqrt((avg_up[index] + self._epsilon) / (avg_sq[index] + self._epsilon)) * grad
        avg_up[index] = self._rho * avg_up[index] + (1 - self._rho) * update * update
        return param + lr * update


class RMSProp(Optimizer):
    """operators/optimizers/rmsprop_op.cc"""

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _apply_one(self, index, param, grad, lr):
        ms = self._ensure_accumulator("mean_square")
        mom = self._ensure_accumulator("momentum")
        ms[index] = self._rho * ms[index] + (1 - self._rho) * grad * grad
        if self._centered:
            mg = self._ensure_accumulator("mean_grad")
            mg[index] = self._rho * mg[index] + (1 - self._rho) * grad
            denom = ms[index] - mg[index] ** 2 + self._epsilon
        else:
            denom = ms[index] + self._epsilon
        mom[index] = self._momentum * mom[index] + lr * grad / jnp.sqrt(denom)
        return param - mom[index]


class Adamax(Optimizer):
    """operators/optimizers/adamax_op.cc"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _apply_one(self, index, param, grad, lr):
        m = self._ensure_accumulator("moment")
        inf_norm = self._ensure_accumulator("inf_norm")
        t = self._global_step
        m[index] = self._beta1 * m[index] + (1 - self._beta1) * grad
        inf_norm[index] = jnp.maximum(self._beta2 * inf_norm[index], jnp.abs(grad))
        lr_t = lr / (1 - self._beta1**t)
        return param - lr_t * m[index] / (inf_norm[index] + self._epsilon)


class Lamb(Optimizer):
    """operators/optimizers/lamb_op.cc — layerwise adaptive large-batch opt."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _apply_one(self, index, param, grad, lr):
        m = self._ensure_accumulator("moment1")
        v = self._ensure_accumulator("moment2")
        t = self._global_step
        m[index] = self._beta1 * m[index] + (1 - self._beta1) * grad
        v[index] = self._beta2 * v[index] + (1 - self._beta2) * grad * grad
        mhat = m[index] / (1 - self._beta1**t)
        vhat = v[index] / (1 - self._beta2**t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        wd = self._lamb_wd
        p_obj = self._parameter_list[index]
        if self._exclude_fn is not None and self._exclude_fn(p_obj):
            wd = 0.0
        update = r + wd * param
        w_norm = jnp.sqrt(jnp.sum(param**2))
        u_norm = jnp.sqrt(jnp.sum(update**2))
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return param - lr * trust * update


# wrapper optimizers (fluid/optimizer.py:3411,3102,4822) — imported last so
# wrappers.py can see Optimizer on the partially-initialized package
from .wrappers import (  # noqa: E402
    ExponentialMovingAverage, ModelAverage, Lookahead, LookaheadOptimizer,
)

__all__ += ["ExponentialMovingAverage", "ModelAverage", "Lookahead",
            "LookaheadOptimizer"]
