"""Learning-rate schedulers.

Reference parity: python/paddle/optimizer/lr_scheduler.py +
fluid/dygraph/learning_rate_scheduler.py. Schedulers are host-side state
(a float per step); functionalized train steps read lr as a traced scalar
input so schedule changes don't retrigger compilation.
"""
from __future__ import annotations

import math


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = learning_rate
        self.step()

    def get_lr(self):
        raise NotImplementedError

    def __call__(self):
        return self.last_lr

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state["last_epoch"]
        self.last_lr = state["last_lr"]


class NoamDecay(LRScheduler):
    """lr = base * d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)."""

    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        return (
            self.base_lr
            * self.d_model**-0.5
            * min(step**-0.5, step * self.warmup_steps**-1.5)
        )


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1, verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (max(self.last_epoch, 0) // self.step_size)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1, verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma**n


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** max(self.last_epoch, 0)


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * max(self.last_epoch, 0))


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * max(self.last_epoch, 0))


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        if self.cycle and step > 0:
            cycles = math.ceil(step / self.decay_steps)
            decay_steps = self.decay_steps * cycles
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        frac = (1 - step / decay_steps) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0.0, last_epoch=-1, verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        return (
            self.eta_min
            + (self.base_lr - self.eta_min) * (1 + math.cos(math.pi * step / self.T_max)) / 2
        )


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr, last_epoch=-1, verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.target = learning_rate if not isinstance(learning_rate, LRScheduler) else None
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(end_lr, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        if step < self.warmup_steps:
            return self.start_lr + (self.end_lr - self.start_lr) * step / self.warmup_steps
        if self.lr_sched is not None:
            return self.lr_sched.last_lr
        return self.target

    def step(self, epoch=None):
        if self.lr_sched is not None and self.last_epoch >= self.warmup_steps:
            self.lr_sched.step(epoch)
        super().step(epoch)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(max(self.last_epoch, 0))


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, cooldown=0, min_lr=0.0, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.cooldown_counter = 0
        self.best = None
        self.num_bad_epochs = 0
        self.base_lr = learning_rate
        self.last_lr = learning_rate
        self.last_epoch = 0
        self.verbose = verbose

    def get_lr(self):
        return self.last_lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        current = float(metrics.item() if hasattr(metrics, "item") else metrics)
        self.last_epoch += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
            return
        better = (
            self.best is None
            or (self.mode == "min" and current < self.best - self.threshold)
            or (self.mode == "max" and current > self.best + self.threshold)
        )
        if better:
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
            if self.num_bad_epochs > self.patience:
                self.last_lr = max(self.last_lr * self.factor, self.min_lr)
                self.cooldown_counter = self.cooldown
                self.num_bad_epochs = 0
