"""Wrapper optimizers: EMA / ModelAverage / Lookahead.

Reference parity: python/paddle/fluid/optimizer.py:3411
(ExponentialMovingAverage), :3102 (ModelAverage over the
average_accumulates op, operators/average_accumulates_op.h:40), :4822
(LookaheadOptimizer, arXiv:1907.08610).

TPU-native redesign: the reference builds auxiliary static programs
(apply_program / restore_program) and mutates scope variables through an
executor. Here the shadow state lives as plain jnp arrays next to the
dygraph parameters, the update rules are pure elementwise expressions XLA
fuses into the step, and apply()/restore() swap arrays in place — no
program cloning, no scope.

Compiled-step composition: ``Lookahead`` is an ``Optimizer`` whose whole
state (slow weights + the inner optimizer's accumulators + the step
counter) lives in the ``_accumulators``/``_global_step`` store that
framework/jit.py threads through the pure step function, so it trains
correctly under ``TrainStepFn`` (the k-step sync is a data-dependent
``jnp.where``, not a trace-time branch). ``ExponentialMovingAverage`` and
``ModelAverage`` read the *live eager* parameter arrays: under a compiled
step those are only refreshed by ``step.sync()``, so call ``sync()``
before ``update()``/``accumulate()`` (or run them eagerly).

The reference classes are static-graph only (they raise in dygraph); our
primary imperative mode is dygraph, so these take an explicit parameter
list (or a Layer). ``apply(...)`` keeps the executor-shaped signature for
migration ergonomics but the executor argument is optional and unused.
"""
from __future__ import annotations

from contextlib import contextmanager

import numpy as np

import jax.numpy as jnp

from ..framework.autograd import no_grad
from . import Optimizer

__all__ = ["ExponentialMovingAverage", "ModelAverage", "Lookahead",
           "LookaheadOptimizer"]


def _resolve_parameters(parameters):
    """Accept a Layer, an iterable of Tensors, or None."""
    if parameters is None:
        raise ValueError(
            "parameters must be provided (a Layer or a list of Tensors); "
            "the reference's static-graph variants collect them from the "
            "default program, which has no dygraph counterpart")
    if hasattr(parameters, "parameters") and callable(parameters.parameters):
        parameters = parameters.parameters()
    out = [p for p in parameters
           if getattr(p, "do_model_average", None) is not False]
    return out


class _ParamSwap:
    """Shared apply()/restore() protocol over a ``_target_values()`` hook."""

    _backup = None

    def _target_values(self):
        raise NotImplementedError

    @contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap parameters for the averaged values; restore on exit."""
        if self._backup is not None:
            raise RuntimeError(
                "apply() is already active; nested apply() would clobber the "
                "backup and restore() would reinstate averaged weights")
        self._backup = [p._array for p in self._parameters]
        for p, v in zip(self._parameters, self._target_values()):
            p._array = v.astype(p._array.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._parameters, self._backup):
            p._array = b
        self._backup = None


class ExponentialMovingAverage(_ParamSwap):
    """EMA of parameters with bias correction and decay scheduling.

    fluid/optimizer.py:3411: ``ema_t = decay * ema_{t-1} + (1-decay) * p_t``
    applied as ``ema_t / (1 - decay^t)`` (zero-init bias correction). With
    ``thres_steps`` (an int-like step count) the effective decay is
    ``min(decay, (1 + thres_steps) / (10 + thres_steps))`` —
    fluid/optimizer.py:3568 (_get_ema_decay).
    """

    def __init__(self, parameters=None, decay=0.999, thres_steps=None,
                 name=None):
        self._parameters = _resolve_parameters(parameters)
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._name = name or ""
        self._step = 0
        # product of per-step decays: with scheduling, decay varies per
        # update, so the bias-correction denominator is 1 - prod(decay_t),
        # which reduces to 1 - decay**t for a constant rate.
        self._decay_prod = 1.0
        self._ema = [jnp.zeros_like(p._array) for p in self._parameters]
        self._backup = None

    def _current_decay(self):
        if self._thres_steps is not None:
            t = float(self._thres_steps() if callable(self._thres_steps)
                      else self._thres_steps)
            return min(self._decay, (1.0 + t) / (10.0 + t))
        return self._decay

    def update(self):
        """Fold the current parameter values into the moving averages."""
        d = self._current_decay()
        self._step += 1
        self._decay_prod *= d
        self._ema = [
            (e * d + p._array.astype(e.dtype) * (1.0 - d))
            for e, p in zip(self._ema, self._parameters)
        ]

    def _target_values(self):
        if self._step == 0:
            # no update() yet: the shadow is still zero-init, so the
            # averaged weights ARE the live weights (ModelAverage's
            # total == 0 path behaves the same way)
            return [p._array for p in self._parameters]
        denom = 1.0 - self._decay_prod
        return [e / denom for e in self._ema]

    def state_dict(self):
        out = {"step": self._step, "decay_prod": self._decay_prod}
        for i, e in enumerate(self._ema):
            out[f"ema_{i}"] = np.asarray(e)
        return out

    def set_state_dict(self, state):
        self._step = int(state["step"])
        self._decay_prod = float(state["decay_prod"])
        self._ema = [jnp.asarray(state[f"ema_{i}"])
                     for i in range(len(self._ema)) if f"ema_{i}" in state]


class ModelAverage(_ParamSwap):
    """Windowed parameter averaging (Polyak-style with restarts).

    fluid/optimizer.py:3102 + operators/average_accumulates_op.h:40. Three
    rolling sums per parameter; the window restarts when
    ``num_accumulates >= min_average_window`` and
    ``num_accumulates >= min(max_average_window,
    num_updates * average_window_rate)``; every 16384 updates sum_1 is
    drained into sum_2 to bound float accumulation error. apply() installs
    ``(sum_1+sum_2+sum_3) / (num_accumulates + old_num_accumulates)``.
    """

    _MAX_NUM_ACCUMULATES = 16384  # average_accumulates_op.h:45

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._parameters = _resolve_parameters(parameters)
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        if self.min_average_window > self.max_average_window:
            raise ValueError("min_average_window must be <= max_average_window")
        f32 = lambda p: jnp.zeros(p._array.shape, jnp.float32)
        self._sum_1 = [f32(p) for p in self._parameters]
        self._sum_2 = [f32(p) for p in self._parameters]
        self._sum_3 = [f32(p) for p in self._parameters]
        self.num_updates = 0
        self.num_accumulates = 0
        self.old_num_accumulates = 0
        self._backup = None

    def accumulate(self):
        """Fold current parameters into the window (call once per step)."""
        self.num_updates += 1
        self.num_accumulates += 1
        self._sum_1 = [s + p._array.astype(jnp.float32)
                       for s, p in zip(self._sum_1, self._parameters)]
        if self.num_updates % self._MAX_NUM_ACCUMULATES == 0:
            self._sum_2 = [s2 + s1 for s2, s1 in zip(self._sum_2, self._sum_1)]
            self._sum_1 = [jnp.zeros_like(s) for s in self._sum_1]
        window = min(self.max_average_window,
                     self.num_updates * self.average_window)
        if (self.num_accumulates >= self.min_average_window
                and self.num_accumulates >= window):
            self._sum_3 = [s1 + s2 for s1, s2 in zip(self._sum_1, self._sum_2)]
            self._sum_1 = [jnp.zeros_like(s) for s in self._sum_1]
            self._sum_2 = [jnp.zeros_like(s) for s in self._sum_2]
            self.old_num_accumulates = self.num_accumulates
            self.num_accumulates = 0

    # the reference hooks accumulation into the optimizer's apply pass;
    # dygraph callers do `opt.step(); model_average.accumulate()`. step()
    # is provided as an alias so it can also be chained like an optimizer.
    step = accumulate
    update = accumulate

    def _target_values(self):
        total = self.num_accumulates + self.old_num_accumulates
        if total == 0:
            return [p._array for p in self._parameters]
        return [
            (s1 + s2 + s3) / float(total)
            for s1, s2, s3 in zip(self._sum_1, self._sum_2, self._sum_3)
        ]

    def state_dict(self):
        out = {
            "num_updates": self.num_updates,
            "num_accumulates": self.num_accumulates,
            "old_num_accumulates": self.old_num_accumulates,
        }
        for name, sums in (("sum_1", self._sum_1), ("sum_2", self._sum_2),
                           ("sum_3", self._sum_3)):
            for i, s in enumerate(sums):
                out[f"{name}_{i}"] = np.asarray(s)
        return out

    def set_state_dict(self, state):
        self.num_updates = int(state["num_updates"])
        self.num_accumulates = int(state["num_accumulates"])
        self.old_num_accumulates = int(state["old_num_accumulates"])
        n = len(self._parameters)
        self._sum_1 = [jnp.asarray(state[f"sum_1_{i}"]) for i in range(n)]
        self._sum_2 = [jnp.asarray(state[f"sum_2_{i}"]) for i in range(n)]
        self._sum_3 = [jnp.asarray(state[f"sum_3_{i}"]) for i in range(n)]


class Lookahead(Optimizer):
    """Lookahead wrapper (fluid/optimizer.py:4822, arXiv:1907.08610).

    The inner optimizer updates fast weights every step; every ``k`` steps
    the slow weights move ``slow += alpha * (fast - slow)`` and the fast
    weights are reset to them.

    Functionalization contract (framework/jit.py): ALL state — the slow
    weights (``_accumulators["slow"]``), the inner optimizer's accumulators
    (step() points the inner at this object's store before delegating), and
    the shared step counter (``_global_step``) — lives in the fields
    ``_swapped_opt`` threads through the pure step, and the k-step sync is
    a data-dependent ``jnp.where`` on the traced counter, so one XLA module
    serves every step. The inner optimizer's own attributes are left
    untouched (saved/restored around the delegated step) so no tracers leak
    into it.
    """

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        if not (isinstance(k, int) and k > 0):
            raise ValueError("k should be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._parameter_list = list(inner_optimizer._parameter_list)
        self._accumulators = inner_optimizer._accumulators
        self._learning_rate = inner_optimizer._learning_rate
        self._weight_decay = None
        self._grad_clip = None
        self._global_step = inner_optimizer._global_step
        self._lr_override = None

    def get_lr(self):
        if self._lr_override is not None:
            return self._lr_override
        return self.inner_optimizer.get_lr()

    def set_lr(self, value):
        self.inner_optimizer.set_lr(value)

    def _init_accumulator_values(self):
        """jit hook: slow weights start as a copy of the fast weights (the
        reference's startup-program assign, fluid/optimizer.py:4928)."""
        return {"slow": [jnp.asarray(p._array, jnp.float32)
                         for p in self._parameter_list]}

    @no_grad()
    def step(self):
        inner = self.inner_optimizer
        slow = self._ensure_accumulator(
            "slow", like_fn=lambda p: jnp.asarray(p._array, jnp.float32))
        saved = (inner._accumulators, inner._global_step, inner._lr_override)
        try:
            # thread the (possibly swapped-in traced) state into the inner
            inner._accumulators = self._accumulators
            inner._global_step = self._global_step
            inner._lr_override = self.get_lr()
            inner.step()
            self._global_step = inner._global_step
        finally:
            (inner._accumulators, inner._global_step,
             inner._lr_override) = saved
        sync = (jnp.asarray(self._global_step) % self.k) == 0
        for i, p in enumerate(self._parameter_list):
            s = slow[i]
            fast = p._array.astype(s.dtype)
            new_s = jnp.where(sync, s + self.alpha * (fast - s), s)
            slow[i] = new_s
            p._array = jnp.where(sync, new_s, fast).astype(p._array.dtype)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad


# reference-era alias (fluid/optimizer.py:4822 class name)
LookaheadOptimizer = Lookahead
