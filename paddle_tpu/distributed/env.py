"""Process/device environment.

Reference parity: python/paddle/fluid/dygraph/parallel.py ParallelEnv
(rank/world-size/device from PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS
env) and python/paddle/distributed/parallel.py init_parallel_env.

TPU-native: a single python process drives all local TPU chips (single-
controller); multi-host pods run one process per host, coordinated by
jax.distributed. "rank" therefore means *process* index (host), and
device-level parallelism is expressed with meshes, not ranks.
"""
from __future__ import annotations

import os

import jax

_initialized = False


class ParallelEnv:
    """Mirrors dygraph/parallel.py:ParallelEnv env-variable surface."""

    def __init__(self):
        self.rank = int(os.getenv("PADDLE_TRAINER_ID", os.getenv("RANK", "0")))
        self.world_size = int(
            os.getenv("PADDLE_TRAINERS_NUM", os.getenv("WORLD_SIZE", "1"))
        )
        endpoints = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self.trainer_endpoints = endpoints.split(",") if endpoints else []
        self.current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank

    @property
    def dev_id(self):
        return int(os.getenv("FLAGS_selected_tpus", "0").split(",")[0])


def _distributed_client_active() -> bool:
    """Whether jax.distributed.initialize already ran — checked WITHOUT
    touching the XLA backend (jax.process_count() would initialize it,
    which forbids a later jax.distributed.initialize)."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def get_rank() -> int:
    if jax.process_count() > 1:
        return jax.process_index()
    return ParallelEnv().rank


def get_world_size() -> int:
    if jax.process_count() > 1:
        return jax.process_count()
    return ParallelEnv().world_size


def init_parallel_env():
    """Initialize multi-host coordination (c_comm_init / init_parallel_env
    equivalent). Single-host: no-op. Multi-host: jax.distributed handshake
    using the coordinator from env (replaces gen_nccl_id RPC rendezvous,
    operators/collective/c_gen_nccl_id_op.cc).

    Must run before any backend-initializing JAX call — like the
    reference, where c_comm_init precedes every collective; fleet.init()
    calls this first thing.
    """
    global _initialized
    if _initialized:
        return ParallelEnv()
    env = ParallelEnv()
    coordinator = os.getenv("PADDLE_COORDINATOR", "")
    if env.world_size > 1 and coordinator and not _distributed_client_active():
        if os.getenv("JAX_PLATFORMS", "").strip() == "cpu":
            # CPU multi-process needs an explicit cross-host collectives
            # transport (the reference's Gloo CPU path,
            # framework/fleet/gloo_wrapper.h:106); TPU rides ICI/DCN and
            # needs nothing here.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=env.world_size,
            process_id=env.rank,
        )
    _initialized = True
    # fault-diagnosis wiring rides the same entry point the reference
    # hung c_comm_init on: every initialized process records the world it
    # joined and arms whatever FLAGS ask for (crash/SIGUSR1 dumps always;
    # hang watchdog behind FLAGS_watchdog_timeout_s; /debugz endpoint
    # behind FLAGS_debug_port, bound at port+rank)
    from ..monitor import flight_recorder as _flight

    _flight.record_event("init_parallel_env", rank=env.rank,
                         world=env.world_size,
                         coordinator=coordinator or None)
    try:
        _flight.install_from_flags()
    except Exception as e:  # diagnosis must never block training startup
        import warnings

        warnings.warn(f"fault-diagnosis install failed: "
                      f"{type(e).__name__}: {e}", RuntimeWarning)
    return env


class DataParallel:
    """paddle.DataParallel (fluid/dygraph/parallel.py:225) on the
    single-controller runtime.

    The reference wraps a Layer so each process all-reduces coalesced
    gradients after backward (parallel.py:386 apply_collective_grads).
    Here one process drives every local device and gradient averaging is
    GSPMD's job inside the sharded step, so the wrapper forwards
    transparently and scale_loss/apply_collective_grads keep the API as
    no-ops with exact semantics (world averaging happens in-step).
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        self._layers = layers

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss  # the compiled step's global-mean loss already scales

    def apply_collective_grads(self):
        pass  # gradient sync is in-program (GSPMD), not a post-hoc pass

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)
