"""Quantized (int8 + per-block scales) DP gradient all-reduce.

EQuARX-style (PAPERS.md): DP gradient sync pays full fp32 wire bytes for
values whose useful precision is far lower. This module moves gradients
across the ICI as int8 with one f32 abs-max scale per
``FLAGS_quantized_allreduce_block`` elements, in the classic two-phase
shape:

1. **reduce-scatter phase** — each rank's quantized payload is
   ``alltoall``'d so every rank holds all n ranks' int8 contribution for
   ITS shard; it dequantizes and accumulates in f32 (no int8 overflow,
   no precision loss in the reduction itself);
2. **all-gather phase** — the f32 shard sum re-quantizes to int8 + fresh
   scales and is ``all_gather``'d, so every rank ends with the identical
   dequantized global sum.

Wire bytes per link: ``2·(n-1)/n · (B/4)·(1 + 4/block)`` — ~3.99× less
than the fp32 all-reduce's ``2·(n-1)/n · B`` at the default block of
2048 (scale overhead 0.2%). Both phases route through
:mod:`paddle_tpu.distributed.collective`, so the reduction lands in the
SAME algorithmic-bytes ledger (``collective/<prim>/traced_algo_bytes``)
and ``ici_bus_util`` gauges that certify every other collective — the
quant smoke asserts the ≥3.5× cut from ledger deltas, not from a model.

Two execution paths, one accounting contract:

- **bound-axis SPMD** (inside ``shard_map``/``pmap``, the multi-
  controller deployment): the real ``lax`` collectives run.
- **single-controller / GSPMD** (eager, or a jit trace where mesh axes
  are not bound — this runtime's ShardedTrainStep, whose fp32 gradient
  sync is GSPMD-implicit): the collectives are identity transforms, so
  the path simulates exactly the numerics the SPMD program computes —
  the two quantization hops — and accounts exactly the wire bytes it
  would move (trace-time only, the ledger's standing rule; eager calls
  account nothing, as always).

The hook into training is ``sync_grads``: ``TrainStepFn``/
``ShardedTrainStep`` route gradients through it when
``FLAGS_quantized_allreduce`` is set at step CONSTRUCTION, and the BERT
smoke asserts loss-curve convergence vs fp32 (tools/quant_smoke.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..flags import flag
from ..framework.tensor import Tensor
from . import collective as _coll
from .collective import ReduceOp, _account, _axes, _group_size, _valid_axes

__all__ = [
    "quantize_blockwise", "dequantize_blockwise", "quantized_all_reduce",
    "sync_grads", "wire_bytes_per_step",
]

_BNT = 127.0
_EPS = 1e-8


def _block_size(override=None) -> int:
    b = int(override if override is not None
            else flag("quantized_allreduce_block"))
    if b < 1:
        from ..errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"quantized_allreduce_block must be >= 1, got {b}")
    return b


def _absmax_quantize(blocks):
    """``[nblk, block]`` f32 → (int8 values, f32 per-block abs-max
    scales) — THE quantize step of both wire hops (one definition so
    the contribution and shard-sum hops can never drift numerically).
    An all-zero block quantizes against the ``1e-8`` floor instead of a
    0 scale (dequantizing by 0 is NaN/inf — same hazard the PTQ
    calibration clamps)."""
    scale = jnp.maximum(jnp.max(jnp.abs(blocks), axis=1), _EPS)
    q = jnp.round(jnp.clip(blocks / scale[:, None] * _BNT, -_BNT, _BNT))
    return q.astype(jnp.int8), scale


def quantize_blockwise(x, block_size=None, pad_multiple=1):
    """Flatten ``x`` and quantize per block: ``(q int8 [nblk, block],
    scales f32 [nblk], meta)``.

    Blocks pad with zeros up to ``block · lcm`` so that ``nblk`` is a
    multiple of ``pad_multiple`` (the group size — both collective
    phases shard on the block axis).
    """
    x = x._array if isinstance(x, Tensor) else jnp.asarray(x)
    block = _block_size(block_size)
    n = int(x.size)
    flat = x.astype(jnp.float32).reshape(-1)
    nblk = max(1, -(-n // block))
    nblk = -(-nblk // pad_multiple) * pad_multiple
    padded = nblk * block
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    q, scale = _absmax_quantize(flat.reshape(nblk, block))
    return q, scale, (tuple(x.shape), str(x.dtype), n)


def dequantize_blockwise(q, scale, meta):
    """Inverse of :func:`quantize_blockwise` (original shape + dtype)."""
    shape, dtype, n = meta
    out = (q.astype(jnp.float32) * (scale / _BNT)[:, None]).reshape(-1)
    return out[:n].reshape(shape).astype(dtype)


def _axes_bound(axes) -> bool:
    """True when the mesh axes are BOUND in the current context
    (shard_map/pmap body) — the only place real lax collectives can
    run. Plain jit (GSPMD) and eager both raise on axis_index."""
    try:
        for ax in axes:
            jax.lax.axis_index(ax)
        return True
    except Exception:
        return False


def quantized_all_reduce(tensor, group=None, block_size=None,
                         average=False):
    """All-reduce ``tensor`` over the group's mesh axes with int8 wire
    precision (per-block f32 scales). See the module docstring for the
    two-phase shape and the accounting contract. ``average=True``
    divides the reduced SUM by the group size — only where a real sum
    happened (the bound-axis SPMD branch); on the single-controller
    identity path the global view already IS the mean, matching
    ``collective.all_reduce(op=AVG)``'s identity convention.

    Numerics: the result carries exactly two quantization roundings
    (contribution + shard-sum), each bounded by half a block step —
    convergence-neutral for DP gradient sync at int8 (asserted vs fp32
    on the BERT smoke).
    """
    arr = tensor._array if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    axes = _valid_axes(_axes(group))
    n = _group_size(group)
    q, scale, meta = quantize_blockwise(arr, block_size, pad_multiple=n)
    nblk = q.shape[0]

    if n > 1 and _axes_bound(axes):
        # real SPMD wire path: alltoall the contributions, reduce the
        # local shard in f32, requantize, all-gather the shard results
        q_all = _coll.alltoall(q, group=group)
        s_all = _coll.alltoall(scale, group=group)
        parts = q_all.reshape(n, nblk // n, q.shape[1])
        scales = s_all.reshape(n, nblk // n)
        shard = jnp.sum(
            parts.astype(jnp.float32) * (scales / _BNT)[..., None], axis=0)
        sq, sscale = _absmax_quantize(shard)
        q2 = _coll.all_gather(None, sq, group=group).reshape(
            nblk, q.shape[1])
        s2 = _coll.all_gather(None, sscale, group=group).reshape(nblk)
        out = dequantize_blockwise(q2, s2, meta)
        if average:
            out = out / n
    else:
        # single-controller / GSPMD: the collectives are identity
        # transforms; compute the SAME two quantization hops the SPMD
        # program applies and account the SAME wire bytes it would move
        # (no-op _account contexts on identically-shaped payloads; the
        # ledger only records under tracing, exactly as for every other
        # collective)
        with _account("alltoall", q, group):
            pass
        with _account("alltoall", scale, group):
            pass
        shard = q.astype(jnp.float32) * (scale / _BNT)[:, None]
        sq, sscale = _absmax_quantize(shard)
        with _account("all_gather", sq[: nblk // n], group):
            pass
        with _account("all_gather", sscale[: nblk // n], group):
            pass
        out = dequantize_blockwise(sq, sscale, meta)
    if isinstance(tensor, Tensor):
        tensor._array = out
        return tensor
    return out


def sync_grads(grads, group=None, average=False, block_size=None,
               quantized=None):
    """Gradient-sync entry the train steps route through.

    ``quantized=None`` reads ``FLAGS_quantized_allreduce``; fp32 mode is
    one :func:`collective.all_reduce` per leaf (the ledger baseline the
    smoke compares against), int8 mode is :func:`quantized_all_reduce`.
    Works on any pytree of gradient arrays.
    """
    use_q = (bool(flag("quantized_allreduce")) if quantized is None
             else bool(quantized))
    if use_q:
        return jax.tree_util.tree_map(
            lambda g: quantized_all_reduce(
                g, group=group, block_size=block_size, average=average),
            grads)
    op = ReduceOp.AVG if average else ReduceOp.SUM
    return jax.tree_util.tree_map(
        lambda g: _coll.all_reduce(g, op=op, group=group), grads)


def wire_bytes_per_step(snapshot_before, snapshot_after) -> int:
    """Sum the per-execution gradient-sync wire bytes between two
    ``monitor.registry_snapshot()``s (all ``collective/*/
    traced_algo_bytes`` deltas) — the ledger arithmetic the quant smoke
    and bench use to certify the fp32→int8 byte cut."""
    total = 0
    for name, m in snapshot_after.items():
        if not name.endswith("/traced_algo_bytes"):
            continue
        before = snapshot_before.get(name, {}).get("value", 0)
        total += int(m["value"] - before)
    return total
