"""Fault injection for chaos testing (``FLAGS_fault_injection``).

Production preemption tolerance is only real if every recovery path has
been exercised by a real process death. This module is the hook the
chaos harness (tools/chaos_smoke.py, tests/fixtures/dist_elastic.py)
drives: well-known code points call :func:`inject` and, when the flag
carries a matching directive, the process is killed (``kill`` = SIGKILL
to self, the genuine ``kill -9``), exits hard (``exit`` = os._exit, no
atexit/teardown), sleeps (``delay`` — straggler emulation), or raises
:class:`ChaosInjected` (``raise`` — in-process failure without dying).

Directive grammar (';'-separated, each ``action:key=val,key=val``):

    kill:point=step,step=3          SIGKILL self at train step 3
    kill:point=step,step=3,rank=1   ... only on rank 1
    delay:point=step,step=2,ms=250  sleep 250ms before step 2
    kill:point=mid_save,n=2         die inside the 2nd checkpoint save
    raise:point=mid_save,n=1        fail the 1st save, keep the process

Points are where the runtime calls ``inject``: ``step`` (train-step
boundary — hapi.Model.fit and the elastic fixtures) and ``mid_save``
(inside the checkpoint writer, after data files are written but before
the manifest publish — the torn-snapshot window crash-consistent
rotation must survive). Each directive fires at most once per process.
The empty flag (default) short-circuits to a single flag read.
"""
from __future__ import annotations

import os
import signal
import time

from ..flags import flag

__all__ = ["ChaosInjected", "inject", "parse", "reset"]

_ACTIONS = ("kill", "exit", "delay", "raise")
_POINTS = ("step", "mid_save")


class ChaosInjected(RuntimeError):
    """Raised by a ``raise`` directive — a survivable injected failure."""


# (raw flag value, parsed directives) + per-process fire bookkeeping
_PARSED: tuple = ("", [])
_FIRED: set = set()
_OCCURRENCES: dict = {}


def parse(spec: str):
    """Parse a directive string; raises InvalidArgumentError on garbage
    (a chaos run with a typo'd spec must fail loudly, not test nothing)."""
    from ..errors import InvalidArgumentError

    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        action, _, kvs = part.partition(":")
        action = action.strip()
        if action not in _ACTIONS:
            raise InvalidArgumentError(
                f"fault_injection: unknown action {action!r} in {part!r} "
                f"(known: {_ACTIONS})")
        d = {"action": action}
        for kv in kvs.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, sep, v = kv.partition("=")
            if not sep:
                raise InvalidArgumentError(
                    f"fault_injection: expected key=value, got {kv!r}")
            d[k.strip()] = v.strip()
        if d.get("point") not in _POINTS:
            raise InvalidArgumentError(
                f"fault_injection: directive {part!r} needs point="
                f"{'|'.join(_POINTS)}")
        for k in ("step", "rank", "n", "code"):
            if k in d:
                try:
                    d[k] = int(d[k])
                except ValueError:
                    raise InvalidArgumentError(
                        f"fault_injection: {k}={d[k]!r} is not an int")
        if "ms" in d:
            try:
                d["ms"] = float(d["ms"])
            except ValueError:
                raise InvalidArgumentError(
                    f"fault_injection: ms={d['ms']!r} is not a number")
        out.append(d)
    return out


def reset():
    """Forget fired/occurrence state (tests)."""
    global _PARSED
    _PARSED = ("", [])
    _FIRED.clear()
    _OCCURRENCES.clear()


def inject(point: str, step=None, rank=None):
    """Fire any matching directive at this code point.

    ``step`` is the caller's step counter (matched against ``step=N``
    directives); ``n`` directives match the Nth time this *point* is
    reached in this process. ``rank`` defaults to the process's
    distributed rank.
    """
    raw = flag("fault_injection")
    if not raw:
        return
    global _PARSED
    if _PARSED[0] != raw:
        _PARSED = (raw, parse(raw))
        _FIRED.clear()
        _OCCURRENCES.clear()
    n = _OCCURRENCES[point] = _OCCURRENCES.get(point, 0) + 1
    for i, d in enumerate(_PARSED[1]):
        if d["point"] != point or i in _FIRED:
            continue
        if "rank" in d and d["rank"] != _current_rank(rank):
            continue
        if "step" in d and (step is None or d["step"] != int(step)):
            continue
        if "n" in d and d["n"] != n:
            continue
        _FIRED.add(i)
        _fire(d, point, step)


def _current_rank(rank):
    if rank is not None:
        return int(rank)
    from ..monitor import flight_recorder as _flight

    return _flight._safe_rank()


def _fire(d, point, step):
    action = d["action"]
    try:
        from ..monitor import flight_recorder as _flight
        from ..monitor import registry as _reg

        _flight.record_event("fault_injected", action=action, point=point,
                             step=-1 if step is None else int(step))
        _reg.counter(f"chaos/{action}").inc()
    except Exception:
        pass  # chaos must fire even if telemetry is half-torn-down
    if action == "delay":
        time.sleep(float(d.get("ms", 100.0)) / 1000.0)
    elif action == "raise":
        raise ChaosInjected(
            f"fault_injection: injected failure at {point} (step={step})")
    elif action == "exit":
        os._exit(int(d.get("code", 17)))
    elif action == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
