"""paddle.distributed equivalent — user-facing distributed API.

Reference parity: python/paddle/distributed/ (collective.py, fleet/,
launch.py, parallel.py ParallelEnv). The TPU-native runtime underneath is
paddle_tpu.parallel (mesh + GSPMD) instead of NCCL rings + transpilers.
"""
from . import collective  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    barrier,
    broadcast,
    reduce,
    reduce_scatter,
    scatter,
    send,
    recv,
    alltoall,
    new_group,
)
from .env import DataParallel  # noqa: F401
from .env import (  # noqa: F401
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
)
from . import fleet  # noqa: F401
from .fleet import DistributedStrategy  # noqa: F401
from .launch import spawn  # noqa: F401
from . import elastic  # noqa: F401  (heartbeat monitor + restart driver)
from . import checkpoint  # noqa: F401  (async reshardable snapshots)
from . import chaos  # noqa: F401  (FLAGS_fault_injection hooks)
from . import quantized  # noqa: F401  (int8 gradient all-reduce)
from .quantized import quantized_all_reduce  # noqa: F401
