"""Trainer-side PS embedding — the distributed_lookup_table equivalent.

Reference parity: operators/distributed_ops/distributed_lookup_table_op.cc
(forward pulls rows by id) + the transpiler-inserted send ops that ship
the sparse gradient back after backward (distribute_transpiler.py:256),
and geo_sgd_transpiler.py for geo mode.

TPU-native split: the DENSE math of the step stays on the TPU (eager or
compiled); the sparse pull/push is host-side numpy against the table
shards. The pulled rows enter autograd as a leaf tensor, so the row
gradient falls out of loss.backward() with no extra machinery; push_step
ships it. This keeps the giant table off the chip — the point of PS mode
— while the per-batch working set rides the normal device path.
"""
from __future__ import annotations

import numpy as np

from ...framework.tensor import Tensor
from ...nn.layer_base import Layer
from ... import ops
from .client import ShardedTable

__all__ = ["PSEmbedding", "GeoPSEmbedding"]


class PSEmbedding(Layer):
    """Sync/async-mode PS embedding.

    forward(ids) pulls the batch's unique rows from the table shards and
    gathers on device; after loss.backward(), ``push_step(lr)`` ships the
    accumulated row gradients (one server-side update per unique id).
    Sync mode is obtained by calling ``table-server barrier`` between
    steps via fleet (the trainer loop in tests shows the pattern).
    """

    def __init__(self, table: ShardedTable):
        super().__init__()
        self.table = table
        self._pending = []  # (unique_ids, rows_tensor)

    def forward(self, ids):
        ids_np = np.asarray(
            ids.numpy() if isinstance(ids, Tensor) else ids, np.int64
        )
        uniq, inverse = np.unique(ids_np, return_inverse=True)
        rows = self.table.pull(uniq)  # [U, dim] host pull
        rows_t = Tensor(rows, stop_gradient=False)
        self._pending.append((uniq, rows_t))
        idx_t = Tensor(inverse.reshape(ids_np.shape).astype(np.int64))
        return ops.embedding(idx_t, rows_t)

    def push_step(self, lr):
        """Ship row grads from the last backward; clears the pull cache."""
        for uniq, rows_t in self._pending:
            g = rows_t.grad
            if g is not None:
                self.table.push_grad(uniq, np.asarray(g.numpy()), lr)
        self._pending.clear()


class GeoPSEmbedding(Layer):
    """Geo-SGD-mode PS embedding (geo_sgd_transpiler.py semantics).

    The trainer keeps a LOCAL replica of the rows it touches and applies
    SGD locally every step (fast, no network on the hot path). Every
    ``k_steps`` trainer steps, the accumulated delta (local - base) is
    pushed to the server (which ADDS it — deltas from different trainers
    merge additively) and fresh rows are pulled back.
    """

    def __init__(self, table: ShardedTable, k_steps=4):
        super().__init__()
        self.table = table
        self.k_steps = int(k_steps)
        self._local = {}   # id -> current local row
        self._base = {}    # id -> row value at last sync
        self._pending = []
        self._step = 0

    def _local_rows(self, uniq):
        missing = [i for i in uniq if int(i) not in self._local]
        if missing:
            pulled = self.table.pull(np.asarray(missing, np.int64))
            for i, r in zip(missing, pulled):
                self._local[int(i)] = r.copy()
                self._base[int(i)] = r.copy()
        return np.stack([self._local[int(i)] for i in uniq])

    def forward(self, ids):
        ids_np = np.asarray(
            ids.numpy() if isinstance(ids, Tensor) else ids, np.int64
        )
        uniq, inverse = np.unique(ids_np, return_inverse=True)
        rows_t = Tensor(self._local_rows(uniq), stop_gradient=False)
        self._pending.append((uniq, rows_t))
        idx_t = Tensor(inverse.reshape(ids_np.shape).astype(np.int64))
        return ops.embedding(idx_t, rows_t)

    def push_step(self, lr):
        """Local SGD update; every k-th call syncs deltas with the PS."""
        for uniq, rows_t in self._pending:
            g = rows_t.grad
            if g is None:
                continue
            g = np.asarray(g.numpy())
            for j, i in enumerate(uniq):
                self._local[int(i)] = self._local[int(i)] - lr * g[j]
        self._pending.clear()
        self._step += 1
        if self._step % self.k_steps == 0:
            self._sync()

    def _sync(self):
        if not self._local:
            return
        ids = np.asarray(sorted(self._local), np.int64)
        delta = np.stack(
            [self._local[int(i)] - self._base[int(i)] for i in ids]
        )
        self.table.push_delta(ids, delta)
        fresh = self.table.pull(ids)
        for i, r in zip(ids, fresh):
            self._local[int(i)] = r.copy()
            self._base[int(i)] = r.copy()
