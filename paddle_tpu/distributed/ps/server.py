"""Parameter-server runtime: the table server.

Reference parity: paddle/fluid/operators/distributed/ — rpc_server.h
(request_handler loop), large_scale_kv.h (lazily-initialized sparse
rows + per-row optimizer state), listen_and_serv_op.cc (the server op),
and the sync barrier of the sync-mode transpiler
(distribute_transpiler.py:256).

TPU-native redesign: the PS holds what does NOT belong on a TPU chip —
huge, sparsely-touched embedding tables living in host RAM. The transport
is a plain length-prefixed-pickle TCP loop (python threads; the grpc/brpc
machinery of the reference collapses because there are no zero-copy GPU
buffers to negotiate — rows are small numpy slabs). Dense parameters stay
on the TPU path (collectives over ICI); ONLY the sparse half goes through
the PS, which is also the reference's recommended large-scale layout.

Row updates:
- sync/async ("sgd"/"adagrad"): trainers push per-row gradients, the
  server applies the update rule under the table lock; sync mode adds a
  per-step named barrier so all trainers' pushes land before the next
  pull (the Barrier monitor of distribute_transpiler sync mode).
- geo ("delta"): trainers train a local replica and push accumulated
  deltas; the server adds them (geo_sgd_transpiler.py semantics).
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading

import numpy as np

__all__ = ["TableServer", "serve_forever"]


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack("<q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return pickle.loads(bytes(buf))


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=4)
    sock.sendall(struct.pack("<q", len(payload)) + payload)


class _Table:
    """One sparse table: id -> (row, opt_state), lazily initialized
    (large_scale_kv.h's init-on-first-touch)."""

    def __init__(self, dim, init_std=0.01, optimizer="sgd", seed=0):
        self.dim = int(dim)
        self.init_std = float(init_std)
        self.optimizer = optimizer
        self.rows = {}
        self.accum = {}  # adagrad state
        self.lock = threading.RLock()
        self._rng = np.random.RandomState(seed)

    def _row(self, i):
        r = self.rows.get(i)
        if r is None:
            r = (self._rng.randn(self.dim) * self.init_std).astype(
                np.float32
            )
            self.rows[i] = r
        return r

    def pull(self, ids):
        with self.lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push_grad(self, ids, grads, lr):
        with self.lock:
            # duplicate ids in one push: accumulate (reference
            # MergeAdd semantics for SelectedRows)
            uniq = {}
            for i, g in zip(ids, grads):
                i = int(i)
                uniq[i] = uniq.get(i, 0.0) + g
            for i, g in uniq.items():
                row = self._row(i)
                if self.optimizer == "adagrad":
                    a = self.accum.setdefault(
                        i, np.zeros(self.dim, np.float32)
                    )
                    a += g * g
                    row -= lr * g / (np.sqrt(a) + 1e-6)
                else:  # sgd
                    row -= lr * g

    def push_delta(self, ids, deltas):
        with self.lock:
            for i, d in zip(ids, deltas):
                self._row(int(i))
                self.rows[int(i)] = self.rows[int(i)] + d

    def dump(self):
        with self.lock:
            if not self.rows:
                return np.zeros(0, np.int64), np.zeros(
                    (0, self.dim), np.float32
                )
            ids = np.asarray(sorted(self.rows), np.int64)
            return ids, np.stack([self.rows[int(i)] for i in ids])


class TableServer:
    """listen_and_serv_op equivalent: a threaded TCP table service."""

    def __init__(self, port=0, host="127.0.0.1"):
        self._tables = {}
        self._tables_lock = threading.RLock()
        self._barriers = {}  # token -> [count, threading.Condition]
        self._barrier_lock = threading.Lock()
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.endpoint = "%s:%d" % self._sock.getsockname()[:2]
        self._threads = []

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def join(self):
        """Block until shutdown (Fleet.run_server's serve loop)."""
        self._stop.wait()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # -- serving -------------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn):
        try:
            while not self._stop.is_set():
                msg = _recv_msg(conn)
                if msg is None:
                    return
                try:
                    reply = self._handle(msg)
                except Exception as e:  # structured error back to client
                    reply = ("err", f"{type(e).__name__}: {e}")
                _send_msg(conn, reply)
                if msg[0] == "shutdown":
                    return
        finally:
            conn.close()

    def _handle(self, msg):
        op = msg[0]
        if op == "create_table":
            _, name, dim, init_std, optimizer = msg
            with self._tables_lock:
                if name not in self._tables:
                    self._tables[name] = _Table(dim, init_std, optimizer)
                t = self._tables[name]
                if t.dim != int(dim):
                    raise ValueError(
                        f"table {name!r} exists with dim {t.dim}"
                    )
            return ("ok", None)
        if op == "pull":
            _, name, ids = msg
            return ("ok", self._tables[name].pull(ids))
        if op == "push_grad":
            _, name, ids, grads, lr = msg
            self._tables[name].push_grad(ids, grads, lr)
            return ("ok", None)
        if op == "push_delta":
            _, name, ids, deltas = msg
            self._tables[name].push_delta(ids, deltas)
            return ("ok", None)
        if op == "dump":
            _, name = msg
            return ("ok", self._tables[name].dump())
        if op == "barrier":
            _, token, n = msg
            self._barrier(token, int(n))
            return ("ok", None)
        if op == "stats":
            with self._tables_lock:
                return ("ok", {
                    name: len(t.rows) for name, t in self._tables.items()
                })
        if op == "shutdown":
            self.stop()
            return ("ok", None)
        raise ValueError(f"unknown PS op {op!r}")

    def _barrier(self, token, n):
        """Named n-party barrier (sync-mode per-step fence). A shutdown
        while parties are parked ABORTS the fence with an error — a
        success reply would silently void the sync-mode guarantee."""
        with self._barrier_lock:
            ent = self._barriers.setdefault(
                token, [0, threading.Condition(self._barrier_lock)]
            )
            ent[0] += 1
            if ent[0] >= n:
                self._barriers.pop(token, None)
                ent[1].notify_all()
                return
            cond = ent[1]
            while token in self._barriers and not self._stop.is_set():
                cond.wait(timeout=0.5)
            if self._stop.is_set() and token in self._barriers:
                raise RuntimeError(
                    f"barrier {token!r} aborted: server shutting down "
                    f"with {ent[0]}/{n} parties arrived"
                )


def serve_forever(port=0, host="127.0.0.1", ready_cb=None):
    """Blocking entry for a dedicated server process."""
    srv = TableServer(port=port, host=host).start()
    if ready_cb is not None:
        ready_cb(srv.endpoint)
    srv.join()
    return srv
