"""Parameter-server runtime: the table server.

Reference parity: paddle/fluid/operators/distributed/ — rpc_server.h
(request_handler loop), large_scale_kv.h (lazily-initialized sparse
rows + per-row optimizer state), listen_and_serv_op.cc (the server op),
and the sync barrier of the sync-mode transpiler
(distribute_transpiler.py:256).

TPU-native redesign: the PS holds what does NOT belong on a TPU chip —
huge, sparsely-touched embedding tables living in host RAM. The transport
is a length-prefixed TCP loop (python threads; the grpc/brpc machinery of
the reference collapses because there are no zero-copy GPU buffers to
negotiate — rows are small numpy slabs) carrying a fixed type-tagged
binary codec: struct-packed scalars/strings plus raw C-order numpy bytes,
mirroring the role of the reference's protobuf schema
(operators/distributed/send_recv.proto.in). Deserialization never
constructs code objects — no pickle anywhere on the wire — so a hostile
peer that reaches the port can at worst read/write table rows, never
execute code. Dense parameters stay on the TPU path (collectives over
ICI); ONLY the sparse half goes through the PS, which is also the
reference's recommended large-scale layout.

Trust model: the server binds loopback by default; binding a routable
address puts the table contents (not the host) at risk — run it inside
the training network perimeter exactly as the reference's brpc PS expects.

Row updates:
- sync/async ("sgd"/"adagrad"): trainers push per-row gradients, the
  server applies the update rule under the table lock; sync mode adds a
  per-step named barrier so all trainers' pushes land before the next
  pull (the Barrier monitor of distribute_transpiler sync mode).
- geo ("delta"): trainers train a local replica and push accumulated
  deltas; the server adds them (geo_sgd_transpiler.py semantics).
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time

import numpy as np

from ...monitor import registry as _mon
from ...profiler import RecordEvent

__all__ = ["TableServer", "serve_forever"]


# -- wire codec -------------------------------------------------------------
# Type-tagged binary values; the decoder is a pure data parser (struct +
# np.frombuffer), so untrusted bytes cannot execute anything. Supported
# value types are exactly what the PS protocol needs: None, bool, int,
# float, str, bytes, non-object ndarray, list/tuple, dict[str, value].

_MAGIC = b"PTPS"
# reject garbage/hostile length prefixes early. Generous (256 GiB) because
# full-table dumps of host-RAM embedding tables legitimately run multi-GiB;
# the receive loop only allocates as bytes actually arrive, so a hostile
# *claimed* length alone cannot balloon memory.
_MAX_MSG = 1 << 38


def _enc_value(obj, out):
    if obj is None:
        out.append(b"N")
    elif obj is True:
        out.append(b"T")
    elif obj is False:
        out.append(b"F")
    elif isinstance(obj, (int, np.integer)):
        out.append(b"i" + struct.pack("<q", int(obj)))
    elif isinstance(obj, (float, np.floating)):
        out.append(b"f" + struct.pack("<d", float(obj)))
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.append(b"s" + struct.pack("<I", len(b)) + b)
    elif isinstance(obj, (bytes, bytearray)):
        out.append(b"b" + struct.pack("<I", len(obj)) + bytes(obj))
    elif isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            raise TypeError("object arrays are not wire-encodable")
        descr = np.lib.format.dtype_to_descr(obj.dtype).encode("ascii")
        a = np.ascontiguousarray(obj)
        out.append(
            b"a"
            + struct.pack("<B", len(descr)) + descr
            + struct.pack("<B", a.ndim)
            + struct.pack("<%dq" % a.ndim, *a.shape)
            + a.tobytes()
        )
    elif isinstance(obj, (list, tuple)):
        out.append(b"l" + struct.pack("<I", len(obj)))
        for v in obj:
            _enc_value(v, out)
    elif isinstance(obj, dict):
        out.append(b"d" + struct.pack("<I", len(obj)))
        for k, v in obj.items():
            if not isinstance(k, str):
                raise TypeError("wire dict keys must be str")
            kb = k.encode("utf-8")
            out.append(struct.pack("<I", len(kb)) + kb)
            _enc_value(v, out)
    else:
        raise TypeError(f"not wire-encodable: {type(obj).__name__}")


# container nesting cap: the decoder recurses per list/dict level, so a
# malformed message of thousands of nested "l"/"d" tags would otherwise
# raise RecursionError inside the connection thread. No protocol message
# nests beyond a handful of levels.
_MAX_NESTING = 32


def _dec_value(buf, off, depth=0):
    if depth > _MAX_NESTING:
        raise ValueError(
            f"wire container nesting exceeds {_MAX_NESTING} levels")
    tag = buf[off:off + 1]
    off += 1
    if tag == b"N":
        return None, off
    if tag == b"T":
        return True, off
    if tag == b"F":
        return False, off
    if tag == b"i":
        return struct.unpack_from("<q", buf, off)[0], off + 8
    if tag == b"f":
        return struct.unpack_from("<d", buf, off)[0], off + 8
    if tag in (b"s", b"b"):
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        if n > len(buf) - off:
            raise ValueError("string payload exceeds message bounds")
        raw = bytes(buf[off:off + n])
        return (raw.decode("utf-8") if tag == b"s" else raw), off + n
    if tag == b"a":
        (dlen,) = struct.unpack_from("<B", buf, off)
        off += 1
        dtype = np.lib.format.descr_to_dtype(
            buf[off:off + dlen].decode("ascii"))
        off += dlen
        if dtype.hasobject:
            raise ValueError("object dtype rejected on the wire")
        (ndim,) = struct.unpack_from("<B", buf, off)
        off += 1
        shape = struct.unpack_from("<%dq" % ndim, buf, off)
        off += 8 * ndim
        if any(d < 0 for d in shape):
            raise ValueError(f"negative array dim on the wire: {shape}")
        count = int(np.prod(shape, dtype=np.int64)) if ndim else 1
        nbytes = count * dtype.itemsize
        if nbytes > len(buf) - off:
            raise ValueError("array payload exceeds message bounds")
        arr = np.frombuffer(
            buf, dtype=dtype, count=count, offset=off
        ).reshape(shape).copy()  # copy: writable, detached from the buffer
        return arr, off + nbytes
    if tag == b"l":
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        items = []
        for _ in range(n):
            v, off = _dec_value(buf, off, depth + 1)
            items.append(v)
        return tuple(items), off
    if tag == b"d":
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        d = {}
        for _ in range(n):
            (klen,) = struct.unpack_from("<I", buf, off)
            off += 4
            if klen > len(buf) - off:
                raise ValueError("dict key exceeds message bounds")
            k = bytes(buf[off:off + klen]).decode("utf-8")
            off += klen
            d[k], off = _dec_value(buf, off, depth + 1)
        return d, off
    raise ValueError(f"bad wire tag {tag!r} at offset {off - 1}")


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 12:
        chunk = sock.recv(12 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    if hdr[:4] != _MAGIC:
        raise ValueError("bad PS wire magic (protocol mismatch or garbage)")
    (n,) = struct.unpack("<q", hdr[4:])
    if not 0 <= n <= _MAX_MSG:
        raise ValueError(f"implausible PS message length {n}")
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    val, off = _dec_value(bytes(buf), 0)
    if off != n:
        raise ValueError("trailing bytes in PS message")
    return val


def _send_msg(sock, obj):
    out = []
    _enc_value(obj, out)
    payload = b"".join(out)
    sock.sendall(_MAGIC + struct.pack("<q", len(payload)) + payload)


class _Table:
    """One sparse table: id -> (row, opt_state), lazily initialized
    (large_scale_kv.h's init-on-first-touch)."""

    def __init__(self, dim, init_std=0.01, optimizer="sgd", seed=0):
        self.dim = int(dim)
        self.init_std = float(init_std)
        self.optimizer = optimizer
        self.rows = {}
        self.accum = {}  # adagrad state
        self.lock = threading.RLock()
        self._rng = np.random.RandomState(seed)

    def _row(self, i):
        r = self.rows.get(i)
        if r is None:
            r = (self._rng.randn(self.dim) * self.init_std).astype(
                np.float32
            )
            self.rows[i] = r
        return r

    def pull(self, ids):
        with self.lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push_grad(self, ids, grads, lr):
        with self.lock:
            # duplicate ids in one push: accumulate (reference
            # MergeAdd semantics for SelectedRows)
            uniq = {}
            for i, g in zip(ids, grads):
                i = int(i)
                uniq[i] = uniq.get(i, 0.0) + g
            for i, g in uniq.items():
                row = self._row(i)
                if self.optimizer == "adagrad":
                    a = self.accum.setdefault(
                        i, np.zeros(self.dim, np.float32)
                    )
                    a += g * g
                    row -= lr * g / (np.sqrt(a) + 1e-6)
                else:  # sgd
                    row -= lr * g

    def push_delta(self, ids, deltas):
        with self.lock:
            for i, d in zip(ids, deltas):
                self._row(int(i))
                self.rows[int(i)] = self.rows[int(i)] + d

    def dump(self):
        with self.lock:
            if not self.rows:
                return np.zeros(0, np.int64), np.zeros(
                    (0, self.dim), np.float32
                )
            ids = np.asarray(sorted(self.rows), np.int64)
            return ids, np.stack([self.rows[int(i)] for i in ids])

    def snapshot(self):
        """Checkpoint payload (checkpoint_notify_op.cc parity): rows +
        optimizer state + config, all as plain arrays."""
        with self.lock:
            ids, rows = self.dump()
            aids = np.asarray(sorted(self.accum), np.int64)
            accum = (np.stack([self.accum[int(i)] for i in aids])
                     if len(aids) else np.zeros((0, self.dim), np.float32))
            return {
                "dim": self.dim, "init_std": self.init_std,
                "optimizer": self.optimizer,
                "ids": ids, "rows": rows,
                "accum_ids": aids, "accum": accum,
            }

    def restore(self, snap):
        with self.lock:
            if int(snap["dim"]) != self.dim:
                raise ValueError(
                    f"snapshot dim {snap['dim']} != table dim {self.dim}")
            self.rows = {
                int(i): np.asarray(r, np.float32)
                for i, r in zip(snap["ids"], snap["rows"])
            }
            self.accum = {
                int(i): np.asarray(a, np.float32)
                for i, a in zip(snap["accum_ids"], snap["accum"])
            }


# the _handle dispatch set; anything else is metric-bucketed as "unknown"
_KNOWN_OPS = frozenset((
    "create_table", "pull", "push_grad", "push_delta", "dump", "barrier",
    "stats", "save", "load", "shutdown",
))


class TableServer:
    """listen_and_serv_op equivalent: a threaded TCP table service."""

    def __init__(self, port=0, host="127.0.0.1", barrier_timeout=600.0,
                 ckpt_root=None):
        # save/load over the wire are confined to this directory; when
        # None (default) they are refused — a remote peer must never pick
        # filesystem paths (the reference's checkpoint_notify likewise
        # writes a server-side-configured dir, checkpoint_notify_op.cc)
        self._ckpt_root = (os.path.realpath(ckpt_root)
                           if ckpt_root is not None else None)
        self._tables = {}
        self._tables_lock = threading.RLock()
        self._barriers = {}  # token -> {count, cond, state, error}
        self._barrier_lock = threading.Lock()
        self._barrier_timeout = float(barrier_timeout)
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.endpoint = "%s:%d" % self._sock.getsockname()[:2]
        self._threads = []

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def join(self):
        """Block until shutdown (Fleet.run_server's serve loop)."""
        self._stop.wait()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # -- serving -------------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn):
        try:
            peer = "%s:%d" % conn.getpeername()[:2]
        except OSError:
            peer = "?"
        # a connection only counts as a protocol peer once it has decoded
        # one valid message — so a port-scanner's garbage can never abort
        # a live training fence, but a real worker whose thread dies
        # mid-session releases everyone it would otherwise strand.
        # is_barrier_peer additionally marks connections that have joined
        # at least one fence: a SIGKILLed worker produces a CLEAN EOF
        # (recv -> None), and if that worker was a fence participant the
        # waiters must be released on EOF too — but a short-lived stats
        # probe disconnecting normally must not abort anything.
        is_protocol_peer = False
        is_barrier_peer = False
        try:
            while not self._stop.is_set():
                msg = _recv_msg(conn)
                if msg is None:
                    if is_barrier_peer:
                        self._fail_pending_barriers(
                            f"peer {peer} (a fence participant) "
                            f"disconnected")
                    return
                is_protocol_peer = True
                if (isinstance(msg, tuple) and msg
                        and msg[0] == "barrier"):
                    is_barrier_peer = True
                # serve/apply accounting: per-op span + latency histogram
                # + error counter (the server-side half of the trainer's
                # ps/rpc stats — a slow or erroring table op shows up on
                # BOTH sides of the wire, or the wire itself is the cost).
                # A message that is not an (op, ...) tuple still gets the
                # structured error reply (never a bare connection drop).
                # Metric names are NEVER taken from the wire verbatim —
                # unknown/malformed ops share fixed buckets, so a hostile
                # peer cannot grow the registry unboundedly.
                op = (str(msg[0]) if isinstance(msg, tuple) and msg
                      else "malformed")
                metric_op = op if op in _KNOWN_OPS else (
                    "malformed" if op == "malformed" else "unknown")
                t0 = time.perf_counter()
                try:
                    with RecordEvent(f"ps::serve::{metric_op}"):
                        reply = self._handle(msg)
                except Exception as e:  # structured error back to client
                    _mon.counter(f"ps/serve/{metric_op}/errors").inc()
                    reply = ("err", f"{type(e).__name__}: {e}")
                _mon.histogram(f"ps/serve/{metric_op}/ms").observe(
                    (time.perf_counter() - t0) * 1e3)
                _send_msg(conn, reply)
                if op == "shutdown":
                    return
        except Exception as e:
            # the conn thread is dying mid-session (wire/decode error on
            # recv, or the reply send hit a dead socket); a barrier party
            # may be parked waiting for THIS peer's next arrival — fail
            # the fence with a diagnostic naming the dead peer instead of
            # stranding the waiters until the 600s timeout. Only fence
            # PARTICIPANTS release fences: a stats probe or scanner dying
            # (however abnormally) must never abort a live training sync.
            if is_barrier_peer:
                self._fail_pending_barriers(
                    f"peer {peer} connection died "
                    f"({type(e).__name__}: {e})")
            from ...monitor import flight_recorder as _flight

            _flight.record_event(
                "ps_conn_died", peer=peer,
                protocol_peer=is_protocol_peer,
                error=f"{type(e).__name__}: {e}"[:300])
        finally:
            conn.close()

    def _handle(self, msg):
        op = msg[0]
        if op == "create_table":
            _, name, dim, init_std, optimizer = msg
            if "/" in name or "\\" in name or ".." in name or not name:
                raise ValueError(
                    f"table name {name!r} must be a plain identifier "
                    "(it becomes a checkpoint filename)")
            with self._tables_lock:
                if name not in self._tables:
                    self._tables[name] = _Table(dim, init_std, optimizer)
                t = self._tables[name]
                if t.dim != int(dim):
                    raise ValueError(
                        f"table {name!r} exists with dim {t.dim}"
                    )
            return ("ok", None)
        if op == "pull":
            _, name, ids = msg
            return ("ok", self._tables[name].pull(ids))
        if op == "push_grad":
            _, name, ids, grads, lr = msg
            self._tables[name].push_grad(ids, grads, lr)
            return ("ok", None)
        if op == "push_delta":
            _, name, ids, deltas = msg
            self._tables[name].push_delta(ids, deltas)
            return ("ok", None)
        if op == "dump":
            _, name = msg
            return ("ok", self._tables[name].dump())
        if op == "barrier":
            _, token, n = msg
            self._barrier(token, int(n))
            return ("ok", None)
        if op == "stats":
            with self._tables_lock:
                return ("ok", {
                    name: len(t.rows) for name, t in self._tables.items()
                })
        if op == "save":
            # checkpoint_notify parity: snapshot every table to a directory
            _, dirname = msg
            dirname = self._resolve_ckpt_dir(dirname)
            os.makedirs(dirname, exist_ok=True)
            with self._tables_lock:
                for name, t in self._tables.items():
                    np.savez(os.path.join(dirname, f"{name}.npz"),
                             **t.snapshot())
            return ("ok", None)
        if op == "load":
            _, dirname = msg
            dirname = self._resolve_ckpt_dir(dirname)
            with self._tables_lock:
                # two-pass: read + validate EVERY snapshot before touching
                # any live table, so a dim mismatch on the Nth file cannot
                # leave the server half-restored
                snaps = {}
                for fn in sorted(os.listdir(dirname)):
                    if not fn.endswith(".npz"):
                        continue
                    name = fn[:-4]
                    with np.load(os.path.join(dirname, fn)) as z:
                        snaps[name] = {k: z[k] for k in z.files}
                required = ("dim", "init_std", "optimizer", "ids", "rows",
                            "accum_ids", "accum")
                for name, snap in snaps.items():
                    missing = [k for k in required if k not in snap]
                    if missing:
                        raise ValueError(
                            f"snapshot {name!r} missing keys {missing}; "
                            "no tables restored")
                    t = self._tables.get(name)
                    if t is not None and t.dim != int(snap["dim"]):
                        raise ValueError(
                            f"snapshot {name!r} dim {int(snap['dim'])} != "
                            f"live table dim {t.dim}; no tables restored")
                for name, snap in snaps.items():
                    if name not in self._tables:
                        self._tables[name] = _Table(
                            int(snap["dim"]), float(snap["init_std"]),
                            str(snap["optimizer"]))
                    self._tables[name].restore(snap)
            return ("ok", None)
        if op == "shutdown":
            self.stop()
            return ("ok", None)
        raise ValueError(f"unknown PS op {op!r}")

    def _resolve_ckpt_dir(self, dirname):
        """Confine wire-requested checkpoint paths to ckpt_root: a remote
        peer names a subdirectory, never an arbitrary host path."""
        if self._ckpt_root is None:
            raise PermissionError(
                "this server was started without ckpt_root; save/load "
                "over the wire are disabled (pass ckpt_root= to "
                "TableServer/serve_forever)")
        resolved = os.path.realpath(
            os.path.join(self._ckpt_root, str(dirname).lstrip("/\\")))
        if (resolved != self._ckpt_root
                and not resolved.startswith(self._ckpt_root + os.sep)):
            raise PermissionError(
                f"checkpoint path {dirname!r} escapes ckpt_root")
        return resolved

    def _fail_pending_barriers(self, reason):
        """Abort every in-flight fence (a peer's connection thread died:
        its future arrivals will never come). Parked waiters wake with an
        error naming the dead peer — the sync-mode guarantee fails loudly
        instead of stranding the fleet until the timeout."""
        with self._barrier_lock:
            for token, ent in list(self._barriers.items()):
                if ent["state"] != "waiting":
                    continue
                ent["state"] = "aborted"
                ent["error"] = (
                    f"barrier {token!r} aborted: {reason}; "
                    f"{ent['count']} part(ies) were waiting on the fence"
                )
                self._barriers.pop(token, None)
                ent["cond"].notify_all()

    def _barrier(self, token, n):
        """Named n-party barrier (sync-mode per-step fence).

        A shutdown OR a timeout (default 600s; mismatched tokens from a
        crashed/retried worker would otherwise park everyone forever)
        ABORTS the fence: every parked party gets an error naming the
        token and how many of n arrived — a success reply would silently
        void the sync-mode guarantee."""
        with self._barrier_lock:
            ent = self._barriers.get(token)
            if ent is None:
                ent = {"count": 0,
                       "cond": threading.Condition(self._barrier_lock),
                       "state": "waiting", "error": None}
                self._barriers[token] = ent
            ent["count"] += 1
            if ent["count"] >= n:
                ent["state"] = "done"
                self._barriers.pop(token, None)
                ent["cond"].notify_all()
                return
            deadline = time.monotonic() + self._barrier_timeout
            while ent["state"] == "waiting" and not self._stop.is_set():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                ent["cond"].wait(timeout=min(0.5, remaining))
            if ent["state"] == "done":
                return
            if ent["state"] == "waiting":  # first to notice: abort fence
                cause = ("server shutting down" if self._stop.is_set()
                         else f"timed out after {self._barrier_timeout:.0f}s")
                ent["state"] = "aborted"
                ent["error"] = (
                    f"barrier {token!r} aborted ({cause}) with "
                    f"{ent['count']}/{n} parties arrived — a worker "
                    f"crashed, retried, or called barrier_worker a "
                    f"different number of times"
                )
                # drop the token so it is reusable: parked waiters hold
                # their own `ent` reference and still see the abort; a
                # very-late straggler founds a fresh fence (which will
                # itself time out with its own diagnostic) instead of the
                # token being poisoned forever
                self._barriers.pop(token, None)
                ent["cond"].notify_all()
            raise RuntimeError(ent["error"])


def serve_forever(port=0, host="127.0.0.1", ready_cb=None, **server_kwargs):
    """Blocking entry for a dedicated server process. Extra kwargs
    (barrier_timeout, ckpt_root) are forwarded to TableServer."""
    srv = TableServer(port=port, host=host, **server_kwargs).start()
    if ready_cb is not None:
        ready_cb(srv.endpoint)
    srv.join()
    return srv
