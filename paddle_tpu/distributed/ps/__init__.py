"""Parameter-server training mode (sync/async + geo).

Reference parity map:
- table server + lazy sparse rows  → `server.py`
  (rpc_server.h, large_scale_kv.h, listen_and_serv_op.cc)
- trainer client + id-hash shards  → `client.py`
  (communicator.cc, distribute_transpiler.py sparse splits)
- lookup + grad push / geo deltas  → `embedding.py`
  (distributed_lookup_table_op.cc, geo_sgd_transpiler.py)
- fleet wiring (run_server/init_worker/a_sync strategy)
  → distributed/fleet/base.py

See tests/test_ps.py for the 1-server/2-trainer subprocess proof
(test_dist_base.py:506 pattern).
"""
from .client import PSClient, ShardedTable  # noqa: F401
from .embedding import GeoPSEmbedding, PSEmbedding  # noqa: F401
from .server import TableServer, serve_forever  # noqa: F401

__all__ = [
    "TableServer", "serve_forever", "PSClient", "ShardedTable",
    "PSEmbedding", "GeoPSEmbedding",
]
