"""Parameter-server client + sharded table view.

Reference parity: operators/distributed/communicator.cc (the trainer-side
send/recv machinery) + distributed_lookup_table_op.cc (pull rows by id
from the server holding each shard). Multiple servers shard a table by
``id % n_servers`` exactly like the reference's hash distribution
(distribute_transpiler.py _get_splited_vars for sparse tables).
"""
from __future__ import annotations

import socket
import threading
import time

import numpy as np

from ...monitor import flight_recorder as _flight
from ...monitor import registry as _mon
from ...profiler import RecordEvent
from .server import _recv_msg, _send_msg

__all__ = ["PSClient", "ShardedTable"]


class PSClient:
    """One TCP connection to one table server; thread-safe."""

    def __init__(self, endpoint, timeout=60.0):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._timeout = timeout
        self._sock = socket.create_connection(
            (host, int(port)), timeout=timeout
        )
        self._lock = threading.Lock()

    def request(self, *msg, timeout="default"):
        # trainer-side RPC accounting: round-trip latency per op (the
        # whole pull/push cost a trainer pays, wire + serve). The
        # histogram/error accounting must survive the WIRE failing —
        # a hung server (socket timeout) or dropped connection is the
        # production failure these metrics exist to diagnose.
        op = str(msg[0])
        t0 = time.perf_counter()
        # send/recv flight-record pair: a dump taken mid-hang shows which
        # RPC is in flight to which endpoint (a send with no matching
        # recv IS the stalled call), and a completed reply feeds the
        # watchdog's progress clock
        _flight.record_event("ps_rpc_send", op=op, endpoint=self.endpoint)
        try:
            with RecordEvent(f"ps::rpc::{op}"), self._lock:
                if timeout != "default":
                    self._sock.settimeout(timeout)
                try:
                    _send_msg(self._sock, msg)
                    reply = _recv_msg(self._sock)
                finally:
                    if timeout != "default":
                        self._sock.settimeout(self._timeout)
            if reply is None:
                raise ConnectionError(
                    f"PS {self.endpoint} closed connection")
            status, payload = reply
            if status != "ok":
                raise RuntimeError(f"PS {self.endpoint}: {payload}")
        except Exception as e:
            _mon.counter(f"ps/rpc/{op}/errors").inc()
            _flight.record_event(
                "ps_rpc_recv", op=op, endpoint=self.endpoint, ok=False,
                error=f"{type(e).__name__}: {e}"[:300])
            raise
        finally:
            _mon.histogram(f"ps/rpc/{op}/ms").observe(
                (time.perf_counter() - t0) * 1e3)
        _flight.record_event("ps_rpc_recv", op=op, endpoint=self.endpoint,
                             ok=True)
        _flight.notify_progress(f"ps_rpc:{op}")
        return payload

    def create_table(self, name, dim, init_std=0.01, optimizer="sgd"):
        return self.request("create_table", name, dim, init_std, optimizer)

    def pull(self, name, ids):
        return self.request("pull", name, np.asarray(ids, np.int64))

    def push_grad(self, name, ids, grads, lr):
        return self.request(
            "push_grad", name, np.asarray(ids, np.int64),
            np.asarray(grads, np.float32), float(lr),
        )

    def push_delta(self, name, ids, deltas):
        return self.request(
            "push_delta", name, np.asarray(ids, np.int64),
            np.asarray(deltas, np.float32),
        )

    def dump(self, name):
        return self.request("dump", name)

    def barrier(self, token, n, timeout=None):
        # a fence legitimately outwaits stragglers (first-step compiles,
        # preemptions) — never bound it by the ordinary RPC timeout
        try:
            return self.request("barrier", token, n, timeout=timeout)
        except Exception as e:
            # a failed fence is the PS-mode flavor of a collective
            # desync: dump the flight recorder (with the cross-rank tail
            # exchange when a side channel exists) so the post-mortem
            # names what this worker was doing when the fleet diverged
            _flight.record_event("ps_barrier_failed", token=str(token),
                                 error=f"{type(e).__name__}: {e}"[:300])
            try:
                desync = _flight.exchange_and_diagnose(
                    tag=f"barrier:{token}")
            except Exception:
                desync = None
            try:
                _flight.dump_now(reason=f"ps_barrier_failed:{token}",
                                 desync=desync)
            except Exception:
                pass
            raise

    def stats(self):
        return self.request("stats")

    def save(self, dirname):
        """checkpoint_notify parity: server snapshots all tables to dir."""
        return self.request("save", str(dirname))

    def load(self, dirname):
        return self.request("load", str(dirname))

    def shutdown_server(self):
        try:
            return self.request("shutdown")
        except (ConnectionError, OSError):
            return None

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class ShardedTable:
    """A table striped over n servers by ``id % n`` (the transpiler's
    sparse split). All PSEmbedding traffic goes through this view."""

    def __init__(self, name, dim, clients, init_std=0.01, optimizer="sgd"):
        self.name = name
        self.dim = int(dim)
        self.clients = list(clients)
        for c in self.clients:
            c.create_table(name, dim, init_std, optimizer)

    def _shard(self, ids):
        ids = np.asarray(ids, np.int64)
        n = len(self.clients)
        return [(s, np.nonzero(ids % n == s)[0]) for s in range(n)]

    def pull(self, ids):
        ids = np.asarray(ids, np.int64)
        out = np.zeros((len(ids), self.dim), np.float32)
        for s, idx in self._shard(ids):
            if len(idx):
                out[idx] = self.clients[s].pull(self.name, ids[idx])
        return out

    def push_grad(self, ids, grads, lr):
        ids = np.asarray(ids, np.int64)
        grads = np.asarray(grads, np.float32)
        for s, idx in self._shard(ids):
            if len(idx):
                self.clients[s].push_grad(
                    self.name, ids[idx], grads[idx], lr
                )

    def push_delta(self, ids, deltas):
        ids = np.asarray(ids, np.int64)
        deltas = np.asarray(deltas, np.float32)
        for s, idx in self._shard(ids):
            if len(idx):
                self.clients[s].push_delta(self.name, ids[idx], deltas[idx])

    def dump(self):
        all_ids, all_rows = [], []
        for c in self.clients:
            ids, rows = c.dump(self.name)
            all_ids.append(ids)
            all_rows.append(rows)
        ids = np.concatenate(all_ids)
        rows = (np.concatenate(all_rows) if len(ids)
                else np.zeros((0, self.dim), np.float32))
        order = np.argsort(ids)
        return ids[order], rows[order]
