"""Failure detection / elastic-training primitives.

Reference parity: operators/distributed/heart_beat_monitor.cc (the
pserver marks trainers dead after a heartbeat timeout) and the
DistributedStrategy.elastic flag (distributed_strategy.proto:105 — the
reference defers orchestration to PaddleCloud; recovery is
checkpoint-based).

TPU-native: multi-host pods have no pserver; liveness is tracked
through a shared filesystem (the checkpoint dir every host already
mounts). Each host runs a HeartbeatMonitor thread touching its beat
file; any host can list dead peers; recovery = resume from
incubate.auto_checkpoint (crash-redo semantics tested there).
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["HeartbeatMonitor", "elastic_run"]


class HeartbeatMonitor:
    """heart_beat_monitor.cc at host granularity over a shared fs."""

    def __init__(self, job_dir: str, rank: int, world_size: int,
                 interval: float = 5.0, timeout: float = 60.0):
        self.job_dir = os.path.join(job_dir, "heartbeats")
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.interval = float(interval)
        self.timeout = float(timeout)
        os.makedirs(self.job_dir, exist_ok=True)
        self._stop = threading.Event()
        self._thread = None

    def _path(self, rank):
        return os.path.join(self.job_dir, f"hb_{rank}")

    def beat(self):
        """Touch this host's beat file once."""
        with open(self._path(self.rank), "a"):
            os.utime(self._path(self.rank), None)

    def start(self):
        """Background beats every ``interval`` seconds (restartable)."""
        if self._thread is not None:
            return self
        self._stop.clear()  # a previous stop() must not kill the new thread
        self.beat()

        def loop():
            while not self._stop.wait(self.interval):
                self.beat()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 1)
            self._thread = None

    def dead_ranks(self, now=None):
        """Ranks whose last beat is older than ``timeout`` (or that never
        beat) — UpdateStatus/dead-node walk of heart_beat_monitor.cc."""
        now = time.time() if now is None else now
        dead = []
        for r in range(self.world_size):
            p = self._path(r)
            try:
                age = now - os.stat(p).st_mtime
            except FileNotFoundError:
                dead.append(r)
                continue
            if age > self.timeout:
                dead.append(r)
        return dead

    def all_alive(self):
        return not self.dead_ranks()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def elastic_run(train_fn, max_restarts: int = 3, exceptions=(Exception,)):
    """Crash-and-resume driver: run ``train_fn()`` and restart it up to
    ``max_restarts`` times on failure. Combined with the env-configured
    auto-checkpoint (incubate.auto_checkpoint), each restart resumes
    from the newest snapshot — the reference's checkpoint-based elastic
    recovery contract.
    """
    from ..errors import FatalError

    from ..incubate import auto_checkpoint as acp

    attempt = 0
    while True:
        # each attempt is a logical process restart: reset the registry so
        # a re-built Model claims the same deterministic snapshot names and
        # _load_latest restores into the new instances, not the dead ones
        acp.reset_registry()
        try:
            return train_fn()
        except exceptions as e:
            attempt += 1
            if attempt > max_restarts:
                raise FatalError(
                    f"elastic_run: giving up after {max_restarts} restarts"
                ) from e
