"""Failure detection / elastic-training primitives.

Reference parity: operators/distributed/heart_beat_monitor.cc (the
pserver marks trainers dead after a heartbeat timeout) and the
DistributedStrategy.elastic flag (distributed_strategy.proto:105 — the
reference defers orchestration to PaddleCloud; recovery is
checkpoint-based).

TPU-native: multi-host pods have no pserver; liveness is tracked
through a shared filesystem (the checkpoint dir every host already
mounts). Each host runs a HeartbeatMonitor thread touching its beat
file; any host can list dead peers; recovery = resume from
incubate.auto_checkpoint / distributed.checkpoint snapshots.

Beyond the reference (ROADMAP item 5 — preemption-tolerant *elastic*
training): the job survives a *changing* world, not just a restarted
one. A dead rank (heartbeat silence) or a persistently-flagged
straggler (:class:`StragglerTracker`, fed by ``monitor/cluster.py``
/clusterz verdicts) triggers a **world renegotiation**: the survivors
each vote their observed membership over the shared filesystem (the
heartbeat side channel), agree on the new world, and
:func:`elastic_run` re-enters the training function — which rebuilds
its mesh at the surviving size and resumes *resharded* from the last
intact snapshot (distributed/checkpoint.py) instead of running at the
straggler's pace or dying. World changes do not consume the crash-
restart budget: a resize is recovery working, not a failure.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = [
    "HeartbeatMonitor",
    "elastic_run",
    "ElasticContext",
    "ElasticWorld",
    "WorldChangedError",
    "EvictedError",
    "StragglerTracker",
    "install_straggler_eviction",
    "check_world",
    "renegotiate_world",
    "mark_evicted",
    "evicted_ranks",
]


class WorldChangedError(RuntimeError):
    """Membership changed: dead or evicted ranks were detected. Carries
    the evidence; elastic_run renegotiates and re-enters training."""

    def __init__(self, survivors, dead=(), evicted=()):
        self.survivors = sorted(survivors)
        self.dead = sorted(dead)
        self.evicted = sorted(evicted)
        super().__init__(
            f"world changed: survivors={self.survivors} "
            f"dead={self.dead} evicted={self.evicted}")


class EvictedError(RuntimeError):
    """THIS rank was evicted (persistent straggler verdict). The rank
    must leave — the survivors checkpoint around it and resize."""

    def __init__(self, rank):
        self.rank = int(rank)
        super().__init__(f"rank {rank} evicted from the training world")


class HeartbeatMonitor:
    """heart_beat_monitor.cc at host granularity over a shared fs."""

    def __init__(self, job_dir: str, rank: int, world_size: int,
                 interval: float = 5.0, timeout: float = 60.0,
                 grace: float | None = None):
        self.root = job_dir
        self.job_dir = os.path.join(job_dir, "heartbeats")
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.interval = float(interval)
        self.timeout = float(timeout)
        # startup grace: a rank that has not beaten YET (job still
        # booting, process scheduler lagging) is "not here yet", not
        # "dead" — only after `grace` seconds of total silence since
        # this monitor came up does absence become death. Defaults to
        # the heartbeat timeout.
        self.grace = self.timeout if grace is None else float(grace)
        self._born = time.time()
        os.makedirs(self.job_dir, exist_ok=True)
        self._stop = threading.Event()
        self._thread = None

    def _path(self, rank):
        return os.path.join(self.job_dir, f"hb_{rank}")

    def beat(self):
        """Touch this host's beat file once."""
        with open(self._path(self.rank), "a"):
            os.utime(self._path(self.rank), None)

    def start(self):
        """Background beats every ``interval`` seconds (restartable)."""
        if self._thread is not None:
            return self
        self._stop.clear()  # a previous stop() must not kill the new thread
        self.beat()

        def loop():
            while not self._stop.wait(self.interval):
                self.beat()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 1)
            self._thread = None

    def dead_ranks(self, now=None):
        """Ranks whose last beat is older than ``timeout`` — the
        UpdateStatus/dead-node walk of heart_beat_monitor.cc. A rank
        that never beat counts as dead only once the startup ``grace``
        has elapsed (a monitor that just came up must not declare the
        whole fleet dead before anyone had a chance to join)."""
        now = time.time() if now is None else now
        dead = []
        for r in range(self.world_size):
            p = self._path(r)
            try:
                age = now - os.stat(p).st_mtime
            except FileNotFoundError:
                if now - self._born > self.grace:
                    dead.append(r)
                continue
            if age > self.timeout:
                dead.append(r)
        return dead

    def all_alive(self):
        return not self.dead_ranks()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


# ---------------------------------------------------------------------------
# straggler eviction
# ---------------------------------------------------------------------------


class StragglerTracker:
    """Consecutive-verdict counter over /clusterz straggler flags.

    ``monitor/cluster.py`` flags a rank when its step time exceeds
    ``FLAGS_straggler_threshold`` × the cluster median; one slow tick is
    noise (GC pause, rebalancing), so eviction requires
    ``FLAGS_eviction_threshold`` *consecutive* verdicts. A clean tick
    resets the rank's streak; a rank missing from the report keeps its
    streak (absence of evidence is not health).
    """

    def __init__(self, threshold=None):
        self._threshold = threshold
        self._counts: dict[int, int] = {}
        self._lock = threading.Lock()

    @property
    def threshold(self) -> int:
        if self._threshold is not None:
            return int(self._threshold)
        from ..flags import flag

        return int(flag("eviction_threshold"))

    def observe(self, flagged, present=None):
        """Feed one verdict round: ``flagged`` ranks bump their streak,
        ranks in ``present`` but not flagged reset theirs."""
        flagged = {int(r) for r in flagged}
        with self._lock:
            for r in flagged:
                self._counts[r] = self._counts.get(r, 0) + 1
            for r in set(int(x) for x in (present or ())) - flagged:
                self._counts[r] = 0

    def streak(self, rank) -> int:
        with self._lock:
            return self._counts.get(int(rank), 0)

    def evictable(self):
        """Ranks whose streak reached the eviction threshold."""
        thr = self.threshold
        with self._lock:
            return sorted(r for r, c in self._counts.items() if c >= thr)

    def reset(self, rank=None):
        with self._lock:
            if rank is None:
                self._counts.clear()
            else:
                self._counts.pop(int(rank), None)


def install_straggler_eviction(tracker: StragglerTracker):
    """Wire /clusterz verdicts into the tracker: every
    ``clusterz_payload`` evaluation feeds one round. Returns the
    listener handle (pass to ``cluster.remove_verdict_listener``)."""
    from ..monitor import cluster as _cluster

    def _on_verdict(payload):
        tracker.observe(
            [s["rank"] for s in payload.get("stragglers", [])],
            present=[row["rank"] for row in payload.get("ranks", [])])

    _cluster.add_verdict_listener(_on_verdict)
    return _on_verdict


# ---------------------------------------------------------------------------
# world membership / renegotiation (over the shared heartbeat fs)
# ---------------------------------------------------------------------------


def _evict_dir(root):
    return os.path.join(root, "evicted")


def mark_evicted(root, rank):
    """Persist an eviction decision so every survivor (and the evicted
    rank itself, post-restart) agrees — the fs analog of the KV channel."""
    d = _evict_dir(root)
    os.makedirs(d, exist_ok=True)
    p = os.path.join(d, f"rank_{int(rank)}")
    with open(p, "a"):
        os.utime(p, None)


def evicted_ranks(root):
    try:
        names = os.listdir(_evict_dir(root))
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        if n.startswith("rank_"):
            try:
                out.append(int(n[len("rank_"):]))
            except ValueError:
                continue
    return sorted(out)


def check_world(monitor: HeartbeatMonitor, tracker: StragglerTracker = None,
                members=None):
    """One membership check, called from the training loop at step
    boundaries. Publishes fresh eviction decisions, then raises
    :class:`EvictedError` (this rank must leave) or
    :class:`WorldChangedError` (peers left — renegotiate + reshard);
    returns the current member list when nothing changed."""
    from ..monitor import flight_recorder as _flight
    from ..monitor import registry as _reg

    members = sorted(members if members is not None
                     else range(monitor.world_size))
    dead = set(monitor.dead_ranks()) & set(members)
    evicted = set(evicted_ranks(monitor.root)) & set(members)
    fresh = set()
    if tracker is not None:
        fresh = set(tracker.evictable()) & set(members) - evicted
        for r in sorted(fresh):
            mark_evicted(monitor.root, r)
            _reg.counter("elastic/evictions").inc()
            _flight.record_event("elastic_evicted", rank=r,
                                 streak=tracker.streak(r))
        evicted |= fresh
    if monitor.rank in evicted:
        raise EvictedError(monitor.rank)
    gone = (dead | evicted) & set(members)
    if gone:
        survivors = [r for r in members if r not in gone]
        raise WorldChangedError(survivors, dead=dead & gone,
                                evicted=evicted & gone)
    return members


def renegotiate_world(monitor: HeartbeatMonitor, members=None,
                      generation=1, timeout=300.0, poll=0.05):
    """Survivors agree on the new world over the shared fs.

    Each survivor recomputes the membership from live evidence
    (heartbeats + eviction markers), publishes its vote under
    ``world_gen_<generation>/vote_<rank>.json``, and polls until every
    voted survivor published the *same* set. Evidence converges (dead
    ranks stay dead past the timeout; eviction markers are persistent),
    so disagreeing votes are re-derived until they match. Returns an
    :class:`ElasticWorld` with this rank's new dense rank.
    """
    from ..errors import FatalError
    from ..monitor import flight_recorder as _flight
    from ..monitor import goodput as _goodput

    members = sorted(members if members is not None
                     else range(monitor.world_size))
    vote_dir = os.path.join(monitor.root, f"world_gen_{int(generation)}")
    os.makedirs(vote_dir, exist_ok=True)
    deadline = time.monotonic() + float(timeout)
    # renegotiation wall time is elastic badput in the goodput ledger —
    # the span closes on every exit (agreement, eviction, timeout)
    with _goodput.span("renegotiate"):
        return _renegotiate_loop(monitor, members, generation, timeout,
                                 poll, vote_dir, deadline, _flight,
                                 FatalError)


def _renegotiate_loop(monitor, members, generation, timeout, poll,
                      vote_dir, deadline, _flight, FatalError):
    generation = int(generation)  # loop-invariant (host int)
    my_vote = None
    while True:
        dead = set(monitor.dead_ranks())
        evicted = set(evicted_ranks(monitor.root))
        survivors = [r for r in members if r not in dead and r not in evicted]
        if monitor.rank not in survivors:
            raise EvictedError(monitor.rank)
        if survivors != my_vote:
            my_vote = list(survivors)
            _publish_vote(vote_dir, monitor.rank, my_vote)
        agreed = _votes_agree(vote_dir, survivors)
        if agreed is not None:
            world = ElasticWorld(
                generation=generation, survivors=agreed,
                rank=agreed.index(monitor.rank), world_size=len(agreed))
            _flight.record_event(
                "elastic_world_agreed", generation=generation,
                survivors=agreed, rank=world.rank)
            return world
        if time.monotonic() > deadline:
            raise FatalError(
                f"world renegotiation gen {generation} did not converge "
                f"within {timeout}s (my vote: {my_vote})")
        time.sleep(poll)


def _publish_vote(vote_dir, rank, survivors):
    tmp = os.path.join(vote_dir, f".vote_{rank}.tmp")
    with open(tmp, "w") as f:
        json.dump({"rank": rank, "survivors": survivors}, f)
    os.replace(tmp, os.path.join(vote_dir, f"vote_{rank}.json"))


def _votes_agree(vote_dir, survivors):
    """All survivors' votes present and identical -> the agreed list."""
    seen = []
    for r in survivors:
        try:
            with open(os.path.join(vote_dir, f"vote_{r}.json")) as f:
                seen.append(json.load(f)["survivors"])
        except (FileNotFoundError, json.JSONDecodeError, KeyError):
            return None
    if not seen or any(v != seen[0] for v in seen[1:]):
        return None
    return [int(r) for r in seen[0]]


# ---------------------------------------------------------------------------
# restart driver
# ---------------------------------------------------------------------------


class ElasticWorld:
    """An agreed membership: original rank ids of the survivors, plus
    this process's dense rank within them."""

    def __init__(self, generation, survivors, rank, world_size):
        self.generation = int(generation)
        self.survivors = [int(r) for r in survivors]
        self.rank = rank
        self.world_size = int(world_size)

    def __repr__(self):
        return (f"ElasticWorld(gen={self.generation}, rank={self.rank}/"
                f"{self.world_size}, survivors={self.survivors})")


class ElasticContext:
    """Handed to ``train_fn`` (when it accepts an argument): the live
    membership view plus the monitor/tracker for step-boundary checks."""

    def __init__(self, monitor=None, tracker=None):
        self.monitor = monitor
        self.tracker = tracker
        self.world: ElasticWorld | None = None
        self.generation = 0
        self.restarts = 0
        self.world_changes = 0

    @property
    def members(self):
        if self.world is not None:
            return list(self.world.survivors)
        if self.monitor is not None:
            return list(range(self.monitor.world_size))
        return [0]

    def check(self):
        """Raise WorldChangedError/EvictedError when membership moved;
        harmless no-op without a monitor (single-process runs)."""
        if self.monitor is None:
            return self.members
        return check_world(self.monitor, self.tracker,
                           members=self.members)


def _accepts_context(fn):
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                      p.VAR_POSITIONAL):
            return True
    return False


def elastic_run(train_fn, max_restarts: int = 3, exceptions=(Exception,),
                monitor: HeartbeatMonitor = None,
                tracker: StragglerTracker = None,
                max_world_changes: int = 32,
                renegotiate_timeout_s: float = 300.0):
    """Preemption-tolerant training driver.

    Runs ``train_fn`` (passing an :class:`ElasticContext` when it takes
    an argument) and reacts to three distinct failure classes:

    - **crash** (``exceptions``): restart, up to ``max_restarts`` times
      — combined with auto-checkpoint each restart resumes from the
      newest intact snapshot (the reference's checkpoint-based elastic
      recovery contract);
    - **world change** (:class:`WorldChangedError` raised from
      ``ctx.check()``): renegotiate the membership with the survivors
      over the heartbeat side channel and re-enter ``train_fn``, which
      rebuilds its mesh at the new size and resumes resharded. Resizes
      have their own (generous) budget — shrinking is recovery working,
      not a failure;
    - **own eviction** (:class:`EvictedError`): recorded, re-raised —
      this process must leave the job.
    """
    from ..errors import FatalError
    from ..incubate import auto_checkpoint as acp
    from ..monitor import flight_recorder as _flight
    from ..monitor import registry as _reg

    ctx = ElasticContext(monitor=monitor, tracker=tracker)
    wants_ctx = _accepts_context(train_fn)
    attempt = 0
    while True:
        # each attempt is a logical process restart: reset the registry so
        # a re-built Model claims the same deterministic snapshot names and
        # _load_latest restores into the new instances, not the dead ones
        acp.reset_registry()
        try:
            return train_fn(ctx) if wants_ctx else train_fn()
        except EvictedError as e:
            _reg.counter("elastic/self_evicted").inc()
            _flight.record_event("elastic_self_evicted", rank=e.rank)
            raise
        except WorldChangedError as wc:
            ctx.world_changes += 1
            if ctx.world_changes > max_world_changes:
                raise FatalError(
                    f"elastic_run: world changed {ctx.world_changes} times"
                    " — membership is thrashing, giving up") from wc
            _reg.counter("elastic/world_changes").inc()
            _flight.record_event(
                "elastic_world_changed", survivors=wc.survivors,
                dead=wc.dead, evicted=wc.evicted)
            ctx.generation += 1
            if monitor is not None:
                # generous deadline (caller-tunable): a surviving peer
                # may be mid-step — possibly recompiling after the
                # previous resize — and must not be timed out into a
                # job-killing FatalError by a fast-reacting rank
                ctx.world = renegotiate_world(
                    monitor, members=ctx.members,
                    generation=ctx.generation,
                    timeout=renegotiate_timeout_s)
            else:
                ctx.world = ElasticWorld(
                    generation=ctx.generation, survivors=wc.survivors,
                    rank=(wc.survivors.index(_flight._safe_rank())
                          if _flight._safe_rank() in wc.survivors else None),
                    world_size=len(wc.survivors))
        except exceptions as e:
            attempt += 1
            ctx.restarts = attempt
            _reg.counter("elastic/restarts").inc()
            _flight.record_event(
                "elastic_restart", attempt=attempt,
                error=f"{type(e).__name__}: {e}"[:200])
            if attempt > max_restarts:
                raise FatalError(
                    f"elastic_run: giving up after {max_restarts} restarts"
                ) from e
