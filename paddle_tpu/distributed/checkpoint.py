"""Async, reshardable, crash-consistent training checkpoints.

The preemption-tolerance contract (ROADMAP item 5) in three guarantees:

1. **Off the step critical path.** A snapshot *capture* is a device-side
   copy of the train step's state pytree (donation-safe: the copies are
   never fed back to the compiled step) dispatched asynchronously, plus
   an async D2H start; the serialize + fsync + publish work runs on a
   background writer thread (``FLAGS_checkpoint_async``). The training
   loop never blocks on disk.

2. **Crash-consistent publication.** Data is written into ``<path>.tmp``
   and published by one atomic ``rename`` only after a ``MANIFEST.json``
   (global shapes, dtypes, PartitionSpecs, per-file CRC32s) is fsynced.
   A process killed mid-save leaves a manifest-less ``.tmp`` that
   :func:`sweep_tmp` removes and :func:`latest_checkpoint` never
   considers; a corrupted published snapshot fails its checksums and is
   *skipped* in favor of the next-newest — a torn snapshot is detected,
   never half-loaded.

3. **Resume into a different world.** Each rank writes only the array
   shards it owns (``replica_id == 0`` de-dups replicated leaves), with
   the global index of every piece recorded. On load the global arrays
   are reassembled from all ranks' pieces and re-sliced onto the *new*
   mesh via ``jax.make_array_from_callback`` — a 4-rank ZeRO-1
   checkpoint restores onto 2 or 8 ranks with a loss-curve-identical
   continuation (sharding specs come from ``parallel/sharding.py``; the
   wire form in the manifest is mesh-independent).

Layout of one snapshot directory::

    step_12/
      MANIFEST.json      format, step, world, mesh_shape, entries{name:
                         {shape,dtype,spec}}, files{name:{crc32,size}}
      shard_r0.pdshard   rank 0's pieces: {name: [(global_index, data)]}
      shard_r1.pdshard   ...
      rank_0.json        per-rank commit record (crc of its shard file);
                         rank 0 aggregates these into the manifest

``incubate/auto_checkpoint.py`` rides the same low-level writer for its
epoch snapshots; ``tools/chaos_smoke.py`` kills writers at every stage
of this pipeline to prove the recovery paths.
"""
from __future__ import annotations

import json
import os
import queue
import re
import shutil
import threading
import time
import zlib

import numpy as np

from ..flags import flag
from ..profiler import RecordEvent

__all__ = [
    "CheckpointError",
    "CheckpointCorruptError",
    "save",
    "save_train_step",
    "restore_train_step",
    "load",
    "validate",
    "latest_checkpoint",
    "sweep_tmp",
    "wait_pending",
    "detach_refs",
    "write_bytes",
    "write_manifest",
    "MANIFEST",
]

MANIFEST = "MANIFEST.json"
FORMAT_VERSION = 1
_PEER_WAIT_S = 120.0  # rank 0's budget for peers' shard commits


class CheckpointError(RuntimeError):
    pass


class CheckpointCorruptError(CheckpointError):
    """A snapshot that must be skipped: torn, checksum-failing, or
    manifest-less. Never propagated past the fallback scan."""


def _flight():
    from ..monitor import flight_recorder

    return flight_recorder


def _goodput():
    from ..monitor import goodput

    return goodput


def _counter(name):
    from ..monitor import registry

    return registry.counter(name)


# ---------------------------------------------------------------------------
# pytree naming / capture
# ---------------------------------------------------------------------------


_NAME_CACHE: dict = {}  # treedef -> leaf names (keystr is the slow part)


def _named_leaves(tree):
    """Flatten a state pytree into ([name, leaf], treedef); names are
    jax keystr paths — stable across processes for identical pytrees.
    Names are cached per treedef: captures run on the step path, and
    re-deriving key strings every save costs more than the capture."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    names = _NAME_CACHE.get(treedef)
    if names is None:
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        names = [jax.tree_util.keystr(path) for path, _ in flat]
        if len(_NAME_CACHE) > 32:
            _NAME_CACHE.clear()
        _NAME_CACHE[treedef] = names
    return list(zip(names, leaves)), treedef


def detach_refs(obj):
    """Replace live Tensor leaves with their current immutable jax
    arrays, recursively — the O(1) capture for eager-object snapshots
    (auto_checkpoint): later training rebinds ``Tensor._array`` to new
    arrays, so the grabbed references stay frozen at capture time."""
    from ..framework.tensor import Tensor

    if isinstance(obj, Tensor):
        return obj._array
    if isinstance(obj, dict):
        return {k: detach_refs(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(detach_refs(v) for v in obj)
    return obj


_COPY_FN = []  # lazily-built jitted whole-tree copy


def _snapshot_leaves(leaves):
    """Device-side copy of every jax leaf (donation-safe: the compiled
    step will donate the *originals*, never these). All array leaves are
    copied by ONE jitted program — a single async dispatch per capture,
    not one per leaf — so the step loop pays microseconds; the writer
    thread's host reads block on the transfer instead."""
    import jax
    import jax.numpy as jnp

    if not _COPY_FN:
        _COPY_FN.append(jax.jit(
            lambda xs: [jnp.copy(x) for x in xs]))
    arrays = [(i, l) for i, l in enumerate(leaves)
              if isinstance(l, jax.Array)]
    out = list(leaves)
    if arrays:
        copies = _COPY_FN[0]([l for _, l in arrays])
        for (i, _), c in zip(arrays, copies):
            out[i] = c
    return out


# ---------------------------------------------------------------------------
# low-level durable writes
# ---------------------------------------------------------------------------


def write_bytes(path, data: bytes):
    """Write + fsync; returns (crc32, size) for the manifest."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    return zlib.crc32(data) & 0xFFFFFFFF, len(data)


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse dir fsync; rename is still atomic
    finally:
        os.close(fd)


def write_manifest(dirpath, files, **meta):
    """Write + fsync the manifest that makes a snapshot loadable. The
    caller publishes (renames) only after this returns."""
    manifest = {"format": FORMAT_VERSION, **meta, "files": files}
    write_bytes(os.path.join(dirpath, MANIFEST),
                json.dumps(manifest, sort_keys=True).encode("utf-8"))
    _fsync_dir(dirpath)
    return manifest


# ---------------------------------------------------------------------------
# shard extraction / reassembly
# ---------------------------------------------------------------------------


def _index_wire(idx, shape):
    """Global-index slices -> [[start, stop], ...] (JSON/pickle stable)."""
    out = []
    for sl, dim in zip(idx, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _leaf_pieces(arr, rank, world):
    """The (global_index, data) pieces THIS rank persists for one leaf.

    Sharded arrays: every addressable shard with ``replica_id == 0`` —
    exactly one global writer per distinct piece, so the union over all
    ranks' files tiles the global array with no duplicate bytes.
    Host/per-process arrays (no global sharding): rank 0 writes the
    whole leaf.
    """
    import jax

    if isinstance(arr, jax.Array):
        try:
            shards = list(arr.addressable_shards)
        except Exception:
            shards = []
        if shards:
            if world > 1 and len(getattr(arr.sharding, "device_set",
                                         ())) == 1:
                # per-PROCESS array (no global placement): every rank
                # holds its own copy with replica_id 0, so without this
                # gate all ranks would write overlapping full pieces and
                # load would silently take an arbitrary writer. Rank 0's
                # copy is canonical — the single-controller convention.
                if rank != 0:
                    return []
            return [
                (_index_wire(sh.index, arr.shape), sh.data)
                for sh in shards
                if getattr(sh, "replica_id", 0) == 0
            ]
    if rank == 0 or world <= 1:
        shape = np.shape(arr)
        full = tuple(slice(0, d) for d in shape)
        return [(_index_wire(full, shape), arr)]
    return []


def _np_dtype(name):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bfloat16 & friends (jax always ships it)

        return np.dtype(getattr(ml_dtypes, name))


def _assemble(name, entry, pieces):
    """Rebuild one global host array from shard pieces (any world)."""
    shape = tuple(int(d) for d in entry["shape"])
    dtype = _np_dtype(entry["dtype"])
    if not pieces:
        raise CheckpointCorruptError(f"{name}: no shard data in any file")
    if shape == ():
        return np.asarray(pieces[0][1], dtype=dtype).reshape(())
    buf = np.zeros(shape, dtype)
    covered = 0
    for idx, data in pieces:
        sl = tuple(slice(a, b) for a, b in idx)
        buf[sl] = np.asarray(data, dtype=dtype).reshape(
            [b - a for a, b in idx])
        covered += int(np.prod([b - a for a, b in idx]))
    if covered < int(np.prod(shape)):
        raise CheckpointCorruptError(
            f"{name}: shards cover {covered} of {int(np.prod(shape))} "
            "elements (missing rank file?)")
    return buf


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------


def save(path, state, shardings=None, *, step=None, mesh=None, keep=None,
         async_=None, peer_timeout_s=None):
    """Snapshot ``state`` (a pytree of arrays) to ``path``.

    ``shardings`` is a matching pytree of NamedShardings (or None —
    everything recorded as replicated); its PartitionSpecs land in the
    manifest in mesh-independent wire form. ``keep`` rotates sibling
    snapshots sharing ``path``'s numeric-suffix prefix. ``async_``
    defaults to ``FLAGS_checkpoint_async``; the returned pending handle
    (async) resolves via :func:`wait_pending`.
    """
    import functools

    import jax

    if async_ is None:
        async_ = bool(flag("checkpoint_async"))
    # the capture runs on the calling (step) thread: its seconds are
    # checkpoint badput in the goodput ledger (deducted from the step
    # frame's compute when called inside one)
    with RecordEvent("checkpoint::capture"), _goodput().span("checkpoint"):
        named, _ = _named_leaves(state)
        names = [n for n, _ in named]
        leaves = _snapshot_leaves([l for _, l in named])
        if shardings is not None:
            specs = [
                _spec_wire_of(s)
                for s in jax.tree_util.tree_leaves(
                    shardings, is_leaf=_is_sharding)
            ]
            if len(specs) != len(names):
                raise CheckpointError(
                    f"shardings pytree has {len(specs)} leaves, state has "
                    f"{len(names)} — they must mirror each other")
        else:
            specs = [[] for _ in names]
    meta = {
        "step": -1 if step is None else int(step),
        "world": _flight()._safe_world(),
        "mesh_shape": dict(mesh.shape) if mesh is not None else None,
        "time": time.time(),
    }
    job = functools.partial(_write_snapshot, str(path), names, leaves,
                            specs, meta, keep, peer_timeout_s)
    if async_:
        _counter("checkpoint/async_saves").inc()
        return _SAVER.submit(job, label=str(path))
    job()
    return None


def _is_sharding(x):
    from jax.sharding import Sharding

    return isinstance(x, Sharding)


def _spec_wire_of(sharding):
    from ..parallel.sharding import spec_to_wire

    spec = getattr(sharding, "spec", None)
    return spec_to_wire(spec) if spec is not None else []


def _write_snapshot(final, names, leaves, specs, meta, keep,
                    peer_timeout_s):
    """Writer body (background thread in async mode). Every rank writes
    its shard file + commit record into the shared ``.tmp``; rank 0
    aggregates the manifest and publishes atomically."""
    from . import chaos

    rank = _flight()._safe_rank()
    world = int(meta.get("world") or 1)
    t0 = time.perf_counter()
    tmp = final + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    # serialize + publish seconds: foreground checkpoint badput when the
    # save is sync (this runs on the step thread); automatically filed
    # as overlapped background work when the async writer thread runs it
    # under a live step frame (overlapped work costs no wall time)
    with _goodput().span("checkpoint"), \
            RecordEvent("checkpoint::serialize"):
        from ..framework import serialization as _ser

        entries = {}
        pieces = {}
        for name, leaf, spec in zip(names, leaves, specs):
            dtype = getattr(leaf, "dtype", None)
            if dtype is None:  # plain python scalar leaf
                dtype = np.asarray(leaf).dtype
            entries[name] = {
                "shape": [int(d) for d in np.shape(leaf)],
                "dtype": str(dtype),
                "spec": spec,
            }
            p = _leaf_pieces(leaf, rank, world)
            if p:
                pieces[name] = p
        shard_name = f"shard_r{rank}.pdshard"
        # dumps() materializes device shards to host here, on the writer
        # thread — the D2H the capture already started
        crc, size = write_bytes(
            os.path.join(tmp, shard_name),
            _ser.dumps({"rank": rank, "pieces": pieces}))
    chaos.inject("mid_save")
    frag = {"rank": rank, "world": world, "file": shard_name,
            "crc32": crc, "size": size}
    write_bytes(os.path.join(tmp, f"rank_{rank}.json"),
                json.dumps(frag).encode("utf-8"))
    _fsync_dir(tmp)
    if rank != 0:
        return  # publication is rank 0's job
    files = {shard_name: {"crc32": crc, "size": size}}
    deadline = time.monotonic() + float(
        _PEER_WAIT_S if peer_timeout_s is None else peer_timeout_s)
    for r in range(1, world):
        rec = _await_peer_commit(tmp, r, deadline)
        files[rec["file"]] = {"crc32": rec["crc32"], "size": rec["size"]}
    write_manifest(tmp, files, **meta, entries=entries)
    with _goodput().span("checkpoint"), RecordEvent("checkpoint::publish"):
        if os.path.exists(final):
            shutil.rmtree(final, ignore_errors=True)
        os.replace(tmp, final)
        _fsync_dir(os.path.dirname(final) or ".")
    _counter("checkpoint/saves").inc()
    _flight().record_event(
        "checkpoint_saved", path=final, step=meta["step"],
        world=world, ms=round((time.perf_counter() - t0) * 1e3, 3))
    led = _goodput().active_ledger()
    if led is not None:
        # re-publish the goodput sidecar after every snapshot
        # publication: a resume can never land on a checkpoint newer
        # than the ledger's lost-work pricing basis
        try:
            led.publish()
        except OSError:
            pass
    if keep:
        _rotate(final, int(keep))


def _await_peer_commit(tmp, r, deadline):
    frag_path = os.path.join(tmp, f"rank_{r}.json")
    while True:
        try:
            with open(frag_path, "r") as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            pass  # not yet written / mid-write
        if time.monotonic() > deadline:
            raise CheckpointError(
                f"rank {r} never committed its shard into {tmp} — "
                "snapshot left unpublished (torn .tmp is swept on resume)")
        time.sleep(0.02)


_STEP_DIR = re.compile(r"^(.*?)(\d+)$")


def _rotate(final, keep):
    """Drop oldest sibling snapshots beyond ``keep`` (same numeric-
    suffix prefix, e.g. step_*). Only intact (manifest-bearing) dirs
    count toward the quota; torn ones are swept separately."""
    parent = os.path.dirname(os.path.abspath(final))
    m = _STEP_DIR.match(os.path.basename(final))
    if not m:
        return
    prefix = m.group(1)
    found = []
    try:
        listing = os.listdir(parent)
    except FileNotFoundError:
        return
    for d in listing:
        dm = _STEP_DIR.match(d)
        if dm is None or dm.group(1) != prefix:
            continue
        if os.path.isfile(os.path.join(parent, d, MANIFEST)):
            found.append((int(dm.group(2)), d))
    for _, d in sorted(found)[:-keep]:
        shutil.rmtree(os.path.join(parent, d), ignore_errors=True)


# ---------------------------------------------------------------------------
# validate / load
# ---------------------------------------------------------------------------


def _read_manifest(path):
    mpath = os.path.join(path, MANIFEST)
    try:
        with open(mpath, "r") as f:
            manifest = json.load(f)
    except FileNotFoundError:
        raise CheckpointCorruptError(f"{path}: no {MANIFEST} (torn save)")
    except (json.JSONDecodeError, OSError) as e:
        raise CheckpointCorruptError(f"{path}: unreadable manifest: {e}")
    if not isinstance(manifest, dict) or "files" not in manifest:
        raise CheckpointCorruptError(f"{path}: malformed manifest")
    return manifest


def _read_checked(path, fname, meta):
    fpath = os.path.join(path, fname)
    try:
        with open(fpath, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        raise CheckpointCorruptError(f"{path}: missing file {fname}")
    crc = zlib.crc32(data) & 0xFFFFFFFF
    if crc != int(meta["crc32"]) or len(data) != int(meta["size"]):
        raise CheckpointCorruptError(
            f"{path}/{fname}: checksum/size mismatch "
            f"(crc {crc:#x} != {int(meta['crc32']):#x} or "
            f"size {len(data)} != {meta['size']})")
    return data


def validate(path):
    """Manifest + every listed file present with matching CRC32/size.
    Returns the manifest; raises CheckpointCorruptError otherwise."""
    manifest = _read_manifest(path)
    for fname, meta in manifest["files"].items():
        _read_checked(path, fname, meta)
    return manifest


def load(path):
    """Read + verify a snapshot; returns ``(flat, manifest)`` where
    ``flat`` maps leaf name -> fully-assembled global numpy array."""
    from ..framework import serialization as _ser

    manifest = _read_manifest(path)
    pieces = {}
    for fname, meta in manifest["files"].items():
        data = _read_checked(path, fname, meta)
        if not fname.endswith(".pdshard"):
            continue
        payload = _ser.loads(data, return_numpy=True)
        for name, ps in payload["pieces"].items():
            pieces.setdefault(name, []).extend(ps)
    entries = manifest.get("entries", {})
    flat = {
        name: _assemble(name, entry, pieces.get(name, []))
        for name, entry in entries.items()
    }
    return flat, manifest


def sweep_tmp(parent):
    """Remove torn ``*.tmp`` snapshot dirs left by mid-save deaths.
    Called on startup/resume, before any new save targets the dir."""
    removed = []
    try:
        listing = os.listdir(parent)
    except FileNotFoundError:
        return removed
    for d in listing:
        full = os.path.join(parent, d)
        if d.endswith(".tmp") and os.path.isdir(full):
            shutil.rmtree(full, ignore_errors=True)
            removed.append(full)
    if removed:
        _flight().record_event("checkpoint_tmp_swept", parent=str(parent),
                               count=len(removed))
    return removed


def latest_checkpoint(parent, prefix="step_"):
    """Newest *intact* snapshot under ``parent``: scans ``<prefix>N``
    dirs newest-first, validates each, skips (and records) corrupt or
    manifest-less ones. Returns ``(path, manifest)`` or ``(None, None)``."""
    try:
        listing = os.listdir(parent)
    except FileNotFoundError:
        return None, None
    candidates = []
    for d in listing:
        if not d.startswith(prefix) or d.endswith(".tmp"):
            continue
        try:
            candidates.append((int(d[len(prefix):]), d))
        except ValueError:
            continue
    for _, d in sorted(candidates, reverse=True):
        full = os.path.join(parent, d)
        try:
            manifest = validate(full)
        except CheckpointCorruptError as e:
            _counter("checkpoint/corrupt_skipped").inc()
            _flight().record_event("checkpoint_skipped_corrupt",
                                   path=full, error=str(e)[:200])
            continue
        return full, manifest
    return None, None


# ---------------------------------------------------------------------------
# train-step integration (TrainStepFn / ShardedTrainStep)
# ---------------------------------------------------------------------------


def save_train_step(step_obj, path, step=None, async_=None, keep=None,
                    peer_timeout_s=None):
    """Snapshot a train step's device state (``.state`` + its
    ``.state_shardings``/``.mesh`` when present — ShardedTrainStep) with
    full resharding metadata."""
    return save(
        path,
        step_obj.state,
        getattr(step_obj, "state_shardings", None),
        step=step,
        mesh=getattr(step_obj, "mesh", None),
        keep=keep,
        async_=async_,
        peer_timeout_s=peer_timeout_s,
    )


def restore_train_step(step_obj, path):
    """Load a snapshot into a live train step, re-slicing every leaf
    onto the step's *current* mesh/shardings (which may differ in world
    size from the save — the reshard-on-resume path). Returns the
    manifest (callers read ``manifest['step']`` to resume the loop)."""
    import jax
    import jax.numpy as jnp

    with RecordEvent("checkpoint::restore"), _goodput().span("restore"):
        flat, manifest = load(path)
        named, treedef = _named_leaves(step_obj.state)
        names = [n for n, _ in named]
        missing = sorted(set(names) - set(flat))
        extra = sorted(set(flat) - set(names))
        if missing or extra:
            raise CheckpointError(
                f"{path} does not match this train step's state: "
                f"missing={missing[:5]} extra={extra[:5]}")
        shardings = getattr(step_obj, "state_shardings", None)
        if shardings is not None:
            sh_leaves = jax.tree_util.tree_leaves(
                shardings, is_leaf=_is_sharding)
        else:
            sh_leaves = [None] * len(names)
        new_leaves = []
        resharded = False
        for (name, tmpl), sh in zip(named, sh_leaves):
            host = flat[name]
            tshape = tuple(np.shape(tmpl))
            if tuple(host.shape) != tshape:
                raise CheckpointError(
                    f"{name}: checkpoint shape {host.shape} != live state "
                    f"shape {tshape}")
            host = np.asarray(host, dtype=_np_dtype(
                str(getattr(tmpl, "dtype", host.dtype))))
            if sh is not None:
                with RecordEvent("checkpoint::reshard"):
                    arr = jax.make_array_from_callback(
                        tshape, sh, lambda idx, h=host: h[idx])
                resharded = True
            else:
                arr = jnp.asarray(host)
            # owned device copy: on CPU, asarray/make_array may alias the
            # host numpy buffer zero-copy — the compiled step DONATES its
            # state, and donating an aliased buffer frees memory numpy
            # owns (heap corruption). Same hazard TrainStepFn.__init__
            # guards against for the initial eager state.
            new_leaves.append(jnp.copy(arr))
        step_obj.state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    _counter("checkpoint/restores").inc()
    mesh = getattr(step_obj, "mesh", None)
    world_changed = (
        int(manifest.get("world") or 1) != _flight()._safe_world()
        or (mesh is not None
            and manifest.get("mesh_shape") not in (None, dict(mesh.shape)))
    )
    if resharded and world_changed:
        _counter("checkpoint/reshards").inc()
        _flight().record_event(
            "checkpoint_resharded", path=str(path),
            saved_world=manifest.get("world"),
            saved_mesh=json.dumps(manifest.get("mesh_shape")),
            new_world=_flight()._safe_world(),
            new_mesh=json.dumps(dict(mesh.shape) if mesh else None))
    _flight().record_event("checkpoint_restored", path=str(path),
                           step=manifest.get("step", -1))
    led = _goodput().active_ledger()
    if led is not None:
        # price the resume: steps the previous life committed AFTER this
        # manifest must be recomputed — the ledger charges them to
        # lost_work as they re-commit
        led.note_resume(int(manifest.get("step", -1)))
    return manifest


# ---------------------------------------------------------------------------
# background writer
# ---------------------------------------------------------------------------


class _Pending:
    def __init__(self, label):
        self.label = label
        self.error = None
        self._done = threading.Event()

    @property
    def done(self):
        return self._done.is_set()

    def wait(self, timeout=None, raise_error=True):
        if not self._done.wait(timeout):
            raise CheckpointError(
                f"checkpoint save {self.label!r} still pending after "
                f"{timeout}s")
        if raise_error and self.error is not None:
            raise self.error
        return self


class AsyncSaver:
    """One FIFO writer thread: snapshots publish in submission order
    (rotation and resume both depend on monotonic publication)."""

    def __init__(self):
        self._q = queue.Queue()
        self._lock = threading.Lock()
        self._thread = None
        self._pending = []

    def submit(self, fn, label=""):
        p = _Pending(label)
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="ptpu-ckpt-writer", daemon=True)
                self._thread.start()
            # prune only successes: an errored pending must survive here
            # until a wait_pending() consumes (and can re-raise) it — a
            # dropped snapshot must not fail silently
            self._pending = [x for x in self._pending
                             if not x.done or x.error is not None]
            self._pending.append(p)
        self._q.put((fn, p))
        return p

    def _run(self):
        while True:
            fn, p = self._q.get()
            try:
                fn()
            except BaseException as e:  # surfaced via wait_pending
                p.error = e
                try:
                    _counter("checkpoint/save_errors").inc()
                    _flight().record_event(
                        "checkpoint_save_failed", label=p.label,
                        error=f"{type(e).__name__}: {e}"[:200])
                except Exception:
                    pass
            finally:
                p._done.set()

    def wait_pending(self, timeout=None, raise_errors=True):
        """Drain every submitted save; with ``raise_errors`` the first
        writer failure (or a timeout) re-raises here — a dropped
        snapshot must not fail silently. Saves that outlive ``timeout``
        are put BACK on the pending list so a later drain still tracks
        them."""
        with self._lock:
            pending, self._pending = self._pending, []
        first = None
        unfinished = []
        for p in pending:
            if not p._done.wait(timeout):
                unfinished.append(p)
                continue
            if first is None and p.error is not None:
                first = p.error
        if unfinished:
            with self._lock:
                self._pending = unfinished + self._pending
        if raise_errors:
            if first is not None:
                raise first
            if unfinished:
                raise CheckpointError(
                    f"{len(unfinished)} checkpoint saves still pending "
                    f"after {timeout}s (first: {unfinished[0].label!r})")
        return first


_SAVER = AsyncSaver()


def wait_pending(timeout=None, raise_errors=True):
    """Block until all in-flight async saves are durable (or failed)."""
    return _SAVER.wait_pending(timeout=timeout, raise_errors=raise_errors)


def submit(fn, label=""):
    """Queue durable-write work on the shared FIFO writer thread
    (auto_checkpoint's epoch snapshots ride the same queue, so epoch
    and step snapshots publish in one global order)."""
    return _SAVER.submit(fn, label)
