"""Fleet distributed metrics.

Reference parity: python/paddle/distributed/fleet/metrics/metric.py
(:23-337) — sum/max/min/auc/mae/rmse/acc reduced across all trainers
(the reference all-reduces over Gloo/PS; here the reduction rides the
jax.distributed world when one exists, and is the identity in a single
process).
"""
from __future__ import annotations

import numpy as np

import jax

__all__ = ["sum", "max", "min", "auc", "mae", "rmse", "acc"]

_builtin_sum, _builtin_max, _builtin_min = sum, max, min


def _allreduce(value, op):
    arr = np.asarray(value, np.float64)
    # check the distributed client WITHOUT touching the backend:
    # jax.process_count() would initialize XLA, silently returning local
    # values pre-fleet.init() and forbidding the later rendezvous
    from ..env import _distributed_client_active

    if not _distributed_client_active() or jax.process_count() == 1:
        return arr
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(arr)
    if op == "sum":
        return np.asarray(gathered).sum(axis=0)
    if op == "max":
        return np.asarray(gathered).max(axis=0)
    return np.asarray(gathered).min(axis=0)


def sum(input):  # noqa: A001 — reference API name
    """fleet/metrics/metric.py:sum — global sum of a local stat."""
    return _allreduce(input, "sum")


def max(input):  # noqa: A001
    return _allreduce(input, "max")


def min(input):  # noqa: A001
    return _allreduce(input, "min")


def auc(stat_pos, stat_neg):
    """metric.py:auc — AUC from per-trainer positive/negative score
    histograms (the streaming stat-tensor design of auc_op)."""
    pos = _allreduce(stat_pos, "sum")
    neg = _allreduce(stat_neg, "sum")
    # walk thresholds high→low accumulating TPR/FPR trapezoids; the ROC
    # starts at the origin (reference metric.py seeds pos/neg at 0)
    new_pos = np.concatenate(([0.0], pos[::-1].cumsum()))
    new_neg = np.concatenate(([0.0], neg[::-1].cumsum()))
    total_pos = new_pos[-1]
    total_neg = new_neg[-1]
    if total_pos == 0 or total_neg == 0:
        return 0.5
    trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy<2.0
    area = trapezoid(new_pos / total_pos, new_neg / total_neg)
    return float(area)


def mae(abserr, total_ins_num):
    """metric.py:mae — global mean absolute error."""
    err = _allreduce(abserr, "sum")
    cnt = _allreduce(total_ins_num, "sum")
    return float(err / _builtin_max(cnt, 1.0))


def rmse(sqrerr, total_ins_num):
    err = _allreduce(sqrerr, "sum")
    cnt = _allreduce(total_ins_num, "sum")
    return float(np.sqrt(err / _builtin_max(cnt, 1.0)))


def acc(correct, total):
    c = _allreduce(correct, "sum")
    t = _allreduce(total, "sum")
    return float(c / _builtin_max(t, 1.0))
