"""Fleet facade, DistributedStrategy, role makers.

Reference parity:
- Fleet: distributed/fleet/base/fleet_base.py:43
- DistributedStrategy: base/distributed_strategy.py over
  framework/distributed_strategy.proto:94 (amp :96, recompute :97,
  gradient_merge, localsgd, lars, lamb, pipeline :92, a_sync, elastic :105)
- RoleMaker: base/role_maker.py:28 (RoleMakerBase), :167
  (PaddleCloudRoleMaker — role/rank/endpoints from env)

TPU-native: DistributedStrategy gains mesh-geometry fields (dp/tp/pp/sp/ep
degrees) that the reference lacks (its TP/SP/EP are absent — SURVEY.md
§2.3); meta-optimizer program rewriting is replaced by composing step
transformations over the functionalized train step.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

from ..env import ParallelEnv, init_parallel_env


@dataclass
class PipelineConfig:
    """framework/distributed_strategy.proto:92 PipelineConfig."""

    micro_batch: int = 1
    accumulate_steps: int = 1


@dataclass
class RecomputeConfig:
    checkpoints: list = field(default_factory=list)


@dataclass
class AMPConfig:
    init_loss_scaling: float = 32768.0
    incr_every_n_steps: int = 1000
    decr_every_n_nan_or_inf: int = 2
    incr_ratio: float = 2.0
    decr_ratio: float = 0.5
    use_dynamic_loss_scaling: bool = True
    custom_white_list: list = field(default_factory=list)
    custom_black_list: list = field(default_factory=list)


@dataclass
class GradientMergeConfig:
    k_steps: int = 1
    avg: bool = True


@dataclass
class LocalSGDConfig:
    k_steps: int = 1


@dataclass
class DGCConfig:
    rampup_begin_step: int = 0


@dataclass
class LarsConfig:
    lars_coeff: float = 0.001
    lars_weight_decay: float = 0.0005


@dataclass
class LambConfig:
    lamb_weight_decay: float = 0.01


@dataclass
class ShardingConfig:
    """ZeRO-style optimizer-state sharding (absent in the reference —
    SURVEY.md §2.3; here it is a first-class mesh axis use)."""

    stage: int = 1


class ASyncConfig:
    """a_sync_configs: k_steps==0 → async push per step; k_steps>0 → geo
    mode, deltas pushed every k trainer steps (geo_sgd_transpiler.py)."""

    def __init__(self):
        self.k_steps = 0


class DistributedStrategy:
    """Mutable strategy bag, field names matching the reference proto."""

    def __init__(self):
        # reference fields (distributed_strategy.proto:94-118)
        self.amp = False
        self.amp_configs = AMPConfig()
        self.recompute = False
        self.recompute_configs = RecomputeConfig()
        self.pipeline = False
        self.pipeline_configs = PipelineConfig()
        self.gradient_merge = False
        self.gradient_merge_configs = GradientMergeConfig()
        self.localsgd = False
        self.localsgd_configs = LocalSGDConfig()
        self.dgc = False
        self.dgc_configs = DGCConfig()
        self.lars = False
        self.lars_configs = LarsConfig()
        self.lamb = False
        self.lamb_configs = LambConfig()
        self.a_sync = False
        self.a_sync_configs = ASyncConfig()
        self.elastic = False
        self.auto = False
        self.nccl_comm_num = 1  # accepted, meaningless on TPU
        self.sync_batch_norm = False
        self.fuse_all_reduce_ops = True  # XLA does this; kept for compat
        self.fuse_grad_size_in_MB = 32
        # TPU-native extensions: mesh geometry
        self.sharding = False
        self.sharding_configs = ShardingConfig()
        self.dp_degree = 0  # 0 = infer (all remaining devices)
        self.tp_degree = 1
        self.pp_degree = 1
        self.sp_degree = 1
        self.ep_degree = 1
        self.sharding_rules = None  # parallel.ShardingRules override

    def __repr__(self):
        on = [k for k, v in self.__dict__.items() if v is True]
        return f"DistributedStrategy(enabled={on})"


class RoleMakerBase:
    """base/role_maker.py:28."""

    def __init__(self):
        self._is_collective = True

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def is_first_worker(self):
        return self.worker_index() == 0

    def worker_index(self):
        return 0

    def worker_num(self):
        return 1

    def get_trainer_endpoints(self):
        return []

    def get_pserver_endpoints(self):
        return []


class PaddleCloudRoleMaker(RoleMakerBase):
    """base/role_maker.py:167 — role from env variables."""

    def __init__(self, is_collective=True, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._env = ParallelEnv()

    def worker_index(self):
        return self._env.rank

    def worker_num(self):
        return self._env.world_size

    def get_trainer_endpoints(self):
        return self._env.trainer_endpoints


class Role:
    """role_maker.py Role enum parity."""

    WORKER = 1
    SERVER = 2


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=None, worker_num=1,
                 server_endpoints=None, is_collective=True,
                 device_type="cpu", **kwargs):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_num = worker_num
        self._server_endpoints = list(server_endpoints or [])
        self._is_collective = is_collective
        # heterogeneous worker typing (HeterXpuTrainer,
        # framework/trainer.h:149): device-typed workers split one PS job
        # and run per-type step functions (see Fleet.heter_step_fn)
        self._device_type = str(device_type)

    def worker_index(self):
        return self._current_id

    def worker_num(self):
        return self._worker_num

    def device_type(self):
        return self._device_type

    def is_worker(self):
        return self._role in (None, Role.WORKER, "WORKER", "worker")

    def is_server(self):
        return self._role in (Role.SERVER, "SERVER", "server")

    def server_index(self):
        return self._current_id

    def get_pserver_endpoints(self):
        return self._server_endpoints


class Fleet:
    """fleet_base.py:43 facade, singleton via module-level ``fleet``."""

    def __init__(self):
        self._role_maker = None
        self._strategy = None
        self._is_initialized = False
        self._mesh = None
        self._user_defined_optimizer = None

    # -- lifecycle ----------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective
        )
        self._strategy = strategy or DistributedStrategy()
        init_parallel_env()
        self._is_initialized = True
        return self

    def build_mesh(self):
        """Materialize the mesh implied by the strategy's degrees."""
        from ...parallel import MeshConfig, create_mesh
        import jax

        s = self._strategy
        n = len(jax.devices())
        fixed = s.tp_degree * s.pp_degree * s.sp_degree * s.ep_degree
        dp = s.dp_degree or max(1, n // fixed)
        self._mesh = create_mesh(
            MeshConfig(dp=dp, tp=s.tp_degree, pp=s.pp_degree,
                       sp=s.sp_degree, ep=s.ep_degree)
        )
        return self._mesh

    # -- role queries (fleet_base.py surface) -------------------------------
    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def device_type(self):
        """This worker's device type ("cpu"/"tpu"/...) — heterogeneous
        worker typing (framework/trainer.h:149 HeterXpuTrainer,
        device_worker.h:334 HeterCpuWorker). Role makers without the
        notion report "cpu"."""
        fn = getattr(self._role_maker, "device_type", None)
        return fn() if callable(fn) else "cpu"

    def heter_step_fn(self, step_fns):
        """Pick this worker's step function by device type — the minimal
        HeterXpuTrainer contract: one PS job, device-typed workers, each
        type running its own (CPU-eager vs accelerator-compiled) step.

        ``step_fns``: dict like {"cpu": fn, "tpu": fn} or with a
        "default" entry. Raises when this worker's type has no entry and
        no default — a silently wrong step function must never run.
        """
        dt = self.device_type()
        if dt in step_fns:
            return step_fns[dt]
        if "default" in step_fns:
            return step_fns["default"]
        raise KeyError(
            f"no step function for device type {dt!r} (have "
            f"{sorted(step_fns)}); heterogeneous jobs must cover every "
            "worker type explicitly")

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_num(self):
        return len(self._role_maker.get_pserver_endpoints())

    def server_index(self):
        idx = getattr(self._role_maker, "server_index", None)
        return idx() if callable(idx) else 0

    def server_endpoints(self, to_string=False):
        eps = self._role_maker.get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    def is_server(self):
        return self._role_maker.is_server()

    def barrier_worker(self):
        # PS mode: the table server hosts the n-party fence
        # (distribute_transpiler sync-mode barrier); collective otherwise
        if getattr(self, "_ps_clients", None):
            self._ps_barrier_seq = getattr(self, "_ps_barrier_seq", 0) + 1
            self._ps_clients[0].barrier(
                f"fleet_worker_{self._ps_barrier_seq}", self.worker_num()
            )
            return
        from .. import collective

        collective.barrier()

    # -- parameter-server lifecycle (fleet_base.py PS surface) --------------
    def init_worker(self):
        """Connect to every table server (communicator startup)."""
        eps = self.server_endpoints()
        if eps:
            from ..ps import PSClient

            self._ps_clients = [PSClient(ep) for ep in eps]
        return getattr(self, "_ps_clients", None)

    def init_server(self, *args, **kwargs):
        pass  # tables are created lazily on first client create_table

    def run_server(self):
        """Serve the sparse tables on this role's endpoint — blocking
        (listen_and_serv_op loop). Call from a SERVER-role process."""
        from ..ps import TableServer

        eps = self.server_endpoints()
        if not eps:
            raise RuntimeError(
                "run_server needs server_endpoints on the role maker "
                "(UserDefinedRoleMaker(role=Role.SERVER, "
                "server_endpoints=[...]))"
            )
        ep = eps[self.server_index()]
        host, port = ep.rsplit(":", 1)
        self._ps_server = TableServer(port=int(port), host=host).start()
        self._ps_server.join()

    def stop_worker(self):
        for c in getattr(self, "_ps_clients", None) or []:
            c.close()
        self._ps_clients = None

    def shutdown_server(self):
        """First worker tears the servers down after training."""
        eps = self.server_endpoints()
        if eps:
            from ..ps import PSClient

            for ep in eps:
                try:
                    PSClient(ep, timeout=5.0).shutdown_server()
                except (ConnectionError, OSError):
                    pass

    def _all_gather(self, value):
        """Gather one scalar from every worker (PS-mode collective used
        by InMemoryDataset.global_shuffle's same-corpus check). Each
        worker writes its value into a reserved blackboard table row,
        everyone fences on the PS barrier, then reads all rows."""
        clients = getattr(self, "_ps_clients", None)
        if not clients:
            if self.worker_num() <= 1:
                return [value]
            return None  # no PS channel: caller treats as unknown
        from ..ps import ShardedTable

        self._ps_ag_seq = getattr(self, "_ps_ag_seq", 0) + 1
        seq, n = self._ps_ag_seq, self.worker_num()
        table = ShardedTable("__fleet_allgather", 1, clients, init_std=0.0)
        row = seq * n + self.worker_index()
        table.push_delta([row], [[float(value)]])
        clients[0].barrier(f"__allgather_{seq}", n)
        rows = table.pull([seq * n + i for i in range(n)])
        return [float(r[0]) for r in rows]

    def embedding_table(self, name, dim, init_std=0.01, optimizer="sgd"):
        """Create/attach the sharded sparse table view
        (distributed_lookup_table surface; shards stripe id % n_servers)."""
        if not getattr(self, "_ps_clients", None):
            raise RuntimeError("call fleet.init_worker() first")
        from ..ps import ShardedTable

        return ShardedTable(
            name, dim, self._ps_clients, init_std=init_std,
            optimizer=optimizer,
        )

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None, **kwargs):
        from ...static import io

        return io.save_inference_model(
            dirname, feeded_var_names, target_vars, executor,
            main_program=main_program,
        )

    def save_persistables(self, executor, dirname, main_program=None, **kw):
        from ...static import io

        return io.save_persistables(executor, dirname, main_program)

    # -- the core: distributed optimizer/model ------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        self._user_defined_optimizer = optimizer
        return DistributedOptimizer(self, optimizer, self._strategy)

    def distributed_model(self, model):
        """Dygraph DataParallel equivalent: on the single-controller TPU
        runtime the model is already global; gradient sync happens inside
        the sharded step, so this is identity (kept for API parity with
        fluid/dygraph/parallel.py:225)."""
        return model

    def state_dict(self):
        opt = self._user_defined_optimizer
        return opt.state_dict() if opt is not None else {}

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        opt = self._user_defined_optimizer
        if opt is None:
            raise RuntimeError("call fleet.distributed_optimizer first")
        return opt.minimize(loss)


class DistributedOptimizer:
    """Wraps a user optimizer per strategy (meta_optimizers/ equivalent).

    The strategy is consumed, not just carried (reference: the
    StrategyCompiler composes meta-optimizers, base/strategy_compiler.py):

    - validation happens eagerly at construction — unimplementable flags
      (dgc) raise here, never silently no-op
      (parallel.train.consume_strategy); a_sync selects parameter-server
      mode (distributed/ps) — independent dense steps per trainer, sparse
      tables synced through the table servers;
    - ``lars``/``lamb`` swap the update rule the way
      meta_optimizers/lars_optimizer.py replaces Momentum→LarsMomentum;
    - ``gradient_merge`` works in eager ``step()``/``minimize()`` too:
      grads accumulate across backward calls (the eager tape sums), and
      the inner optimizer is applied every ``k_steps``-th call;
    - ``recompute``/``sharding``/``localsgd`` are compiled-step behaviors:
      train-step builders (hapi Model, parallel.sharded_train_step) read
      ``user_defined_strategy`` and configure jax.checkpoint / ZeRO-1
      shardings / LocalSGD accordingly.
    """

    def __init__(self, fleet_obj, inner, strategy):
        from ...parallel.train import consume_strategy

        self._fleet = fleet_obj
        self.user_defined_strategy = strategy
        self._opts = consume_strategy(strategy)  # raises on dgc
        self.inner_opt = self._maybe_swap_update_rule(inner, strategy)
        self._gm_k = self._opts.get("grad_accum_steps", 1) or 1
        self._gm_avg = self._opts.get("grad_accum_avg", True)
        self._gm_count = 0

    @staticmethod
    def _maybe_swap_update_rule(inner, strategy):
        """lars/lamb meta-optimizer equivalents: swap the update kernel."""
        if strategy is None or not (
            getattr(strategy, "lars", False) or getattr(strategy, "lamb", False)
        ):
            return inner
        if not hasattr(inner, "_parameter_list"):
            from ...errors import UnimplementedError

            raise UnimplementedError(
                "strategy.lars/lamb swap the eager optimizer's update "
                "rule; for static programs construct the static "
                "optimizer with the desired rule directly"
            )
        from ... import optimizer as opt_mod
        from ...ops import optimizer_kernels as ok

        params = inner._parameter_list
        lr = inner._learning_rate
        clip = inner._grad_clip
        if getattr(strategy, "lamb", False):
            # weight decay comes from lamb_configs (reference
            # lamb_optimizer.py replaces the inner regularization the
            # same way); grad clipping is preserved from the inner opt
            wd = strategy.lamb_configs.lamb_weight_decay
            return opt_mod.Lamb(
                learning_rate=lr, parameters=params, lamb_weight_decay=wd,
                grad_clip=clip,
            )
        # lars: momentum with LARS local-lr scaling
        cfg = strategy.lars_configs

        class _LarsMomentum(opt_mod.Momentum):
            def _apply_one(self, index, param, grad, lr_v):
                vel = self._ensure_accumulator("velocity")[index]
                new_p, new_v = ok.lars_momentum_update(
                    param, grad, vel, lr_v,
                    mu=self._momentum,
                    lars_coeff=cfg.lars_coeff,
                    lars_weight_decay=cfg.lars_weight_decay,
                )
                self._accumulators["velocity"][index] = new_v
                return new_p

        mu = getattr(inner, "_momentum", 0.9)
        if getattr(inner, "_use_nesterov", False):
            raise NotImplementedError(
                "strategy.lars replaces the update rule with LARS momentum "
                "(operators/optimizers/lars_momentum_op.cc), which has no "
                "nesterov variant; unset use_nesterov or lars"
            )
        return _LarsMomentum(
            learning_rate=lr, momentum=mu, parameters=params,
            grad_clip=clip,
        )

    def __getattr__(self, name):
        return getattr(self.inner_opt, name)

    def step(self):
        """Eager step honoring gradient_merge: grads keep accumulating on
        the tape; the inner optimizer runs every k-th call with 1/k-scaled
        grads (meta_optimizers/gradient_merge_optimizer.py semantics)."""
        if self._gm_k <= 1:
            return self.inner_opt.step()
        self._gm_count += 1
        if self._gm_count < self._gm_k:
            return None  # keep accumulating; do NOT clear grads
        self._gm_count = 0
        if self._gm_avg:
            from ...framework.tensor import Tensor

            for p in self.inner_opt._parameter_list:
                if p.grad is not None:
                    p.grad = Tensor._from_array(p.grad._array / self._gm_k)
        out = self.inner_opt.step()
        self.inner_opt.clear_grad()
        return out

    def clear_grad(self):
        if self._gm_k > 1 and self._gm_count != 0:
            return None  # mid-accumulation: grads must survive
        return self.inner_opt.clear_grad()

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ...static.program import Variable, in_static_mode

        if in_static_mode() and isinstance(loss, Variable):
            # static fleet path (fleet_base.py:291 over a Program): the
            # wrapped optimizer's minimize appends backward + update ops.
            # Collective gradient sync is GSPMD's job at run time; the
            # compiled-step-only strategy behaviors cannot rewrite a
            # static program — refuse loudly rather than silently train
            # without them (strategy_compiler contract).
            unsupported = [
                name for name, on in (
                    ("recompute", self._opts.get("recompute")),
                    ("gradient_merge", self._opts.get("grad_accum_steps", 1) > 1),
                    ("sharding", self._opts.get("zero1")),
                    ("localsgd", self._opts.get("localsgd")),
                    ("amp", self._opts.get("amp")),
                ) if on
            ]
            if unsupported:
                from ...errors import UnimplementedError

                raise UnimplementedError(
                    f"DistributedStrategy.{'/'.join(unsupported)} applies "
                    "to compiled train steps (hapi Model / "
                    "parallel.sharded_train_step), not static programs; "
                    "unset the flag or use the functional path"
                )
            return self.inner_opt.minimize(
                loss, startup_program=startup_program,
                parameter_list=parameter_list, no_grad_set=no_grad_set,
            )
        loss.backward()
        self.step()
        return None, None


fleet = Fleet()
