"""paddle.distributed.fleet equivalent.

Reference parity: python/paddle/distributed/fleet/base/fleet_base.py:43
(Fleet facade: init :81, distributed_optimizer :269, minimize :291),
base/distributed_strategy.py (proto-backed strategy), base/role_maker.py,
base/strategy_compiler.py (meta-optimizer selection).

TPU-native: strategies configure mesh geometry + step transformations
(amp/recompute/gradient-merge wrap the functionalized step) instead of
rewriting a program IR with meta-optimizers.
"""
from .base import (  # noqa: F401
    Role,
    DistributedStrategy,
    Fleet,
    PaddleCloudRoleMaker,
    UserDefinedRoleMaker,
    fleet,
)
from . import utils  # noqa: F401  (fs layer: LocalFS/HDFSClient)
from . import metrics  # noqa: F401  (distributed metrics)

# module-level facade functions, mirroring `from paddle.distributed import
# fleet; fleet.init(...)`
init = fleet.init
is_first_worker = fleet.is_first_worker
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_worker = fleet.is_worker
worker_endpoints = fleet.worker_endpoints
server_num = fleet.server_num
server_index = fleet.server_index
server_endpoints = fleet.server_endpoints
is_server = fleet.is_server
barrier_worker = fleet.barrier_worker
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
stop_worker = fleet.stop_worker
distributed_optimizer = fleet.distributed_optimizer
distributed_model = fleet.distributed_model
state_dict = fleet.state_dict
minimize = fleet.minimize
shutdown_server = fleet.shutdown_server
embedding_table = fleet.embedding_table


def __getattr__(name):  # live singleton state (e.g. _ps_clients)
    return getattr(fleet, name)
