"""Fleet utilities (reference: python/paddle/distributed/fleet/utils/)."""
from .fs import FS, LocalFS, HDFSClient  # noqa: F401
