"""Filesystem facade for checkpoint/dataset IO.

Reference parity: python/paddle/fluid/incubate/fleet/utils/fs.py (FS base,
LocalFS) and framework/io/fs.cc (shell-out fs layer). The HDFS client
shells out to a hadoop binary in the reference; on this runtime HDFS is
gated behind an explicit error (checkpoints on pod slices normally target
GCS/local disk mounted paths, which LocalFS covers).
"""
from __future__ import annotations

import os
import shutil


class FS:
    """Abstract fs interface (fleet/utils/fs.py FS)."""

    def ls_dir(self, path):
        raise NotImplementedError

    def is_dir(self, path):
        raise NotImplementedError

    def is_file(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def rename(self, src, dst):
        raise NotImplementedError

    def touch(self, path):
        raise NotImplementedError

    def upload(self, local, remote):
        raise NotImplementedError

    def download(self, remote, local):
        raise NotImplementedError


class LocalFS(FS):
    """fleet/utils/fs.py LocalFS — local-disk implementation."""

    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for entry in sorted(os.listdir(path)):
            full = os.path.join(path, entry)
            (dirs if os.path.isdir(full) else files).append(entry)
        return dirs, files

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.replace(src, dst)

    mv = rename

    def touch(self, path):
        with open(path, "a"):
            os.utime(path, None)

    def upload(self, local, remote):
        self.mkdirs(os.path.dirname(remote) or ".")
        if os.path.isdir(local):
            shutil.copytree(local, remote, dirs_exist_ok=True)
        else:
            shutil.copy2(local, remote)

    def download(self, remote, local):
        self.upload(remote, local)


class HDFSClient(FS):
    """Gated: the reference shells out to `hadoop fs` (fs.py HDFSClient);
    no hadoop binary exists on this runtime."""

    def __init__(self, hadoop_home=None, configs=None):
        from ....errors import UnavailableError

        raise UnavailableError(
            "HDFSClient requires a hadoop installation; point the "
            "checkpoint dir at local/NFS/GCS-mounted storage and use "
            "LocalFS instead"
        )
