"""Collective communication API.

Reference parity: python/paddle/distributed/collective.py (broadcast :59,
all_reduce :115, reduce :189, all_gather :271, scatter :343, barrier :414)
and the c_* collective op family (paddle/fluid/operators/collective/).

TPU-native semantics: a *group* is a mesh axis (or tuple of axes), not an
NCCL ring. Inside compiled/sharded code (shard_map or a sharded train
step), these functions lower to jax.lax collectives over ICI; XLA schedules
and overlaps them — the reference's c_sync_calc_stream/c_sync_comm_stream
ops have no equivalent because there are no streams to sync.

Outside traced code they operate on the global view directly (a sharded
jax.Array already *is* the collective result's layout), so single-process
"world" calls are identity transforms, matching paddle's nranks==1 path.

Migration note (deviation from the reference API): inside traced SPMD code
``send``/``recv`` need *both* endpoints — ``send(t, dst, src=...)`` /
``recv(t, src, dst=...)`` — because the matched pair lowers to a single
static ``lax.ppermute`` pair. Prefer the explicit :func:`p2p` helper for
new code; reference-style one-sided calls keep working in eager code and
raise a descriptive error under tracing.
"""
from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.tensor import Tensor
from ..monitor import cost_model as _cost
from ..monitor import flight_recorder as _flight
from ..monitor import registry as _mon
from ..parallel.mesh import get_mesh
from ..profiler import RecordEvent

__all__ = [
    "ReduceOp", "new_group", "all_reduce", "broadcast", "reduce",
    "all_gather", "reduce_scatter", "scatter", "alltoall", "barrier",
    "send", "recv", "p2p",
    "per_execution_algo_bytes", "ici_bus_util",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A collective group = named mesh axis/axes (replaces ring_id)."""

    def __init__(self, axes, rank=-1, nranks=1):
        self.axes = axes if isinstance(axes, (tuple, list)) else (axes,)
        self.rank = rank
        self.nranks = nranks

    @property
    def name(self):
        return "+".join(self.axes)


_default_group = Group(("dp",))


def new_group(ranks=None, axes=None):
    """Create a collective group bound to mesh axes.

    The reference keys groups by ring_id over explicit rank lists
    (collective.py:_new_ring_id); on a mesh the natural key is the axis
    name. ``ranks`` is accepted for API compat and ignored (device
    placement is the mesh's concern).
    """
    return Group(axes or ("dp",))


def _axes(group):
    g = group or _default_group
    return tuple(g.axes) if isinstance(g, Group) else (g,)


def _unwrap(t):
    return t._array if isinstance(t, Tensor) else t


def _rewrap(arr, like):
    if isinstance(like, Tensor):
        like._array = arr
        return like
    return arr


def _in_trace(arr) -> bool:
    return isinstance(arr, jax.core.Tracer)


def _nbytes(arr) -> int:
    """Payload size of an array or tracer (0 if unknowable)."""
    try:
        shape = arr.shape
        itemsize = np.dtype(arr.dtype).itemsize
    except Exception:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * itemsize


def _group_size(group) -> int:
    """Number of participants the group's mesh axes span (1 when no mesh
    is active — eager identity collectives move no bytes)."""
    mesh = get_mesh()
    if mesh is None:
        return 1
    n = 1
    for ax in _valid_axes(_axes(group)):
        n *= int(mesh.shape[ax])
    return n


# Per-link wire-traffic factors over the *input payload* B for an
# n-member group (ring-algorithm accounting, the nccl-tests "bus
# bandwidth" convention): what actually crosses each ICI link, i.e. the
# bytes EQuARX-style compressed collectives would shrink. all_gather's
# input is the local shard, so its wire traffic is (n-1)·B; the
# reduce-shaped primitives move fractions of their full-array input.
_ALGO_FACTORS = {
    "all_reduce": lambda n: 2.0 * (n - 1) / n,
    "reduce": lambda n: 2.0 * (n - 1) / n,
    "all_gather": lambda n: float(n - 1),
    "reduce_scatter": lambda n: (n - 1) / n,
    "broadcast": lambda n: (n - 1) / n,
    "scatter": lambda n: (n - 1) / n,
    "alltoall": lambda n: (n - 1) / n,
    "p2p": lambda n: 1.0,
    "shift": lambda n: 1.0,
}


def _algo_bytes(name, nbytes, n) -> int:
    """Algorithmic per-link wire bytes of one collective call (0 for a
    lone participant, unknown primitives, or byte-less calls)."""
    if n <= 1 or not nbytes:
        return 0
    factor = _ALGO_FACTORS.get(name)
    if factor is None:
        return 0
    return int(nbytes * factor(n))


def per_execution_algo_bytes() -> dict:
    """Per-primitive algorithmic ICI wire bytes ONE execution of the
    traced program(s) moves: the ``collective/<prim>/traced_algo_bytes``
    counters. Each traced call is recorded once at trace time, and the
    lowered collective runs once per execution of the compiled step — so
    this is the per-step wire volume (re-traces of the same step would
    double-count; reset the registry around a trace if that matters)."""
    out = {}
    for name, m in _mon.all_metrics().items():
        if name.startswith("collective/") and \
                name.endswith("/traced_algo_bytes"):
            out[name.split("/")[1]] = m.value
    return out


def ici_bus_util(executions_per_s, peaks=None) -> dict:
    """Per-primitive ICI bus utilization: algorithmic per-execution wire
    bytes × how often the compiled step runs, over the device's ICI
    peak (cost_model.device_peaks). The caller supplies the execution
    rate (the TrainingMonitor's steps/sec); the result lands in
    ``collective/<prim>/bus_util`` gauges and is returned, ``"total"``
    included. Eager collectives contribute nothing — in this
    single-controller runtime they are identity transforms that move no
    wire bytes, and timing them would fabricate utilization."""
    peaks = peaks or _cost.device_peaks()
    ici = peaks.get("ici_bw") or 0
    out = {}
    if not ici or not executions_per_s:
        return out
    total = 0.0
    for prim, nbytes in per_execution_algo_bytes().items():
        util = nbytes * float(executions_per_s) / ici
        _mon.gauge(f"collective/{prim}/bus_util").set(util)
        out[prim] = util
        total += util
    if out:
        out["total"] = total
    return out


class _account:
    """Per-primitive byte/latency accounting + host span + flight record.

    Every collective call bumps ``collective/<name>/calls`` and
    ``collective/<name>/bytes`` (input payload size — the comms volume a
    quantized all-reduce would shrink, the precondition for measuring
    EQuARX-style wins) and observes ``collective/<name>/latency_ms``.
    Under tracing the latency is trace-time, so only the call/byte
    counters are recorded (suffixed ``traced_``: one trace stands for N
    executions, counting it as live traffic would lie).

    Utilization accounting: a TRACED call additionally records its
    *algorithmic* wire bytes (payload × the primitive's ring factor over
    the group's mesh size — ``_algo_bytes``) in
    ``collective/<name>/traced_algo_bytes`` — the per-execution ICI
    volume of the compiled program, the EQuARX denominator
    (:func:`ici_bus_util` turns it into bus utilization at a given step
    rate). Eager calls record NO algo bytes: in this single-controller
    runtime they are identity transforms — the global view already holds
    the result — so no wire traffic exists to account.

    Each call is also recorded in the flight recorder with the group's
    next monotonic sequence number and a shape/dtype/reduce-op
    fingerprint — the per-rank evidence the desync exchange compares
    when a mismatched collective would otherwise just deadlock dark.
    A completed (non-traced) call feeds the hang watchdog's progress
    clock.
    """

    def __init__(self, name, arr, group=None, reduce_op=None):
        self.name = name
        self.traced = _in_trace(arr)
        self.bytes = _nbytes(arr)
        # wire-volume accounting is trace-time only: the lowered program
        # moves these bytes once per execution; an eager identity call
        # moves none (counting it would fabricate traffic)
        self.algo_bytes = (_algo_bytes(name, self.bytes,
                                       _group_size(group))
                           if self.traced else 0)
        self.group_name = "+".join(_axes(group))
        self.reduce_op = reduce_op
        # wait() is a rank-LOCAL stream sync (c_sync_*_stream compat): a
        # single rank may legally call it alone, so it must not consume
        # a cross-rank desync sequence number
        self.sequenced = name != "wait"
        try:
            self.shape = tuple(arr.shape)
            self.dtype = str(arr.dtype)
        except Exception:  # barrier (arr None) / non-array payloads
            self.shape, self.dtype = (), ""
        self.span = None
        self.t0 = 0.0

    def __enter__(self):
        prefix = "traced_" if self.traced else ""
        _mon.counter(f"collective/{self.name}/{prefix}calls").inc()
        if self.bytes:
            _mon.counter(
                f"collective/{self.name}/{prefix}bytes").inc(self.bytes)
        if self.algo_bytes:
            _mon.counter(
                f"collective/{self.name}/{prefix}algo_bytes").inc(
                self.algo_bytes)
        _flight.record_collective(
            self.name, self.group_name, shape=self.shape, dtype=self.dtype,
            reduce_op=self.reduce_op, traced=self.traced, nbytes=self.bytes,
            sequenced=self.sequenced)
        if not self.traced:
            self.span = RecordEvent(f"collective::{self.name}").begin()
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if not self.traced:
            _mon.histogram(f"collective/{self.name}/latency_ms").observe(
                (time.perf_counter() - self.t0) * 1e3)
            self.span.end()
            if exc[0] is None:
                _flight.notify_progress(f"collective:{self.name}")
        return False


def _valid_axes(axes):
    """Keep only axes present in the current mesh (size>1 not required)."""
    mesh = get_mesh()
    if mesh is None:
        return ()
    return tuple(a for a in axes if a in mesh.shape)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In traced code: psum/pmax/pmin/pprod over the group's mesh axes.
    Eager: identity (single-controller holds the global view already)."""
    arr = _unwrap(tensor)
    with _account("all_reduce", arr, group, op):
        if _in_trace(arr):
            axes = _valid_axes(_axes(group))
            if axes:
                if op == ReduceOp.SUM:
                    arr = lax.psum(arr, axes)
                elif op == ReduceOp.MAX:
                    arr = lax.pmax(arr, axes)
                elif op == ReduceOp.MIN:
                    arr = lax.pmin(arr, axes)
                elif op == ReduceOp.PROD:
                    arr = jnp.exp(lax.psum(jnp.log(arr), axes))
                elif op == ReduceOp.AVG:
                    arr = lax.pmean(arr, axes)
                else:
                    raise ValueError(f"unknown reduce op {op}")
    return _rewrap(arr, tensor)


def pmean(tensor, group=None):
    return all_reduce(tensor, op=ReduceOp.AVG, group=group)


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Traced: take the value from index ``src`` along the group axis.
    Eager: identity."""
    arr = _unwrap(tensor)
    with _account("broadcast", arr, group):
        if _in_trace(arr):
            for ax in _valid_axes(_axes(group)):
                arr = _broadcast_on_axis(arr, src, ax)
    return _rewrap(arr, tensor)


def _broadcast_on_axis(arr, src, ax):
    """Uninstrumented traced broadcast core: one-hot select of src's
    shard, summed to all members. Shared with scatter so a scatter's
    payload is accounted once under scatter, never also as a
    broadcast."""
    idx = lax.axis_index(ax)
    mask = (idx == src).astype(arr.dtype)
    return lax.psum(arr * mask, ax)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce-to-one. On mesh hardware the all-reduce and reduce cost the
    same over ICI, so this is all_reduce (the reference's c_reduce_* are
    likewise allreduce-shaped on ring hardware)."""
    return all_reduce(tensor, op=op, group=group)


def all_gather(tensor_list, tensor=None, group=None, sync_op=True):
    """Paddle signature: all_gather(tensor_list, tensor). Traced: gather
    along a new leading axis over the group axis. Also usable functional
    style: out = all_gather(None, tensor)."""
    if tensor is None and not isinstance(tensor_list, list):
        tensor_list, tensor = None, tensor_list
    arr = _unwrap(tensor)
    with _account("all_gather", arr, group):
        if _in_trace(arr):
            axes = _valid_axes(_axes(group))
            out = arr
            for ax in axes:
                out = lax.all_gather(out, ax)
                out = out.reshape((-1,) + arr.shape)
            parts = out
        else:
            parts = arr[None]
    if tensor_list is not None:
        n = parts.shape[0]
        tensor_list.clear()
        for i in range(n):
            tensor_list.append(
                Tensor._from_array(parts[i])
                if isinstance(tensor, Tensor)
                else parts[i]
            )
        return tensor_list
    return Tensor._from_array(parts) if isinstance(tensor, Tensor) else parts


def reduce_scatter(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """c_reducescatter equivalent: psum_scatter along the leading dim."""
    arr = _unwrap(tensor)
    with _account("reduce_scatter", arr, group, op):
        if _in_trace(arr):
            axes = _valid_axes(_axes(group))
            for ax in axes:
                arr = lax.psum_scatter(arr, ax, tiled=True)
    return _rewrap(arr, tensor) if not isinstance(tensor, Tensor) else Tensor._from_array(arr)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Traced: each member takes its slice of src's value."""
    arr = _unwrap(tensor)
    with _account("scatter", arr, group):
        if _in_trace(arr):
            axes = _valid_axes(_axes(group))
            for ax in axes:
                full = _broadcast_on_axis(arr, src, ax)
                n = get_mesh().shape[ax]
                idx = lax.axis_index(ax)
                size = full.shape[0] // n
                arr = lax.dynamic_slice_in_dim(full, idx * size, size,
                                               axis=0)
    return _rewrap(arr, tensor) if not isinstance(tensor, Tensor) else Tensor._from_array(arr)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """All-to-all over the group axis (basis of expert parallelism)."""
    arr = _unwrap(in_tensor_list)
    with _account("alltoall", arr, group):
        if _in_trace(arr):
            axes = _valid_axes(_axes(group))
            for ax in axes:
                n = get_mesh().shape[ax]
                arr = lax.all_to_all(
                    arr.reshape((n, -1) + arr.shape[1:]),
                    ax, split_axis=0, concat_axis=0, tiled=False,
                ).reshape((-1,) + arr.shape[1:])
    return (
        Tensor._from_array(arr)
        if isinstance(in_tensor_list, Tensor)
        else arr
    )


def p2p(tensor, src, dst, group=None):
    """Paired point-to-point as ONE static single-pair permutation.

    SPMD semantics: rank ``dst`` ends up with rank ``src``'s value; every
    other rank gets zeros (lax.ppermute's untargeted-destination rule).
    This is how a matched send/recv pair lowers in a single compiled
    program — see parallel.pipeline for the pipeline-parallel use.
    """
    arr = _unwrap(tensor)
    with _account("p2p", arr, group):
        if _in_trace(arr):
            axes = _valid_axes(_axes(group))
            for ax in axes:
                n = get_mesh().shape[ax]
                arr = lax.ppermute(arr, ax, [(src % n, dst % n)])
    # never mutate the input: untargeted ranks get zeros, and writing that
    # back would destroy the sender's local copy (paddle.distributed.send
    # leaves the argument intact)
    return Tensor._from_array(arr) if isinstance(tensor, Tensor) else arr


def send(tensor, dst, group=None, sync_op=True, src=None):
    """Point-to-point send. In SPMD traced code both endpoints must be
    static, so the matched pair is expressed as one permutation: pass
    ``src`` (the sending rank) alongside ``dst``. lax.ppermute requires
    unique sources/destinations — a one-to-all or all-to-one perm is
    invalid, hence the single-pair form."""
    arr = _unwrap(tensor)
    if _in_trace(arr):
        if src is None:
            raise ValueError(
                "send() inside traced/SPMD code needs both endpoints: "
                "send(tensor, dst, src=<sending rank>) — a paired p2p "
                "lowers to a single-pair ppermute (see collective.p2p)"
            )
        return p2p(tensor, src, dst, group=group)
    return tensor


def recv(tensor, src, group=None, sync_op=True, dst=None):
    """Point-to-point receive; the SPMD twin of :func:`send` — pass
    ``dst`` (the receiving rank) so the pair lowers to one permutation."""
    arr = _unwrap(tensor)
    if _in_trace(arr):
        if dst is None:
            raise ValueError(
                "recv() inside traced/SPMD code needs both endpoints: "
                "recv(tensor, src, dst=<receiving rank>) — a paired p2p "
                "lowers to a single-pair ppermute (see collective.p2p)"
            )
        return p2p(tensor, src, dst, group=group)
    return tensor


def shift(tensor, offset=1, group=None):
    """Ring shift (ppermute by offset) — the primitive under ring attention
    and pipeline handoff."""
    arr = _unwrap(tensor)
    with _account("shift", arr, group):
        if _in_trace(arr):
            axes = _valid_axes(_axes(group))
            for ax in axes:
                n = get_mesh().shape[ax]
                perm = [(i, (i + offset) % n) for i in range(n)]
                arr = lax.ppermute(arr, ax, perm)
    return _rewrap(arr, tensor)


def barrier(group=None):
    """operators/collective/barrier_op.cc equivalent. Eager single
    controller: block until all pending device work completes."""
    with _account("barrier", None, group):
        (jnp.zeros(()) + 0).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    """c_sync_*_stream compat: XLA has no user-visible streams; block on
    the value instead."""
    arr = _unwrap(tensor)
    if not _in_trace(arr):
        with _account("wait", arr, group):
            jax.block_until_ready(arr)
    return tensor
