"""Multi-process launcher.

Reference parity: python/paddle/distributed/launch.py — spawns one process
per GPU, wiring PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS /
PADDLE_CURRENT_ENDPOINT env.

TPU-native: one process drives all chips of a host (single-controller), so
processes == hosts, not devices. ``spawn`` exists for multi-host emulation
and CPU-mesh testing (SURVEY.md §4: subprocess tests on localhost); on a
real pod each host runs the same script and jax.distributed coordinates.

Usage: python -m paddle_tpu.distributed.launch --nproc 2 train.py

Fault diagnosis: ``--debug-port 8080`` hands every rank a live debug
endpoint (rank r serves /healthz /metrics /flightrecorder /threadz
/flagz on 127.0.0.1:8080+r via FLAGS_debug_port), and
``--watchdog-timeout 300`` arms each rank's hang watchdog
(FLAGS_watchdog_timeout_s) so a stalled fleet dumps its flight recorder
+ cross-rank desync report instead of hanging silently.
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _build_env(rank: int, nproc: int, coordinator: str, base_env=None):
    env = dict(base_env or os.environ)
    env.update(
        PADDLE_TRAINER_ID=str(rank),
        PADDLE_TRAINERS_NUM=str(nproc),
        PADDLE_COORDINATOR=coordinator,
        PADDLE_TRAINER_ENDPOINTS=",".join(
            f"127.0.0.1:{int(coordinator.split(':')[1]) + i}"
            for i in range(nproc)
        ),
        PADDLE_CURRENT_ENDPOINT=f"127.0.0.1:{int(coordinator.split(':')[1]) + rank}",
    )
    return env


def launch_procs(script_args, nproc: int = 1, env=None, debug_port=0,
                 watchdog_timeout=0.0):
    """Spawn nproc copies of `python script args...`; returns Popen list.

    ``debug_port``/``watchdog_timeout`` wire the fault-diagnosis flags
    into every rank's environment (rank r's debug server binds
    ``debug_port + r`` — the +rank offset happens inside
    monitor.flight_recorder.install_from_flags).
    """
    coordinator = f"127.0.0.1:{_free_port()}"
    procs = []
    for rank in range(nproc):
        penv = _build_env(rank, nproc, coordinator, env)
        if debug_port:
            penv["FLAGS_debug_port"] = str(int(debug_port))
        if watchdog_timeout:
            penv["FLAGS_watchdog_timeout_s"] = str(float(watchdog_timeout))
        procs.append(
            subprocess.Popen([sys.executable] + list(script_args), env=penv)
        )
    return procs


def spawn(func=None, args=(), nprocs=1, **kwargs):
    """paddle.distributed.spawn equivalent.

    Single-controller note: with nprocs==1 (the TPU-normal case) the
    function runs inline — device parallelism comes from the mesh, not
    from processes.
    """
    if nprocs == 1:
        from .env import init_parallel_env

        init_parallel_env()
        return func(*args) if func is not None else None
    raise NotImplementedError(
        "multi-host spawn: launch one process per host with "
        "python -m paddle_tpu.distributed.launch (processes are hosts on "
        "TPU, not devices; in-host parallelism uses the mesh)"
    )


def main():
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nproc", type=int, default=1)
    p.add_argument("--debug-port", type=int, default=0,
                   help="base port for per-rank /debugz endpoints "
                        "(rank r serves on port+r; 0: off)")
    p.add_argument("--watchdog-timeout", type=float, default=0.0,
                   help="per-rank hang-watchdog deadline in seconds "
                        "(0: off)")
    p.add_argument("script", nargs=argparse.REMAINDER)
    ns = p.parse_args()
    procs = launch_procs(ns.script, ns.nproc, debug_port=ns.debug_port,
                         watchdog_timeout=ns.watchdog_timeout)
    code = 0
    for proc in procs:
        code |= proc.wait()
    sys.exit(code)


if __name__ == "__main__":
    main()
