"""Encrypted model save/load.

Reference parity: paddle/fluid/framework/io/crypto/ (AESCipher,
aes_cipher.h:48, cipher_utils.h) exposed through pybind/crypto.cc —
key generation + encrypt/decrypt of model files so checkpoints at rest
are protected.

The reference uses cryptopp AES-GCM; here the `cryptography` package
provides AESGCM. File format: 12-byte nonce || ciphertext+tag.
"""
from __future__ import annotations

import os

__all__ = ["CipherUtils", "AESCipher", "encrypt_file", "decrypt_file",
           "save_encrypted", "load_encrypted"]


class CipherUtils:
    """cipher_utils.h: key generation helpers."""

    @staticmethod
    def gen_key(length_bits: int = 256) -> bytes:
        if length_bits not in (128, 192, 256):
            from .errors import InvalidArgumentError

            raise InvalidArgumentError(
                f"AES key length must be 128/192/256 bits, got {length_bits}"
            )
        return os.urandom(length_bits // 8)

    @staticmethod
    def gen_key_to_file(length_bits: int, path: str) -> bytes:
        key = CipherUtils.gen_key(length_bits)
        # owner-only permissions: a world-readable key file would undo the
        # at-rest protection this module exists to provide
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        os.fchmod(fd, 0o600)  # the mode arg is ignored for pre-existing files
        with os.fdopen(fd, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()


class AESCipher:
    """aes_cipher.h:48 — AES-GCM encrypt/decrypt of byte strings and
    files."""

    NONCE_BYTES = 12

    def __init__(self, key: bytes):
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM

        self._aead = AESGCM(key)

    def encrypt(self, plaintext: bytes) -> bytes:
        nonce = os.urandom(self.NONCE_BYTES)
        return nonce + self._aead.encrypt(nonce, plaintext, None)

    def decrypt(self, blob: bytes) -> bytes:
        from .errors import PreconditionNotMetError

        if len(blob) < self.NONCE_BYTES + 16:
            raise PreconditionNotMetError(
                "ciphertext too short to hold nonce+tag (corrupt file?)"
            )
        try:
            return self._aead.decrypt(
                blob[:self.NONCE_BYTES], blob[self.NONCE_BYTES:], None
            )
        except Exception as e:
            raise PreconditionNotMetError(
                "decryption failed: wrong key or corrupted ciphertext"
            ) from e

    def encrypt_to_file(self, plaintext: bytes, path: str):
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext))

    def decrypt_from_file(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return self.decrypt(f.read())


def encrypt_file(key: bytes, in_path: str, out_path: str):
    with open(in_path, "rb") as f:
        AESCipher(key).encrypt_to_file(f.read(), out_path)


def decrypt_file(key: bytes, in_path: str, out_path: str):
    data = AESCipher(key).decrypt_from_file(in_path)
    with open(out_path, "wb") as f:
        f.write(data)


def save_encrypted(obj, path: str, key: bytes):
    """paddle.save + at-rest encryption (the fleet encrypted-persistables
    flow, framework/io/crypto + save_combine). Fully in-memory: the
    plaintext checkpoint never touches disk."""
    from .framework import serialization

    AESCipher(key).encrypt_to_file(serialization.dumps(obj), path)


def load_encrypted(path: str, key: bytes):
    from .framework import serialization

    return serialization.loads(AESCipher(key).decrypt_from_file(path))
