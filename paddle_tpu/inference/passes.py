"""Inference IR passes.

Reference parity: inference/analysis/ir_pass_manager.cc + the pass list
of api/paddle_pass_builder.cc. On this runtime most of the reference's
fusion passes (conv_bn_fuse, fc_fuse, multihead_matmul_fuse, …) are
XLA's job — the whole block compiles into one fused HLO module — so the
passes that still pay are the *graph-shrinking* ones that XLA never
sees: constant folding (precompute everything not reachable from a
feed; fewer ops to trace+compile, weights pre-transformed at load time)
and dead-op elimination (drop ops whose outputs no fetch needs).
"""
from __future__ import annotations

import numpy as np

__all__ = ["IrPassManager", "constant_folding_pass", "dead_op_elimination_pass"]


def _op_outputs(op):
    return [n for ns in op.outputs.values() for n in ns if n]


def _op_inputs(op):
    return [n for ns in op.inputs.values() for n in ns if n]


def dead_op_elimination_pass(program, fetch_names):
    """Remove top-block ops no fetch transitively depends on.

    Reference: the DCE effect of ir/graph passes (e.g.
    delete_quant_dequant leftovers); returns the number of ops removed.
    """
    block = program.global_block()
    needed = set(fetch_names)
    keep = []
    for op in reversed(block.ops):
        outs = _op_outputs(op)
        if any(o in needed for o in outs):
            keep.append(op)
            needed.update(_op_inputs(op))
    keep.reverse()
    removed = len(block.ops) - len(keep)
    block.ops[:] = keep
    if removed:
        program._version = getattr(program, "_version", 0) + 1
    return removed


def constant_folding_pass(program, scope, feed_names, fetch_names):
    """Precompute every op not reachable from a feed.

    An op whose inputs are all load-time constants (parameters in the
    scope, captured constants, or outputs of already-folded ops) runs
    ONCE here with the real kernels; its outputs become scope-resident
    persistable vars and the op disappears from the block. Weight
    pre-transformations (reshape/transpose/cast of params, bias
    reshapes, `full`-style literals) all collapse at load time.

    RNG ops and control-flow ops never fold. Returns ops folded.
    """
    from ..ops.registry import kernel

    block = program.global_block()
    consts = dict(getattr(program, "_constants", {}) or {})
    available = set(consts)
    for name in scope.var_names():
        available.add(name)
    feeds = set(feed_names)
    fetches = set(fetch_names)

    folded = 0
    keep = []
    for op in block.ops:
        ins = _op_inputs(op)
        outs = _op_outputs(op)
        foldable = (
            op.type not in ("while", "cond", "scan", "feed", "fetch")
            and not op.type.startswith("grad::")
            and not op.attrs.get("__rng__")
            and all(n in available and n not in feeds for n in ins)
            and outs
        )
        if not foldable:
            keep.append(op)
            continue
        attrs = {k: v for k, v in op.attrs.items() if not k.startswith("__")}
        args = []
        for n in ins:
            args.append(scope.get(n) if scope.has(n) else consts[n])
        try:
            out = kernel(op.type)(*args, **attrs)
        except Exception:
            keep.append(op)  # kernel refused (e.g. eager-only guard)
            continue
        results = list(out) if isinstance(out, (tuple, list)) else [out]
        for name, value in zip(op.outputs.get("Out", []), results):
            if not name or value is None:
                continue
            scope.set(name, value)
            if block.has_var(name):
                block.var(name).persistable = True
            available.add(name)
        folded += 1
    block.ops[:] = keep
    if folded:
        program._version = getattr(program, "_version", 0) + 1
    return folded


class IrPassManager:
    """ir_pass_manager.cc equivalent: ordered pass application with stats."""

    def __init__(self, passes=None):
        self.passes = passes or ["constant_folding", "dead_op_elimination"]
        self.stats = {}

    def apply(self, program, scope, feed_names, fetch_names):
        block = program.global_block()
        self.stats = {"ops_before": len(block.ops)}
        for name in self.passes:
            if name == "constant_folding":
                self.stats["folded"] = constant_folding_pass(
                    program, scope, feed_names, fetch_names
                )
            elif name == "dead_op_elimination":
                self.stats["dce_removed"] = dead_op_elimination_pass(
                    program, fetch_names
                )
            else:
                from ..errors import NotFoundError

                raise NotFoundError(f"unknown inference pass {name!r}")
        self.stats["ops_after"] = len(block.ops)
        return self.stats
