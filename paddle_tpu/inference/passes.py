"""Inference IR passes — facade over the program-IR optimizer.

Reference parity: inference/analysis/ir_pass_manager.cc + the pass list
of api/paddle_pass_builder.cc. The Predictor-local pipeline that used to
live here (constant folding + dead-op elimination) was generalized into
:mod:`paddle_tpu.analysis.optimizer` (ISSUE 16) so ``Executor.run`` and
the Predictor share one registered pass pipeline; this module keeps the
stable load-time API — ``IrPassManager`` and the two pass functions —
and delegates to the registered optimizer passes. The legacy stats
shape (``{ops_before, folded, dce_removed, ops_after}``) is preserved
for ``Predictor.pass_stats`` consumers.
"""
from __future__ import annotations

from ..analysis import optimizer as _opt

__all__ = ["IrPassManager", "constant_folding_pass", "dead_op_elimination_pass"]

# optimizer pass name -> legacy Predictor.pass_stats key
_LEGACY_KEY = {"constant_folding": "folded", "dead_op_elimination": "dce_removed"}


def constant_folding_pass(program, scope, feed_names, fetch_names):
    """Precompute every op not reachable from a feed.

    An op whose inputs are all load-time constants (parameters in the
    scope, captured constants, or outputs of already-folded ops) runs
    ONCE with the real kernels; its outputs become scope-resident
    persistable vars and the op disappears from the block. RNG ops and
    control-flow ops never fold. Returns ops folded. Delegates to the
    registered ``constant_folding`` optimizer pass.
    """
    return _opt.constant_folding(
        _opt.OptContext(program, feed_names, fetch_names, scope=scope))


def dead_op_elimination_pass(program, fetch_names):
    """Remove top-block ops no fetch transitively depends on.

    Returns the number of ops removed. Delegates to the registered
    ``dead_op_elimination`` optimizer pass (iterative, side-effect
    aware: control flow, ``grad::`` replays, ``__inplace__`` ops and
    persistable writers are always kept).
    """
    return _opt.dead_op_elimination(_opt.OptContext(program, (), fetch_names))


class IrPassManager:
    """ir_pass_manager.cc equivalent: ordered pass application with stats.

    Now a facade over :class:`paddle_tpu.analysis.optimizer.PassManager`
    — same two-pass load-time pipeline, same legacy stats dict, but the
    passes themselves (and their verify/replan contract plus per-pass
    counters) come from the shared optimizer registry.
    """

    def __init__(self, passes=None):
        self.passes = passes or ["constant_folding", "dead_op_elimination"]
        self.stats = {}

    def apply(self, program, scope, feed_names, fetch_names):
        block = program.global_block()
        self.stats = {"ops_before": len(block.ops)}
        pm = _opt.PassManager(self.passes)  # NotFoundError on unknown names
        for st in pm.apply(program, feed_names, fetch_names, level=1,
                           scope=scope):
            self.stats[_LEGACY_KEY.get(st.name, st.name)] = st.ops_rewritten
        self.stats["ops_after"] = len(block.ops)
        return self.stats
