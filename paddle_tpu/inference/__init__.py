"""Inference API.

Reference parity: paddle/fluid/inference/api/ — AnalysisConfig
(paddle_analysis_config.h), AnalysisPredictor (analysis_predictor.h:82),
create_paddle_predictor, PaddleTensor handles. The pass-pipeline
optimization role (ir_pass_manager.cc fusions, memory_optimize_pass) is
played by XLA: the pruned inference program compiles to one fused HLO
module on first run and is cached per input signature (NaiveExecutor's
no-churn hot loop ≙ replaying the compiled executable).
"""
from .predictor import (  # noqa: F401
    Config,
    Predictor,
    Tensor as PredictorTensor,
    create_predictor,
)

AnalysisConfig = Config
