"""Predictor implementation (analysis_predictor.cc equivalent)."""
from __future__ import annotations

import os
import warnings

import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor"]


def _compat_noop(name, why):
    """Accepted-for-compat Config methods warn instead of silently doing
    nothing (AnalysisConfig parity without a false sense of effect)."""
    warnings.warn(
        f"inference.Config.{name} has no effect on the TPU runtime: {why}",
        stacklevel=3,
    )


class Config:
    """AnalysisConfig surface (paddle_analysis_config.h)."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self._model_dir = model_dir
        self._prog_file = prog_file
        self._params_file = params_file
        self._use_tpu = True
        self._memory_optim = True
        self._ir_optim = True

    def set_model(self, model_dir, params_file=None):
        self._model_dir = model_dir
        self._params_file = params_file

    def model_dir(self):
        return self._model_dir

    def enable_use_gpu(self, memory_pool_mb=100, device_id=0):
        _compat_noop("enable_use_gpu",
                     "device selection and memory pools are XLA's")

    def enable_tpu(self):
        self._use_tpu = True

    def disable_gpu(self):
        self._use_tpu = False

    def switch_ir_optim(self, flag=True):
        """Toggle the load-time pass pipeline (ir_pass_manager.cc):
        constant folding + dead-op elimination."""
        self._ir_optim = flag

    def enable_memory_optim(self, flag=True):
        _compat_noop("enable_memory_optim",
                     "XLA's buffer assignment already reuses activations")
        self._memory_optim = flag

    def set_cpu_math_library_num_threads(self, n):
        _compat_noop("set_cpu_math_library_num_threads",
                     "host threading is managed by the XLA client")

    def enable_tensorrt_engine(self, *a, **k):
        _compat_noop("enable_tensorrt_engine",
                     "there is no TensorRT; XLA compiles the whole graph")

    def enable_mkldnn(self, *a, **k):
        _compat_noop("enable_mkldnn", "no MKLDNN on this runtime")


class Tensor:
    """Input/output handle (PaddleTensor / ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._data = None

    def copy_from_cpu(self, arr):
        arr = np.asarray(arr)
        # the device feed path needs native-endian contiguous memory;
        # sliced views and big-endian arrays (network/file decoders) are
        # legitimate caller data — copy them into shape instead of
        # erroring downstream (the "copy" in copy_from_cpu)
        if not arr.dtype.isnative:
            arr = arr.astype(arr.dtype.newbyteorder("="))
        if not arr.flags["C_CONTIGUOUS"]:
            arr = np.ascontiguousarray(arr)
        self._data = arr

    def copy_to_cpu(self):
        return np.asarray(self._data)

    def reshape(self, shape):
        if self._data is not None:
            self._data = self._data.reshape(shape)

    def shape(self):
        return list(self._data.shape) if self._data is not None else None


class Predictor:
    """AnalysisPredictor equivalent over the jitted static executor."""

    def __init__(self, config: Config):
        from ..static import Executor, io as static_io

        self.config = config
        self._exe = Executor()
        self._program, self._feed_names, self._fetch_names = (
            static_io.load_inference_model(
                config.model_dir(), self._exe,
                model_filename=config._prog_file,
                params_filename=config._params_file,
            )
        )
        self.pass_stats = {}
        if config._ir_optim:
            # ir_pass_manager.cc: load-time graph optimization
            from ..static.executor import global_scope
            from .passes import IrPassManager

            pm = IrPassManager()
            self.pass_stats = pm.apply(
                self._program, global_scope(),
                self._feed_names, self._fetch_names,
            )
        self._inputs = {n: Tensor(n) for n in self._feed_names}
        self._outputs = {n: Tensor(n) for n in self._fetch_names}

    def get_input_names(self):
        return list(self._feed_names)

    def quant_metadata(self):
        """Scale metadata of a loaded int8 model (the ``__quant__.json``
        sidecar ``slim.ptq.save_int8_model`` writes): bits, per-var
        scales, int8 weight names. None for ordinary f32 models — the
        check an operator's tooling runs to confirm WHAT a serving
        backend actually loaded."""
        from ..slim.ptq import load_quant_metadata

        return load_quant_metadata(self.config.model_dir())

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    get_input_tensor = get_input_handle

    def get_output_handle(self, name):
        return self._outputs[name]

    get_output_tensor = get_output_handle

    def clone(self):
        """Replica twin sharing the compiled-program caches.

        The clone reuses this predictor's Executor — and with it the
        RunPlan and jit/AOT executable caches — plus the loaded program
        and scope-resident weights, so N clones serve with ZERO extra
        XLA compiles (AnalysisPredictor::Clone's shared-program intent,
        realized at the executable-cache level). Only the IO tensor
        handles are per-clone: concurrent worker threads stage inputs
        and read outputs without racing each other.
        """
        new = object.__new__(Predictor)
        new.config = self.config
        new._exe = self._exe          # shared: jit/AOT + plan caches
        new._program = self._program  # shared identity -> shared plans
        new._feed_names = self._feed_names
        new._fetch_names = self._fetch_names
        new.pass_stats = self.pass_stats
        new._inputs = {n: Tensor(n) for n in self._feed_names}
        new._outputs = {n: Tensor(n) for n in self._fetch_names}
        return new

    def run(self, inputs=None):
        """Zero-copy style: stage inputs via handles then run(); or pass a
        list of numpy arrays matching get_input_names() order."""
        if inputs is not None:
            for n, arr in zip(self._feed_names, inputs):
                self._inputs[n].copy_from_cpu(arr)
        feed = {n: self._inputs[n]._data for n in self._feed_names}
        for n, v in feed.items():
            if v is None:
                raise RuntimeError(f"input {n!r} not set")
        outs = self._exe.run(
            self._program, feed=feed, fetch_list=self._fetch_names
        )
        for n, o in zip(self._fetch_names, outs):
            self._outputs[n]._data = o
        return outs


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
