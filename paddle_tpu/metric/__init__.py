"""paddle.metric equivalent.

Reference parity: python/paddle/metric/metrics.py (Metric base, Accuracy,
Precision, Recall, Auc) and fluid/metrics.py streaming metrics.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def _np(x):
    from ..framework.tensor import Tensor

    if isinstance(x, Tensor):
        return x.numpy()
    return np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, pred, label, *args):
        """Optional pre-processing run inside the (possibly compiled)
        eval step; default passthrough."""
        return pred, label


class Accuracy(Metric):
    """Top-k accuracy (metrics.py Accuracy)."""

    def __init__(self, topk=(1,), name="acc"):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim:
            label = label.squeeze(-1)
        correct = idx == label[..., None]
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        for i, k in enumerate(self.topk):
            c = correct[..., :k].any(axis=-1)
            self.total[i] += c.sum()
            self.count[i] += c.size
        c0 = correct[..., : self.topk[0]].any(axis=-1)
        return float(c0.mean())

    def accumulate(self):
        res = [
            float(t / c) if c > 0 else 0.0
            for t, c in zip(self.total, self.count)
        ]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision (metrics.py Precision)."""

    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp / denom) if denom else 0.0


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp / denom) if denom else 0.0


class Auc(Metric):
    """ROC AUC via histogram buckets (metrics.py Auc / auc_op.cc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._neg = np.zeros(self.num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = _np(labels).reshape(-1)
        buckets = np.minimum(
            (preds * self.num_thresholds).astype(np.int64),
            self.num_thresholds,
        )
        np.add.at(self._pos, buckets[labels == 1], 1)
        np.add.at(self._neg, buckets[labels == 0], 1)

    def accumulate(self):
        tot_pos = self._pos.sum()
        tot_neg = self._neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # sum over buckets: neg_i * (pos_above_i + pos_i/2)
        pos_cum = np.cumsum(self._pos[::-1])[::-1]
        pos_above = pos_cum - self._pos
        auc = (self._neg * (pos_above + self._pos / 2.0)).sum()
        return float(auc / (tot_pos * tot_neg))
