"""All-to-all sequence parallelism (DeepSpeed-Ulysses style) over sp.

Beyond-reference capability, the second long-context mode next to
ring_attention (SURVEY.md §5): instead of rotating K/V blocks around a
ring, ONE all-to-all re-shards activations from sequence-sharded
[B, H, L/sp, D] to head-sharded [B, H/sp, L, D]; each device then runs
ordinary full-sequence attention on its head slice, and a second
all-to-all restores sequence sharding.

Trade-off vs ring attention (why both exist): Ulysses does 2 all-to-alls
of the Q/K/V/O activations total — cheaper than the ring's (sp-1) K/V
hops when sp is large and heads are plentiful — but requires
num_heads % sp == 0 and holds full-length K/V per head slice, so its
max L is bounded by per-chip HBM while the ring's is not. Both ride ICI
(lax.all_to_all / ppermute under shard_map).

Dispatch plumbing (shard_map island, Tensor tape routing, eager
resharding) is shared with ring_attention via _dispatch_sp_attention.
"""
from __future__ import annotations

from functools import partial

from jax import lax

from .ring_attention import _dispatch_sp_attention, _plain_attention

__all__ = ["ulysses_attention"]


def _ulysses_body(q, k, v, mask, *, axis, scale, causal):
    """Per-shard body. q,k,v local: [B, H, L/sp, D]; mask local
    [B, 1, 1, L/sp] (additive, K-dim sharded) or None."""

    def seq_to_heads(x):
        # [B, H, Ls, D] -> all_to_all on H -> [B, H/sp, L, D]
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if mask is not None:
        # the additive mask is K-dim sharded; attention over the FULL
        # sequence needs the full mask — all_gather the (tiny) [B,1,1,Ls]
        # strip along its last dim
        mask = lax.all_gather(mask, axis, axis=3, tiled=True)
    out = _plain_attention(qh, kh, vh, mask, scale, causal)
    return heads_to_seq(out.astype(q.dtype))


def ulysses_attention(q, k, v, mask=None, axis="sp", causal=False,
                      scale=None, mesh=None):
    """Attention with head↔sequence all-to-all re-sharding over ``axis``.

    q, k, v: [B, H, L, D] arrays (or Tensors) with L sharded over
    ``axis``; requires H % axis_size == 0. mask: additive [B, 1, 1, L]
    (K-dim sharded, same contract as ring_attention). Falls back to plain
    attention when no mesh / axis size 1.
    """

    def guard(qa, n):
        if qa.shape[1] % n != 0:
            raise ValueError(
                f"ulysses_attention needs num_heads ({qa.shape[1]}) "
                f"divisible by the {axis!r} axis size ({n}); use "
                "ring_attention for head counts that do not split"
            )

    return _dispatch_sp_attention(
        "ulysses_attention",
        lambda scale: partial(_ulysses_body, axis=axis, scale=scale,
                              causal=causal),
        q, k, v, mask, axis, causal, scale, mesh, guard=guard,
    )
