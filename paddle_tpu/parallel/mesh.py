"""Device-mesh management.

Reference parity: the role of ParallelExecutor's communicator setup
(paddle/fluid/framework/parallel_executor.cc:118 InitNCCLCtxs — flat and
hierarchical rings keyed by ring_id) and imperative/nccl_context.cc
bootstrap. TPU-native: one logical mesh, axes named by parallelism kind;
"rings" are mesh axes and need no bootstrap — XLA lowers collectives onto
ICI/DCN directly.
"""
from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field

import numpy as np

import jax
from jax.sharding import Mesh

# canonical axis order: pipeline outermost (cross-slice / DCN friendly),
# then data, then the intra-layer axes that want highest ICI bandwidth
AXES = ("pp", "dp", "ep", "sp", "tp")

_state = threading.local()


@dataclass
class MeshConfig:
    """Sizes of each parallelism axis (1 = disabled).

    Mirrors the role of DistributedStrategy's hierarchical-allreduce /
    nranks knobs (framework/distributed_strategy.proto:94) but expressed as
    mesh geometry.
    """

    dp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    devices: list = field(default=None)

    def total(self) -> int:
        return self.dp * self.pp * self.tp * self.sp * self.ep


def create_mesh(config: MeshConfig | None = None, **sizes) -> Mesh:
    """Build a Mesh with the canonical axis order.

    create_mesh(dp=2, tp=4) uses 8 devices; unspecified axes default to 1
    and still appear in the mesh so sharding rules can always reference
    them. With no sizes at all, all devices go to dp.
    """
    if config is None:
        config = MeshConfig(**sizes)
    devices = config.devices if config.devices is not None else jax.devices()
    n = config.total()
    if not sizes and config.dp == 1 and n == 1:
        config.dp = len(devices)
        n = config.dp
    if n > len(devices):
        raise ValueError(
            f"mesh needs {n} devices ({config}), only {len(devices)} available"
        )
    shape = [getattr(config, ax) for ax in AXES]
    arr = np.array(devices[:n]).reshape(shape)
    return Mesh(arr, AXES)


def set_mesh(mesh: Mesh | None):
    _state.mesh = mesh
    return mesh


def get_mesh() -> Mesh | None:
    return getattr(_state, "mesh", None)


def in_mesh() -> bool:
    return get_mesh() is not None


@contextlib.contextmanager
def mesh_scope(mesh: Mesh):
    prev = get_mesh()
    set_mesh(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        set_mesh(prev)


def axis_size(axis: str, mesh: Mesh | None = None) -> int:
    mesh = mesh or get_mesh()
    if mesh is None:
        return 1
    return int(mesh.shape.get(axis, 1))
