"""Ring attention — sequence/context parallelism over the sp mesh axis.

Beyond-reference capability (SURVEY.md §2.3: SP/ring attention absent in
the reference; §5 names it the north-star extension). Design follows the
blockwise-parallel/ring attention construction: Q stays put, K/V blocks
rotate around the sp ring via lax.ppermute, and softmax is computed online
(flash-attention style running max/denominator), so no device ever holds
the full [L, L] score matrix or the full K/V sequence.

Comms ride ICI: each of the sp-1 steps moves one K/V block to the ring
neighbour while the matmuls for the current block run — XLA overlaps the
ppermute with compute.

Implemented as a shard_map island, so it nests inside a GSPMD-partitioned
train step (heads sharded on tp, batch on dp, sequence on sp).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import axis_size, get_mesh

__all__ = ["ring_attention"]


def _plain_attention(q, k, v, mask, scale, causal):
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        idx_q = jnp.arange(lq)[:, None]
        idx_k = jnp.arange(lk)[None, :]
        scores = jnp.where(idx_q >= idx_k, scores, -jnp.inf)
    if mask is not None:
        scores = scores + mask
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def _ring_body(q, k, v, mask, *, axis, scale, causal):
    """Per-shard ring attention. q,k,v: [B, H, Lq, D] local blocks."""
    n = lax.psum(1, axis)
    my = lax.axis_index(axis)
    lq = q.shape[2]
    lk = k.shape[2]

    acc = jnp.zeros(q.shape, jnp.float32)                    # weighted sum
    m = jnp.full(q.shape[:3], -jnp.inf, jnp.float32)          # running max
    denom = jnp.zeros(q.shape[:3], jnp.float32)               # running sum

    def step(i, carry):
        acc, m, denom, k, v, mask_blk = carry
        # K/V block currently held came from shard (my + i) mod n
        src = (my + i) % n
        # bf16 inputs hit the MXU; accumulation in f32
        scores = (
            jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
        )
        if causal:
            gq = my * lq + jnp.arange(lq)[:, None]
            gk = src * lk + jnp.arange(lk)[None, :]
            scores = jnp.where(gq >= gk, scores, -jnp.inf)
        if mask_blk is not None:
            scores = scores + mask_blk.astype(jnp.float32)
        blk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # guard fully-masked rows: exp(-inf - -inf)
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m) - safe_m)
        corr = jnp.where(jnp.isneginf(m), 0.0, corr)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        denom = denom * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        # rotate K/V (and K-mask) one step around the ring
        perm = [(j, (j - 1) % n) for j in range(n)]
        k = lax.ppermute(k, axis, perm)
        v = lax.ppermute(v, axis, perm)
        if mask_blk is not None:
            mask_blk = lax.ppermute(mask_blk, axis, perm)
        return acc, new_m, denom, k, v, mask_blk

    # python loop (n is static) so ppermute/compute overlap is visible to
    # the scheduler without a loop-carried dependency on trip count
    carry = (acc, m, denom, k, v, mask)
    for i in range(n):
        carry = step(i, carry)
    acc, m, denom = carry[0], carry[1], carry[2]
    denom = jnp.where(denom == 0.0, 1.0, denom)
    return (acc / denom[..., None]).astype(q.dtype)


# last SP-attention dispatch decision, written at trace/call time:
# {"op": <ring|ulysses>, "mode": "sharded"|"fallback", "axis_size": n}.
# Lets harnesses (tests, __graft_entry__.dryrun_multichip) assert the
# sequence-parallel path actually ran instead of silently falling back to
# replicated attention when the mesh/axis was absent.
LAST_DISPATCH = {}


def _dispatch_sp_attention(op_name, body_builder, q, k, v, mask, axis,
                           causal, scale, mesh, guard=None):
    """Shared dispatch tail for the two SP attention modes (ring and
    Ulysses): Tensor unwrap, plain-attention fallback without a mesh,
    partial-manual shard_map construction (sp manual, dp/tp GSPMD-auto),
    eager resharding of single-device-committed tensors, and tape
    routing. ``body_builder(scale)`` returns the per-shard body
    ``f(q, k, v, mask_or_None)``; ``guard(qa, n)`` may raise for
    unsupported geometries."""
    from ..framework.tensor import Tensor

    unwrap = lambda t: t._array if isinstance(t, Tensor) else t  # noqa: E731
    wrap_out = isinstance(q, Tensor)
    qa, ka, va = unwrap(q), unwrap(k), unwrap(v)
    ma = unwrap(mask) if mask is not None else None
    if scale is None:
        scale = float(qa.shape[-1]) ** -0.5

    mesh = mesh or get_mesh()
    n = axis_size(axis, mesh)
    LAST_DISPATCH.clear()
    LAST_DISPATCH.update(
        op=op_name,
        mode="fallback" if (mesh is None or n == 1) else "sharded",
        axis_size=n,
    )
    if mesh is None or n == 1:
        pure = lambda q, k, v, *m_: _plain_attention(  # noqa: E731
            q, k, v, m_[0] if m_ else None, scale, causal
        )
    else:
        if guard is not None:
            guard(qa, n)
        # partial-manual: only sp is manual; dp/tp remain GSPMD-auto so
        # this nests inside tp/dp-partitioned programs
        specs = P(None, None, axis, None)
        body = body_builder(scale)
        if ma is None:
            pure = jax.shard_map(
                lambda q, k, v: body(q, k, v, None),
                mesh=mesh, in_specs=(specs, specs, specs),
                out_specs=specs, axis_names={axis}, check_vma=False,
            )
        else:
            mask_spec = P(None, None, None, axis)
            pure = jax.shard_map(
                body, mesh=mesh,
                in_specs=(specs, specs, specs, mask_spec),
                out_specs=specs, axis_names={axis}, check_vma=False,
            )
        # partial-manual shard_map only lowers under jit; jit here inlines
        # when already inside an outer trace
        pure = jax.jit(pure)
    if wrap_out:
        # route through the tape (original Tensor objects) so eager
        # backward accumulates into the caller's tensors
        from ..framework.autograd import apply_op

        tensors = [q, k, v] + ([mask] if ma is not None else [])
        tensors = [t if isinstance(t, Tensor) else Tensor._from_array(jnp.asarray(t))
                   for t in tensors]
        if mesh is not None and n > 1:
            # eager edge: a SINGLE-device-committed tensor conflicts with
            # the mesh inside vjp — settle it onto the sp layout once.
            # Arrays already laid out across devices (e.g. dp-sharded by
            # the caller) are left alone: partial-manual shard_map
            # composes with their sharding as-is.
            from jax.sharding import NamedSharding

            qspec = NamedSharding(mesh, P(None, None, axis, None))
            mspec = NamedSharding(mesh, P(None, None, None, axis))
            for i, t in enumerate(tensors):
                arr = t._array
                if (not isinstance(arr, jax.core.Tracer)
                        and len(arr.sharding.device_set) == 1):
                    t._array = jax.device_put(
                        arr, mspec if (ma is not None and i == 3) else qspec,
                    )
        return apply_op(op_name, pure, tensors, {})
    args = (qa, ka, va) if ma is None else (qa, ka, va, ma)
    return pure(*args)


def ring_attention(q, k, v, mask=None, axis="sp", causal=False, scale=None,
                   mesh=None):
    """Attention with K/V ring-rotated over the sp axis.

    q, k, v: [B, H, L, D] arrays (or Tensors) whose L dim is sharded over
    ``axis`` in the enclosing mesh; mask: additive [B, 1, 1, L] or
    [B, 1, Lq, Lk] (only the K-dim-sharded [B,1,1,L] form rotates).
    Falls back to plain attention when no mesh / axis size 1.
    """
    return _dispatch_sp_attention(
        "ring_attention",
        lambda scale: partial(_ring_body, axis=axis, scale=scale,
                              causal=causal),
        q, k, v, mask, axis, causal, scale, mesh,
    )
