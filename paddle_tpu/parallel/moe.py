"""Mixture-of-Experts with expert parallelism over the ep mesh axis.

Beyond-reference capability (SURVEY.md §2.3: EP/MoE absent in the
reference). GShard/Switch-style top-k routing implemented as dense
einsum dispatch/combine: expert weights carry a leading [num_experts]
axis sharded on ep, tokens are dispatched with a one-hot combine tensor,
and GSPMD lowers the dispatch einsums to all-to-alls over ICI.

The dense-dispatch formulation (einsum with a [G, S, E, C] combine tensor
instead of gather/scatter) is the canonical TPU design: static shapes,
MXU-friendly, no sorting kernels.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import ops
from ..framework import autograd
from ..framework.tensor import Parameter, Tensor
from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layers import Linear
from .mesh import get_mesh
from .sharding import ShardingRules, with_sharding_constraint

__all__ = ["MoELayer", "SwitchFFN"]


class SwitchFFN(Layer):
    """Top-1 (Switch) routed expert FFN.

    x: [B, L, H] -> [B, L, H]; E experts, each a 2-layer MLP with
    intermediate dim F. Expert params are [E, ...] leaves sharded on ep.
    """

    def __init__(self, hidden_size, intermediate_size, num_experts,
                 capacity_factor=1.25, activation="relu",
                 router_noise=1e-2):
        super().__init__()
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.router_noise = router_noise
        self.router = Linear(hidden_size, num_experts)
        # expert weights: [E, H, F], [E, F], [E, F, H], [E, H]
        bound1 = float(np.sqrt(6.0 / (hidden_size + intermediate_size)))
        from ..framework.random import split_key

        self.expert_w1 = Parameter.from_array(
            jax.random.uniform(
                split_key(), (num_experts, hidden_size, intermediate_size),
                jnp.float32, -bound1, bound1,
            ),
            name="expert_w1",
        )
        self.expert_b1 = Parameter.from_array(
            jnp.zeros((num_experts, intermediate_size)), name="expert_b1"
        )
        self.expert_w2 = Parameter.from_array(
            jax.random.uniform(
                split_key(), (num_experts, intermediate_size, hidden_size),
                jnp.float32, -bound1, bound1,
            ),
            name="expert_w2",
        )
        self.expert_b2 = Parameter.from_array(
            jnp.zeros((num_experts, hidden_size)), name="expert_b2"
        )
        self._last_aux_loss = None

    @staticmethod
    def sharding_rules():
        return ShardingRules([
            (r"expert_(w|b)\d$", P("ep")),
        ])

    def forward(self, x):
        logits = self.router(x)  # [B, L, E]
        fn = self._dispatch_fn()
        param_tensors = [self.expert_w1, self.expert_b1,
                         self.expert_w2, self.expert_b2]
        mesh = get_mesh()
        if mesh is not None and int(mesh.shape.get("ep", 1)) > 1:
            # eager edge: settle expert params onto the ep axis once; they
            # stay resident across calls
            from jax.sharding import NamedSharding

            for p in param_tensors:
                if not isinstance(p._array, jax.core.Tracer):
                    p._array = jax.device_put(
                        p._array, NamedSharding(mesh, P("ep"))
                    )

            def repl(t):
                if isinstance(t, Tensor) and not isinstance(
                    t._array, jax.core.Tracer
                ):
                    return Tensor._from_array(
                        jax.device_put(t._array, NamedSharding(mesh, P())),
                        stop_gradient=t.stop_gradient,
                    )
                return t

            x, logits = repl(x), repl(logits)
        out, aux = autograd.apply_op(
            "moe_switch_ffn", jax.jit(fn),
            [x, logits, *param_tensors],
            {},
        )
        self._last_aux_loss = aux
        return out

    def aux_loss(self):
        """Load-balancing auxiliary loss of the last forward (Switch
        Transformer eq. 4); add `model.moe.aux_loss()` to the train loss."""
        return self._last_aux_loss

    def _dispatch_fn(self):
        E = self.num_experts
        cap_f = self.capacity_factor
        act = getattr(jax.nn, self.activation)
        training = self.training
        noise = self.router_noise

        def pure(x, logits, w1, b1, w2, b2):
            b, l, h = x.shape
            s = b * l
            cap = max(1, int(cap_f * s / E))
            xt = x.reshape(s, h)
            lg = logits.reshape(s, E).astype(jnp.float32)
            # NOTE: router jitter (Switch §2.2) is intentionally omitted —
            # stateful RNG inside this pure fn would bake a constant under
            # jit; thread it via the train-step rng when needed.
            probs = jax.nn.softmax(lg, axis=-1)
            gate = jnp.max(probs, axis=-1)              # [S]
            expert = jnp.argmax(probs, axis=-1)         # [S]
            # position of each token within its expert's queue
            onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)   # [S, E]
            # rank within the chosen expert's queue: mask the cumsum to the
            # chosen column *before* the -1 (subtracting inside the sum
            # would shift by E, aliasing the first E tokens into slot 0)
            pos_in_expert = (
                jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=-1) - 1
            )  # [S]
            keep = pos_in_expert < cap
            gate = gate * keep

            # dispatch tensor [S, E, C]
            disp = (
                jax.nn.one_hot(expert, E, dtype=x.dtype)[:, :, None]
                * jax.nn.one_hot(
                    jnp.clip(pos_in_expert, 0, cap - 1), cap, dtype=x.dtype
                )[:, None, :]
                * keep[:, None, None]
            )
            # expert inputs [E, C, H]
            ex_in = jnp.einsum("sec,sh->ech", disp, xt)
            ex_in = with_sharding_constraint(ex_in, P("ep", None, None))
            hmid = act(
                jnp.einsum("ech,ehf->ecf", ex_in, w1) + b1[:, None, :]
            )
            ex_out = jnp.einsum("ecf,efh->ech", hmid, w2) + b2[:, None, :]
            ex_out = with_sharding_constraint(ex_out, P("ep", None, None))
            combine = disp * gate[:, None, None]        # [S, E, C]
            yt = jnp.einsum("sec,ech->sh", combine, ex_out)

            # load-balance aux loss: E * sum_e f_e * p_e
            frac_tokens = jnp.mean(
                jax.nn.one_hot(expert, E, dtype=jnp.float32), axis=0
            )
            frac_probs = jnp.mean(probs, axis=0)
            aux = E * jnp.sum(frac_tokens * frac_probs)
            return yt.reshape(b, l, h), aux

        return pure


MoELayer = SwitchFFN  # alias
