"""Parameter/activation sharding rules.

Reference parity: the role of multi_devices_graph_pass.cc (deciding, per
variable, where it lives and which collective moves it) — reimagined as
GSPMD sharding annotations: a rule table maps parameter names (regex) to
PartitionSpecs; XLA's partitioner then inserts the collectives the
reference inserted by graph rewriting.
"""
from __future__ import annotations

import re
from collections import OrderedDict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import get_mesh

__all__ = [
    "ShardingRules",
    "named_sharding",
    "shard_state",
    "shard_batch",
    "with_sharding_constraint",
    "zero1_shard_opt",
    "spec_to_wire",
    "spec_from_wire",
    "DEFAULT_RULES",
]


def spec_to_wire(spec: P) -> list:
    """PartitionSpec -> JSON-serializable form (checkpoint manifests).

    Each entry is None (unsharded dim), an axis name string, or a list of
    axis names (a dim sharded over multiple mesh axes). The wire form is
    mesh-independent: a checkpoint saved from a 4-way dp mesh re-slices
    onto a 2- or 8-way mesh by rebuilding the spec against the new mesh.
    """
    out = []
    for part in tuple(spec):
        if part is None:
            out.append(None)
        elif isinstance(part, (tuple, list)):
            out.append([str(p) for p in part])
        else:
            out.append(str(part))
    return out


def spec_from_wire(parts) -> P:
    """Inverse of :func:`spec_to_wire`."""
    rebuilt = []
    for part in parts or []:
        if part is None:
            rebuilt.append(None)
        elif isinstance(part, (tuple, list)):
            rebuilt.append(tuple(str(p) for p in part))
        else:
            rebuilt.append(str(part))
    return P(*rebuilt)


class ShardingRules:
    """Ordered (regex -> PartitionSpec) table; first match wins.

    Example (megatron TP over axis "tp"):
        rules = ShardingRules([
            (r".*\\.qkv_proj\\.weight$", P(None, "tp")),   # column parallel
            (r".*\\.out_proj\\.weight$", P("tp", None)),   # row parallel
            (r".*\\.embedding\\.weight$", P("tp", None)),  # vocab parallel
        ])
    Unmatched parameters are replicated (P()).
    """

    def __init__(self, rules=None, default=P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in (rules or [])]
        self.default = default

    def add(self, pattern, spec):
        self.rules.append((re.compile(pattern), spec))
        return self

    def spec_for(self, name: str) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                return spec
        return self.default

    def clamped_spec_for(self, name: str, ndim: int) -> P:
        """``spec_for`` trimmed to the array rank (rules written for the
        2D weight may match a 1D bias) — the public entry sharded
        serving uses to map loaded inference params onto the mesh."""
        return _clamp_spec(self.spec_for(name), ndim)

    def __add__(self, other: "ShardingRules") -> "ShardingRules":
        out = ShardingRules(default=other.default)
        out.rules = list(self.rules) + list(other.rules)
        return out


DEFAULT_RULES = ShardingRules()  # replicate everything (pure DP)


def _clamp_spec(spec: P, ndim: int) -> P:
    """Trim a PartitionSpec to the array rank (rules may be written for the
    2D weight but match a 1D bias)."""
    parts = tuple(spec)
    if len(parts) > ndim:
        parts = parts[:ndim]
    return P(*parts)


def named_sharding(spec: P, mesh: Mesh | None = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    if mesh is None:
        raise RuntimeError("no active mesh; use parallel.mesh_scope(...)")
    return NamedSharding(mesh, spec)


def shard_state(state, rules: ShardingRules | None = None, mesh: Mesh | None = None):
    """Produce the sharding pytree for a train-step state dict.

    params/frozen follow the rule table; buffers and optimizer accumulators
    inherit the sharding of their parameter (accumulator lists are aligned
    with the optimizer's parameter list order = model.parameters() order).
    Returns a pytree of NamedShardings shaped like ``state``.
    """
    mesh = mesh or get_mesh()
    rules = rules or DEFAULT_RULES

    def param_shardings(group):
        return OrderedDict(
            (
                name,
                NamedSharding(
                    mesh, _clamp_spec(rules.spec_for(name), arr.ndim)
                ),
            )
            for name, arr in group.items()
        )

    out = {
        "params": param_shardings(state["params"]),
        "frozen": param_shardings(state["frozen"]),
        "buffers": OrderedDict(
            (name, NamedSharding(mesh, P())) for name in state["buffers"]
        ),
    }
    if "opt" in state:
        # accumulators: per-param lists in params order; scalar-shaped
        # accumulators (e.g. beta powers) replicate.
        pshard = list(out["params"].values())
        pshapes = [a.shape for a in state["params"].values()]
        accums = {}
        for name, accs in state["opt"]["accums"].items():
            shards = []
            for arr, ps, pshape in zip(accs, pshard, pshapes):
                if tuple(arr.shape) == tuple(pshape):
                    spec = _clamp_spec(ps.spec, arr.ndim)
                else:  # shape-divergent accumulator (beta powers etc.)
                    spec = P()
                shards.append(NamedSharding(mesh, spec))
            accums[name] = shards
        out["opt"] = {
            "accums": accums,
            "step": NamedSharding(mesh, P()),
        }
    if "gm" in state:
        # gradient-merge accumulation buffers follow their parameter
        out["gm"] = {
            "acc": OrderedDict(
                (name, out["params"][name]) for name in state["gm"]["acc"]
            ),
            "count": NamedSharding(mesh, P()),
        }
    return out


def _zero1_spec(spec: P, shape, dp: int, axis="dp") -> P:
    """Extend ``spec`` to additionally shard the first divisible, still-
    unsharded dim over the dp axis (ZeRO-1 placement for an optimizer
    accumulator)."""
    parts = list(spec) + [None] * (len(shape) - len(tuple(spec)))
    if any(
        (axis == p) or (isinstance(p, tuple) and axis in p) for p in parts
    ):
        return spec  # already sharded over dp by the param rule
    for i, (p, d) in enumerate(zip(parts, shape)):
        if p is None and d % dp == 0 and d >= dp:
            parts[i] = axis
            return P(*parts)
    return spec  # no divisible dim: leave replicated


def zero1_shard_opt(shardings, state, mesh: Mesh | None = None, axis="dp"):
    """ZeRO stage-1: shard optimizer state over the data-parallel axis.

    The reference has no ZeRO (SURVEY.md §2.3 — `sharding` absent from
    distributed_strategy.proto); this implements the capability TPU-first:
    each accumulator that matches its parameter's shape gets an extra
    ``dp`` partition on its first divisible dim. Params/grads stay whole —
    XLA gathers shards where the update math needs them (the
    reduce-scatter/all-gather pair ZeRO implementations hand-write falls
    out of GSPMD).

    Mutates and returns the ``shardings`` pytree produced by shard_state.
    """
    mesh = mesh or get_mesh()
    dp = int(mesh.shape.get(axis, 1))
    if dp <= 1 or "opt" not in shardings:
        return shardings
    pshapes = [a.shape for a in state["params"].values()]
    for name, accs in shardings["opt"]["accums"].items():
        arrs = state["opt"]["accums"][name]
        new = []
        for sh, arr, pshape in zip(accs, arrs, pshapes):
            if tuple(arr.shape) == tuple(pshape):
                spec = _zero1_spec(sh.spec, arr.shape, dp, axis)
                new.append(NamedSharding(mesh, spec))
            else:
                new.append(sh)
        shardings["opt"]["accums"][name] = new
    return shardings


def shard_batch(batch, mesh: Mesh | None = None, axes=("dp",)):
    """NamedSharding for input batches: leading dim split over dp (and sp
    for sequence dim if requested as ("dp", "sp"))."""
    mesh = mesh or get_mesh()

    def one(arr):
        spec = [None] * arr.ndim
        if arr.ndim >= 1:
            spec[0] = axes[0]
        if len(axes) > 1 and arr.ndim >= 2:
            spec[1] = axes[1]
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map(one, batch)


def with_sharding_constraint(x, spec: P):
    """Annotate an activation's sharding. No-op without an active mesh.

    Traced values get a GSPMD constraint; concrete (eager) arrays are
    device_put onto the mesh instead — with_sharding_constraint is
    jit-only in JAX."""
    mesh = get_mesh()
    if mesh is None:
        return x
    from ..framework.tensor import Tensor

    def one(arr):
        if isinstance(arr, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(
                arr, NamedSharding(mesh, spec)
            )
        return jax.device_put(arr, NamedSharding(mesh, spec))

    if isinstance(x, Tensor):
        return Tensor._from_array(one(x._array), stop_gradient=x.stop_gradient)
    return one(x)
