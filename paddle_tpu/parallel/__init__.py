"""paddle_tpu.parallel — SPMD machinery over TPU meshes.

Replaces the reference's NCCL-ring world (platform/collective_helper.h:62
NCCLCommContext, framework/parallel_executor.cc ring init) with the
TPU-native model: a single logical `jax.sharding.Mesh` with named axes

    dp — data parallel           (batch dimension)
    pp — pipeline parallel       (layer stages)
    tp — tensor/model parallel   (hidden dimension, megatron-style)
    sp — sequence/context parallel (ring attention over ICI)
    ep — expert parallel         (MoE experts)

Collectives are mesh-axis reductions compiled by XLA onto ICI/DCN — there
are no comm streams, rings, or sync ops to manage (c_sync_calc_stream etc.
intentionally have no equivalent).
"""
from .mesh import (  # noqa: F401
    MeshConfig,
    create_mesh,
    get_mesh,
    set_mesh,
    mesh_scope,
    axis_size,
    in_mesh,
)
from .sharding import (  # noqa: F401
    ShardingRules,
    named_sharding,
    shard_state,
    shard_batch,
    with_sharding_constraint,
    zero1_shard_opt,
    DEFAULT_RULES,
)
from .train import (  # noqa: F401
    sharded_train_step,
    ShardedTrainStep,
    LocalSGDTrainStep,
    consume_strategy,
)
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .pipeline import GPipe, PipelineParallel, pipeline_schedule  # noqa: F401
from .moe import MoELayer, SwitchFFN  # noqa: F401
