"""Pipeline parallelism (GPipe schedule) over the pp mesh axis.

Reference parity: PipelineTrainer/SectionWorker
(paddle/fluid/framework/pipeline_trainer.cc:24, section_worker.cc:83 —
per-section ProgramDescs on separate devices, microbatch scopes flowing
through queues, Forward-all/Backward-all/Optimize GPipe schedule) and
fluid.optimizer.PipelineOptimizer (python/paddle/fluid/optimizer.py:4431).

TPU-native redesign: sections become one SPMD program. All pp ranks run
the same stage function on their own slice of a [n_stages, ...]-stacked
parameter pytree (sharded on pp); activations hop stages via
lax.ppermute over ICI each tick. The GPipe schedule is the classic
skewed loop: tick t runs microbatch (t - stage) on each stage. Backward
falls out of jax.grad through the ppermutes (reverse ring), and the
optimizer applies elementwise to the stacked params — so pipeline
composes with dp/tp/sp via GSPMD (`auto` axes) and with the standard
ShardedTrainStep.

SectionWorker's threads/queues/condition-vars have no equivalent: XLA
schedules the whole skewed loop.
"""
from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework import autograd
from ..framework import jit as fjit
from ..framework.tensor import Parameter, Tensor
from ..nn.layer_base import Layer
from .mesh import AXES, get_mesh, mesh_scope

__all__ = ["GPipe", "PipelineParallel", "pipeline_schedule"]


class GPipe(Layer):
    """Wrap N identical stage Layers into one pipeline-parallel Layer.

    The stages must share parameter structure (e.g. k transformer blocks
    each) and map activations shape-preservingly. Parameters are stored
    stacked on a leading [n_stages] axis; shard it on pp via
    ``GPipe.sharding_rules()``.
    """

    def __init__(self, stages, num_microbatches, axis="pp"):
        super().__init__()
        assert len(stages) >= 1
        self._stage0 = stages[0]
        self.n_stages = len(stages)
        self.n_micro = num_microbatches
        self.axis = axis
        # stack per-stage parameters: name -> [n_stages, *shape]
        states = [fjit.capture_state(s) for s in stages]
        names = list(states[0]["params"].keys())
        for st in states[1:]:
            assert list(st["params"].keys()) == names, (
                "pipeline stages must have identical parameter structure"
            )
        self._param_names = names
        for name in names:
            stacked = jnp.stack([st["params"][name] for st in states])
            self.add_parameter(
                _flat(name), Parameter.from_array(stacked, name=_flat(name))
            )
        # buffers (batchnorm running stats) stack on the same [n_stages]
        # leading axis and ride the pipeline as per-stage state: each stage
        # updates its own slice per microbatch tick, the final slices are
        # written back after the schedule (mirroring the reference's
        # per-section scopes carrying persistables, pipeline_trainer.cc:122)
        self._buffer_names = list(states[0]["buffers"].keys())
        for st in states[1:]:
            assert list(st["buffers"].keys()) == self._buffer_names, (
                "pipeline stages must have identical buffer structure"
            )
        for name in self._buffer_names:
            stackedb = jnp.stack([st["buffers"][name] for st in states])
            self.register_buffer(_bflat(name), Tensor._from_array(stackedb))

    def sharding_rules(self):
        """Rules shard the stacked leading axis over pp; within-stage dims
        can be composed with tp rules by the caller."""
        from .sharding import ShardingRules

        return ShardingRules(
            [(r"(^|\.)stacked__", P(self.axis))]
        )

    def forward(self, x, *extras):
        """``extras`` are broadcast inputs handed to every stage unchanged
        (e.g. an attention mask); only ``x`` flows through the pipeline."""
        mesh = get_mesh()
        param_tensors = [self._parameters[_flat(n)] for n in self._param_names]
        buf_tensors = [self._buffers[_bflat(n)] for n in self._buffer_names]
        if mesh is not None and int(mesh.shape.get(self.axis, 1)) > 1:
            # eager edge: settle operands onto the mesh once; params stay
            # resident in the pp-sharded layout across calls
            from jax.sharding import NamedSharding

            for p in (*param_tensors, *buf_tensors):
                if not isinstance(p._array, jax.core.Tracer):
                    p._array = jax.device_put(
                        p._array, NamedSharding(mesh, P(self.axis))
                    )

            def repl(t):
                if isinstance(t, Tensor) and not isinstance(
                    t._array, jax.core.Tracer
                ):
                    return Tensor._from_array(
                        jax.device_put(t._array, NamedSharding(mesh, P())),
                        stop_gradient=t.stop_gradient,
                    )
                return t

            x = repl(x)
            extras = tuple(repl(e) for e in extras)
        fn = partial(
            _gpipe_pure,
            stage0=self._stage0,
            names=self._param_names,
            buf_names=self._buffer_names,
            n_stages=self.n_stages,
            n_micro=self.n_micro,
            axis=self.axis,
            mesh=mesh,
            n_extras=len(extras),
        )
        # jit so the shard_map island always lowers under a trace (also
        # makes eager-mode vjp run compiled); inlines under an outer jit
        outs = autograd.apply_op(
            "gpipe_forward", jax.jit(fn),
            [*param_tensors, *buf_tensors, x, *extras], {},
        )
        if not self._buffer_names:
            return outs
        y, *new_bufs = outs
        if self.training:
            with autograd.no_grad():
                for n, nb in zip(self._buffer_names, new_bufs):
                    self._buffers[_bflat(n)].set_value(nb.detach())
        return y


def _flat(name):
    return "stacked__" + name.replace(".", "__")


def _bflat(name):
    return "stackedbuf__" + name.replace(".", "__")


def _gpipe_pure(*args, stage0, names, buf_names=(), n_stages, n_micro, axis,
                mesh, n_extras=0):
    """Pure fn: (stacked params..., stacked bufs..., x, extras...) ->
    y (+ updated stacked bufs) over the pp axis."""
    n_params = len(names)
    n_bufs = len(buf_names)
    stacked = dict(zip(names, args[:n_params]))
    bufs = dict(zip(buf_names, args[n_params:n_params + n_bufs]))
    x = args[n_params + n_bufs]
    extras = args[n_params + n_bufs + 1:]

    from collections import OrderedDict

    def stage_fn(local_params, local_bufs, act, *ex):
        state = {
            "params": local_params,
            "frozen": {},
            "buffers": OrderedDict(
                (n, local_bufs[n]) for n in buf_names
            ),
        }
        out, new_state = fjit.functional_call(stage0, state, act, *ex)
        return out, tuple(new_state["buffers"][n] for n in buf_names)

    if mesh is None or int(mesh.shape.get(axis, 1)) == 1:
        # no pp axis: run stages sequentially. When the model carries
        # stateful buffers (batchnorm running stats), iterate the SAME
        # n_micro microbatches as the pipelined path — via lax.scan with
        # the buffers as carry, so trace/compile cost stays constant in
        # n_micro — giving an identical buffer update trajectory (n_micro
        # momentum updates per step, each from microbatch statistics);
        # otherwise eval outputs diverge between single-device and
        # pipelined training of the same model. Buffer-free models keep
        # the plain full-batch pass (pointwise-per-sample ⇒ identical
        # outputs, cheaper).
        b = x.shape[0]
        if not (buf_names and n_micro > 1 and b % n_micro == 0):
            y = x
            per_stage_bufs = []
            for s in range(n_stages):
                y, nb = stage_fn(
                    {n: stacked[n][s] for n in names},
                    {n: bufs[n][s] for n in buf_names}, y, *extras,
                )
                per_stage_bufs.append(nb)
            if not buf_names:
                return y
            new_stacked = tuple(
                jnp.stack([per_stage_bufs[s][i] for s in range(n_stages)])
                for i in range(n_bufs)
            )
            return (y, *new_stacked)

        mb = b // n_micro
        x_mb = x.reshape((n_micro, mb) + x.shape[1:])
        per_sample = [e.ndim >= 1 and e.shape[0] == b for e in extras]
        scanned_ex = tuple(
            e.reshape((n_micro, mb) + e.shape[1:])
            for e, ps in zip(extras, per_sample) if ps
        )
        bcast_ex = tuple(e for e, ps in zip(extras, per_sample) if not ps)

        def body(carry, xs):
            xm = xs[0]
            it_s, it_b = iter(xs[1:]), iter(bcast_ex)
            ex = [next(it_s) if ps else next(it_b) for ps in per_sample]
            y = xm
            per_stage = []
            for s in range(n_stages):
                y, nb = stage_fn(
                    {n: stacked[n][s] for n in names},
                    {n: carry[n][s] for n in buf_names}, y, *ex,
                )
                per_stage.append(nb)
            new_carry = {
                n: jnp.stack([per_stage[s][i] for s in range(n_stages)])
                for i, n in enumerate(buf_names)
            }
            return new_carry, y

        final_bufs, y_mb = lax.scan(
            body, {n: bufs[n] for n in buf_names}, (x_mb, *scanned_ex)
        )
        y = y_mb.reshape((b,) + y_mb.shape[2:])
        return (y, *(final_bufs[n] for n in buf_names))

    b = x.shape[0]
    assert b % n_micro == 0, (b, n_micro)
    mb = b // n_micro
    x_mb = x.reshape((n_micro, mb) + x.shape[1:])
    # per-sample extras (leading dim == batch) are microbatched alongside
    # x; anything else broadcasts to all microbatches unchanged
    ex_kinds = tuple(
        e.ndim >= 1 and e.shape[0] == b for e in extras
    )
    extras = tuple(
        e.reshape((n_micro, mb) + e.shape[1:]) if per_sample else e
        for e, per_sample in zip(extras, ex_kinds)
    )

    # keep the stacked params/buffers pinned to the pp layout inside the
    # program
    from jax.sharding import NamedSharding

    stacked = {
        n: lax.with_sharding_constraint(
            stacked[n], NamedSharding(mesh, P(axis))
        )
        for n in names
    }
    bufs = {
        n: lax.with_sharding_constraint(
            bufs[n], NamedSharding(mesh, P(axis))
        )
        for n in buf_names
    }

    body = partial(
        _gpipe_body, stage_fn=stage_fn, names=names, buf_names=buf_names,
        n_stages=n_stages, n_micro=n_micro, axis=axis, ex_kinds=ex_kinds,
    )
    in_specs = (
        {n: P(axis) for n in names},
        {n: P(axis) for n in buf_names},
        P(),
        *([P()] * len(extras)),
    )
    out_specs = (P(), {n: P(axis) for n in buf_names})
    # partial-manual shard_map: only pp is manual; dp/tp/sp stay under
    # GSPMD (auto) so the pipeline composes with the other parallelisms
    sm = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        axis_names={axis}, check_vma=False,
    )
    # partial-manual shard_map only lowers under jit; jit inlines when
    # already inside an outer trace
    y_mb, new_bufs = jax.jit(sm)(stacked, bufs, x_mb, *extras)
    y = y_mb.reshape((b,) + y_mb.shape[2:])
    if not buf_names:
        return y
    return (y, *(new_bufs[n] for n in buf_names))


def pipeline_schedule(n_stages: int, n_micro: int, kind: str = "1f1b"):
    """Generate a topologically-valid dispatch order of pipeline events.

    Returns a list of ("F"|"B", stage, microbatch) tuples. Mirrors the
    role of SectionWorker's per-section op scheduling
    (framework/section_worker.cc:83 — Forward-all/Backward-all per
    op_role); "1f1b" additionally bounds live activations per stage to
    ~(n_stages - stage) the way later Paddle 1F1B schedules do.

    The order is a *dispatch* order for the single-controller runtime:
    device-level overlap comes from async dispatch, correctness from data
    dependencies, so only topological validity and memory shape matter.
    """
    S, M = n_stages, n_micro
    done_f = [[False] * M for _ in range(S)]
    done_b = [[False] * M for _ in range(S)]
    nf = [0] * S  # forwards dispatched per stage
    nb = [0] * S
    events = []

    def f_ready(s, m):
        if done_f[s][m]:
            return False
        return s == 0 or done_f[s - 1][m]

    def b_ready(s, m):
        if done_b[s][m]:
            return False
        if s == S - 1:
            return done_f[s][m]
        return done_b[s + 1][m]

    total = 2 * S * M
    while len(events) < total:
        progressed = False
        for s in range(S):
            f_next = nf[s] if nf[s] < M and f_ready(s, nf[s]) else None
            b_next = nb[s] if nb[s] < M and b_ready(s, nb[s]) else None
            if f_next is None and b_next is None:
                continue
            warm = min(S - s, M)
            prefer_b = (
                kind == "1f1b" and b_next is not None
                and (nf[s] - nb[s] >= warm or nf[s] >= M)
            ) or f_next is None
            if prefer_b:
                events.append(("B", s, b_next))
                done_b[s][b_next] = True
                nb[s] += 1
            else:
                events.append(("F", s, f_next))
                done_f[s][f_next] = True
                nf[s] += 1
            progressed = True
        assert progressed, "pipeline schedule deadlock"
    return events


class PipelineParallel:
    """Heterogeneous pipeline-parallel trainer over pp submeshes.

    Reference parity: PipelineTrainer + SectionWorker
    (framework/pipeline_trainer.cc:24 — arbitrary per-section
    ProgramDescs on distinct device groups, microbatch scopes flowing
    through queues) and PipelineOptimizer's per-device program split
    (python/paddle/fluid/optimizer.py:4431). Unlike GPipe above, stages
    may be *different* Layers (embedding-first, head-last), carry
    buffers, and change activation shape/pytree structure between
    stages.

    TPU-native single-controller MPMD: each stage's state lives on its
    own slice of the pp mesh axis (replicated/dp-sharded over the
    remaining axes); per-stage jitted programs run forward and
    recompute-based backward (GPipe-paper rematerialization — only
    stage-boundary activations are stored); the host dispatches events
    in GPipe or 1F1B order and the async JAX runtime overlaps stages on
    disjoint devices, replacing SectionWorker's threads+condition-vars.
    Cross-stage handoffs are device_put reshards over ICI (the scope
    queues of pipeline_trainer.cc:122).

    API::

        pp = PipelineParallel(
            [emb_stage, block_stage, block_stage2, head_stage],
            lambda params: opt.AdamW(1e-4, parameters=params),
            loss_fn,          # (last_stage_output, *labels) -> scalar
            num_microbatches=4, schedule="1f1b")
        metrics = pp.step(input_batch, *label_batches)
    """

    def __init__(self, stages, opt_factory, loss_fn, num_microbatches,
                 mesh=None, axis="pp", schedule="1f1b", rules=None):
        from collections import OrderedDict

        from jax.sharding import Mesh, NamedSharding

        mesh = mesh or get_mesh()
        if mesh is None:
            raise RuntimeError("PipelineParallel needs a mesh "
                               "(parallel.mesh_scope)")
        npp = int(mesh.shape.get(axis, 1))
        if len(stages) != npp:
            raise ValueError(
                f"{len(stages)} stages but mesh {axis}={npp}; one stage "
                f"per {axis} slice (split or merge your stages)"
            )
        self.mesh = mesh
        self.axis = axis
        self.stages = list(stages)
        self.S = len(stages)
        self.M = int(num_microbatches)
        self.loss_fn = loss_fn
        self.schedule = schedule
        self._events = pipeline_schedule(self.S, self.M, schedule)

        ax_pos = AXES.index(axis)
        sub_axes = tuple(a for a in AXES if a != axis)
        self.submeshes = []
        for i in range(self.S):
            devs = np.take(mesh.devices, i, axis=ax_pos)
            self.submeshes.append(Mesh(devs, sub_axes))

        # per-stage functional state, placed on the stage's submesh
        self.opts = []
        self.states = []
        self._fwd = []
        self._bwd = []
        self._apply = []
        for i, stage in enumerate(self.stages):
            stage.train()
            opt_i = opt_factory(stage.parameters())
            st = fjit.init_opt_state(stage, opt_i)
            if rules is not None:
                # tensor-parallel INSIDE each pipeline stage: the rule
                # table partitions stage params over the submesh's tp/ep
                # axes (pp × tp composition); unmatched params replicate
                from .sharding import shard_state

                shardings = shard_state(st, rules, self.submeshes[i])
                st = jax.tree_util.tree_map(
                    jax.device_put, st, shardings
                )
            else:
                repl = NamedSharding(self.submeshes[i], P())
                st = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, repl), st
                )
            self.opts.append(opt_i)
            self.states.append(st)
            is_last = i == self.S - 1
            is_first = i == 0

            def core(params, frozen, buffers, act, rng, _stage=stage):
                st2 = {
                    "params": params,
                    "frozen": frozen,
                    "buffers": OrderedDict(buffers),
                }
                out, new_st = fjit.functional_call(
                    _stage, st2, *_act_args(act), rng=rng
                )
                return out, new_st["buffers"]

            def call_loss(y, labels, _loss=loss_fn):
                wy = tuple(
                    Tensor._from_array(a) for a in _act_args(y)
                )
                wl = [Tensor._from_array(l) for l in labels]
                loss = _loss(*wy, *wl)
                return loss._array if isinstance(loss, Tensor) else loss

            if not is_last:

                def fwd(state, act, rng, _core=core):
                    y, nb = _core(
                        state["params"], state["frozen"], state["buffers"],
                        act, rng,
                    )
                    return y, nb

                def bwd(state, act, gy, rng, _core=core, _first=is_first):
                    frozen, buffers = state["frozen"], state["buffers"]

                    if _first:
                        # the raw input (int tokens / images) gets no
                        # cotangent: differentiate w.r.t. params only
                        def f0(p):
                            y, _ = _core(p, frozen, buffers, act, rng)
                            return y

                        _, vjp = jax.vjp(f0, state["params"])
                        (gp,) = vjp(gy)
                        return gp, ()

                    def f(p, a):
                        y, _ = _core(p, frozen, buffers, a, rng)
                        return y

                    _, vjp = jax.vjp(f, state["params"], act)
                    gp, gx = vjp(gy)
                    return gp, gx

            else:

                def fwd(state, act, labels, rng, _core=core,
                        _loss=call_loss):
                    y, nb = _core(
                        state["params"], state["frozen"], state["buffers"],
                        act, rng,
                    )
                    return _loss(y, labels), nb

                def bwd(state, act, labels, rng, _core=core,
                        _loss=call_loss, _first=is_first):
                    frozen, buffers = state["frozen"], state["buffers"]

                    if _first:  # S == 1: whole model on one slice
                        def f0(p):
                            y, nb = _core(p, frozen, buffers, act, rng)
                            return _loss(y, labels), nb

                        loss, vjp, nb = jax.vjp(f0, state["params"],
                                                has_aux=True)
                        (gp,) = vjp(jnp.ones_like(loss))
                        return loss, nb, gp, ()

                    def f(p, a):
                        y, nb = _core(p, frozen, buffers, a, rng)
                        return _loss(y, labels), nb

                    loss, vjp, nb = jax.vjp(f, state["params"], act,
                                            has_aux=True)
                    gp, gx = vjp(jnp.ones_like(loss))
                    return loss, nb, gp, gx

            self._fwd.append(jax.jit(fwd))
            self._bwd.append(jax.jit(bwd))

            def apply_fn(state, grads, lr, _stage=stage, _opt=opt_i):
                new_params, new_opt = fjit._apply_optimizer(
                    _stage, _opt, state, grads, lr
                )
                return new_params, new_opt

            self._apply.append(jax.jit(apply_fn))

        self._rng = default_generator_key()

    # -- data movement ------------------------------------------------------
    def _place(self, tree, stage_idx, batch_spec=True):
        """Put an activation pytree onto a stage's submesh (dp-sharded
        batch dim). The cross-stage reshard — the scope-queue handoff of
        pipeline_trainer.cc:122 — rides ICI."""
        from jax.sharding import NamedSharding

        sub = self.submeshes[stage_idx]

        def one(a):
            spec = P("dp") if (batch_spec and a.ndim >= 1) else P()
            return jax.device_put(a, NamedSharding(sub, spec))

        return jax.tree_util.tree_map(one, tree)

    # -- the step -----------------------------------------------------------
    def step(self, x, *labels):
        """One pipelined optimizer step over num_microbatches."""
        import jax.random as jrandom

        S, M = self.S, self.M

        def to_arr(t):
            return t._array if isinstance(t, Tensor) else jnp.asarray(t)

        x = jax.tree_util.tree_map(
            to_arr, x, is_leaf=lambda t: isinstance(t, Tensor)
        )
        labels = [
            l._array if isinstance(l, Tensor) else jnp.asarray(l)
            for l in labels
        ]
        b = jax.tree_util.tree_leaves(x)[0].shape[0]
        assert b % M == 0, (b, M)
        mb = b // M
        x_mb = [
            jax.tree_util.tree_map(lambda a: a[m * mb:(m + 1) * mb], x)
            for m in range(M)
        ]
        lab_mb = [
            [l[m * mb:(m + 1) * mb] for l in labels] for m in range(M)
        ]

        self._rng, base = jrandom.split(self._rng)
        keys = [
            [jrandom.fold_in(base, s * M + m) for m in range(M)]
            for s in range(S)
        ]

        acts = [dict() for _ in range(S)]   # (stage) -> {m: input act}
        for m in range(M):
            acts[0][m] = self._place(x_mb[m], 0)
        labs = [self._place(lab_mb[m], S - 1) for m in range(M)]
        gys = [dict() for _ in range(S)]    # upstream grads per stage
        gacc = [None] * S
        losses = []

        for ev, s, m in self._events:
            st = self.states[s]
            # stage programs trace under their own submesh so in-model
            # sharding constraints (P("dp", "sp", ...)) resolve against
            # the stage's devices, not the global mesh
            with mesh_scope(self.submeshes[s]):
                if ev == "F":
                    if s == S - 1:
                        # loss+buffers come out of the backward recompute;
                        # the forward event is pure bookkeeping on the
                        # last stage (avoids a third pass)
                        continue
                    y, nb = self._fwd[s](st, acts[s][m], keys[s][m])
                    self.states[s] = {**st, "buffers": nb}
                else:  # backward
                    if s == S - 1:
                        loss, nb, gp, gx = self._bwd[s](
                            st, acts[s][m], labs[m], keys[s][m]
                        )
                        self.states[s] = {**st, "buffers": nb}
                        losses.append(loss)
                    else:
                        gp, gx = self._bwd[s](
                            st, acts[s][m], gys[s].pop(m), keys[s][m]
                        )
            if ev == "F":
                acts[s + 1][m] = self._place(y, s + 1)
            else:
                del acts[s][m]  # activation memory freed (1F1B bound)
                if s > 0:
                    gys[s - 1][m] = self._place(gx, s - 1)
                gacc[s] = gp if gacc[s] is None else jax.tree_util.tree_map(
                    jnp.add, gacc[s], gp
                )

        # optimizer: mean of microbatch grads == grad of the mean loss
        lr = jnp.asarray(self.opts[0].get_lr(), jnp.float32)
        for s in range(S):
            grads = jax.tree_util.tree_map(lambda g: g / M, gacc[s])
            new_params, new_opt = self._apply[s](self.states[s], grads, lr)
            self.states[s] = {
                **self.states[s], "params": new_params, "opt": new_opt,
            }
        loss = jnp.mean(jnp.stack([jnp.asarray(l) for l in losses]))
        return {"loss": loss}

    __call__ = step

    def sync(self):
        """Write device state back into the eager stage Layers."""
        for stage, st, opt_i in zip(self.stages, self.states, self.opts):
            host = jax.tree_util.tree_map(
                lambda a: jnp.asarray(np.asarray(a)), st
            )
            fjit.restore_state(stage, host, opt_i)
        return self


def _act_args(act):
    """An activation pytree becomes the stage's positional args: a bare
    array is one arg; a tuple/list is splatted."""
    if isinstance(act, (tuple, list)):
        return tuple(act)
    return (act,)


def default_generator_key():
    from ..framework.random import default_generator

    return default_generator().split()


def _gpipe_body(stacked, bufs, x_mb, *extras, stage_fn, names, buf_names=(),
                n_stages, n_micro, axis, ex_kinds=()):
    """Runs per-stage under shard_map. stacked leaves: [1, *shape] local."""
    local = {n: stacked[n][0] for n in names}
    local_b = {n: bufs[n][0] for n in buf_names}
    stage = lax.axis_index(axis)
    n = n_stages

    act_shape = x_mb.shape[1:]
    recv = jnp.zeros(act_shape, x_mb.dtype)
    out = jnp.zeros((n_micro,) + act_shape, x_mb.dtype)

    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    for t in range(n_micro + n_stages - 1):
        # stage 0 injects microbatch t (if any); others take the handoff
        mb_idx = min(t, n_micro - 1)
        inject = x_mb[mb_idx]
        cur = jnp.where(stage == 0, inject, recv)
        run = jnp.logical_and(t - stage >= 0, t - stage < n_micro)
        # NOTE(GPipe skew): per-sample extras must follow the activation's
        # microbatch index *per stage* — stage s at tick t works on
        # microbatch t-s. A replicated extra is fine; a per-sample one is
        # only exact when every stage sees its own slice, so we select by
        # the stage-local microbatch index.
        local_mb = jnp.clip(t - stage, 0, n_micro - 1)
        cur_extras = tuple(
            (lax.dynamic_index_in_dim(e, local_mb, keepdims=False)
             if per_sample else e)
            for e, per_sample in zip(extras, ex_kinds)
        )
        y, new_b = stage_fn(local, local_b, cur, *cur_extras)
        # buffer updates (bn stats) only commit on ticks where this stage
        # actually processed a microbatch
        local_b = {
            n: jnp.where(run, nb, local_b[n])
            for n, nb in zip(buf_names, new_b)
        }
        # keep activations defined on idle stages (they compute garbage
        # that is masked out here; XLA's schedule overlaps it with comms)
        y = jnp.where(run, y, jnp.zeros_like(y))
        # last stage collects microbatch t-(n-1)
        oidx = t - (n_stages - 1)
        if oidx >= 0:
            collected = jnp.where(stage == n - 1, y, jnp.zeros_like(y))
            out = out.at[oidx].set(collected)
        recv = lax.ppermute(y, axis, fwd_perm)

    # outputs live on the last stage only; broadcast via psum
    return lax.psum(out, axis), {n: local_b[n][None] for n in buf_names}
