"""Pipeline parallelism (GPipe schedule) over the pp mesh axis.

Reference parity: PipelineTrainer/SectionWorker
(paddle/fluid/framework/pipeline_trainer.cc:24, section_worker.cc:83 —
per-section ProgramDescs on separate devices, microbatch scopes flowing
through queues, Forward-all/Backward-all/Optimize GPipe schedule) and
fluid.optimizer.PipelineOptimizer (python/paddle/fluid/optimizer.py:4431).

TPU-native redesign: sections become one SPMD program. All pp ranks run
the same stage function on their own slice of a [n_stages, ...]-stacked
parameter pytree (sharded on pp); activations hop stages via
lax.ppermute over ICI each tick. The GPipe schedule is the classic
skewed loop: tick t runs microbatch (t - stage) on each stage. Backward
falls out of jax.grad through the ppermutes (reverse ring), and the
optimizer applies elementwise to the stacked params — so pipeline
composes with dp/tp/sp via GSPMD (`auto` axes) and with the standard
ShardedTrainStep.

SectionWorker's threads/queues/condition-vars have no equivalent: XLA
schedules the whole skewed loop.
"""
from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework import autograd
from ..framework import jit as fjit
from ..framework.tensor import Parameter, Tensor
from ..nn.layer_base import Layer
from .mesh import AXES, get_mesh

__all__ = ["GPipe"]


class GPipe(Layer):
    """Wrap N identical stage Layers into one pipeline-parallel Layer.

    The stages must share parameter structure (e.g. k transformer blocks
    each) and map activations shape-preservingly. Parameters are stored
    stacked on a leading [n_stages] axis; shard it on pp via
    ``GPipe.sharding_rules()``.
    """

    def __init__(self, stages, num_microbatches, axis="pp"):
        super().__init__()
        assert len(stages) >= 1
        self._stage0 = stages[0]
        self.n_stages = len(stages)
        self.n_micro = num_microbatches
        self.axis = axis
        # stack per-stage parameters: name -> [n_stages, *shape]
        states = [fjit.capture_state(s) for s in stages]
        names = list(states[0]["params"].keys())
        for st in states[1:]:
            assert list(st["params"].keys()) == names, (
                "pipeline stages must have identical parameter structure"
            )
        self._param_names = names
        for name in names:
            stacked = jnp.stack([st["params"][name] for st in states])
            self.add_parameter(
                _flat(name), Parameter.from_array(stacked, name=_flat(name))
            )
        if states[0]["buffers"]:
            raise NotImplementedError(
                "pipeline stages with buffers (batchnorm) are unsupported; "
                "use buffer-free blocks (layernorm)"
            )

    def sharding_rules(self):
        """Rules shard the stacked leading axis over pp; within-stage dims
        can be composed with tp rules by the caller."""
        from .sharding import ShardingRules

        return ShardingRules(
            [(r"(^|\.)stacked__", P(self.axis))]
        )

    def forward(self, x, *extras):
        """``extras`` are broadcast inputs handed to every stage unchanged
        (e.g. an attention mask); only ``x`` flows through the pipeline."""
        mesh = get_mesh()
        param_tensors = [self._parameters[_flat(n)] for n in self._param_names]
        if mesh is not None and int(mesh.shape.get(self.axis, 1)) > 1:
            # eager edge: settle operands onto the mesh once; params stay
            # resident in the pp-sharded layout across calls
            from jax.sharding import NamedSharding

            for p in param_tensors:
                if not isinstance(p._array, jax.core.Tracer):
                    p._array = jax.device_put(
                        p._array, NamedSharding(mesh, P(self.axis))
                    )

            def repl(t):
                if isinstance(t, Tensor) and not isinstance(
                    t._array, jax.core.Tracer
                ):
                    return Tensor._from_array(
                        jax.device_put(t._array, NamedSharding(mesh, P())),
                        stop_gradient=t.stop_gradient,
                    )
                return t

            x = repl(x)
            extras = tuple(repl(e) for e in extras)
        fn = partial(
            _gpipe_pure,
            stage0=self._stage0,
            names=self._param_names,
            n_stages=self.n_stages,
            n_micro=self.n_micro,
            axis=self.axis,
            mesh=mesh,
            n_extras=len(extras),
        )
        # jit so the shard_map island always lowers under a trace (also
        # makes eager-mode vjp run compiled); inlines under an outer jit
        return autograd.apply_op(
            "gpipe_forward", jax.jit(fn), [*param_tensors, x, *extras], {}
        )


def _flat(name):
    return "stacked__" + name.replace(".", "__")


def _gpipe_pure(*args, stage0, names, n_stages, n_micro, axis, mesh,
                n_extras=0):
    """Pure fn: (stacked params..., x, extras...) -> y over the pp axis."""
    n_params = len(names)
    stacked = dict(zip(names, args[:n_params]))
    x = args[n_params]
    extras = args[n_params + 1 :]

    def stage_fn(local_params, act, *ex):
        state = {
            "params": local_params,
            "frozen": {},
            "buffers": {},
        }
        out, _ = fjit.functional_call(stage0, state, act, *ex)
        return out

    if mesh is None or int(mesh.shape.get(axis, 1)) == 1:
        # no pp axis: run stages sequentially (single-device semantics)
        y = x
        for s in range(n_stages):
            y = stage_fn({n: stacked[n][s] for n in names}, y, *extras)
        return y

    b = x.shape[0]
    assert b % n_micro == 0, (b, n_micro)
    mb = b // n_micro
    x_mb = x.reshape((n_micro, mb) + x.shape[1:])
    # per-sample extras (leading dim == batch) are microbatched alongside
    # x; anything else broadcasts to all microbatches unchanged
    ex_kinds = tuple(
        e.ndim >= 1 and e.shape[0] == b for e in extras
    )
    extras = tuple(
        e.reshape((n_micro, mb) + e.shape[1:]) if per_sample else e
        for e, per_sample in zip(extras, ex_kinds)
    )

    # keep the stacked params pinned to the pp layout inside the program
    from jax.sharding import NamedSharding

    stacked = {
        n: lax.with_sharding_constraint(
            stacked[n], NamedSharding(mesh, P(axis))
        )
        for n in names
    }

    body = partial(
        _gpipe_body, stage_fn=stage_fn, names=names,
        n_stages=n_stages, n_micro=n_micro, axis=axis, ex_kinds=ex_kinds,
    )
    in_specs = (
        {n: P(axis) for n in names},
        P(),
        *([P()] * len(extras)),
    )
    # partial-manual shard_map: only pp is manual; dp/tp/sp stay under
    # GSPMD (auto) so the pipeline composes with the other parallelisms
    sm = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=P(),
        axis_names={axis}, check_vma=False,
    )
    # partial-manual shard_map only lowers under jit; jit inlines when
    # already inside an outer trace
    y_mb = jax.jit(sm)(stacked, x_mb, *extras)
    return y_mb.reshape((b,) + y_mb.shape[2:])


def _gpipe_body(stacked, x_mb, *extras, stage_fn, names, n_stages, n_micro,
                axis, ex_kinds=()):
    """Runs per-stage under shard_map. stacked leaves: [1, *shape] local."""
    local = {n: stacked[n][0] for n in names}
    stage = lax.axis_index(axis)
    n = n_stages

    act_shape = x_mb.shape[1:]
    recv = jnp.zeros(act_shape, x_mb.dtype)
    out = jnp.zeros((n_micro,) + act_shape, x_mb.dtype)

    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    for t in range(n_micro + n_stages - 1):
        # stage 0 injects microbatch t (if any); others take the handoff
        mb_idx = min(t, n_micro - 1)
        inject = x_mb[mb_idx]
        cur = jnp.where(stage == 0, inject, recv)
        run = jnp.logical_and(t - stage >= 0, t - stage < n_micro)
        # NOTE(GPipe skew): per-sample extras must follow the activation's
        # microbatch index *per stage* — stage s at tick t works on
        # microbatch t-s. A replicated extra is fine; a per-sample one is
        # only exact when every stage sees its own slice, so we select by
        # the stage-local microbatch index.
        local_mb = jnp.clip(t - stage, 0, n_micro - 1)
        cur_extras = tuple(
            (lax.dynamic_index_in_dim(e, local_mb, keepdims=False)
             if per_sample else e)
            for e, per_sample in zip(extras, ex_kinds)
        )
        y = stage_fn(local, cur, *cur_extras)
        # keep activations defined on idle stages (they compute garbage
        # that is masked out here; XLA's schedule overlaps it with comms)
        y = jnp.where(run, y, jnp.zeros_like(y))
        # last stage collects microbatch t-(n-1)
        oidx = t - (n_stages - 1)
        if oidx >= 0:
            collected = jnp.where(stage == n - 1, y, jnp.zeros_like(y))
            out = out.at[oidx].set(collected)
        recv = lax.ppermute(y, axis, fwd_perm)

    # outputs live on the last stage only; broadcast via psum
    return lax.psum(out, axis)
