"""Sharded (multi-device) train steps.

Reference parity: CompiledProgram.with_data_parallel + ParallelExecutor
(python/paddle/fluid/compiler.py:160, framework/parallel_executor.cc) —
replicate the step across devices and keep gradients in sync. TPU-native:
the functionalized step (framework/jit.py) is pjit-compiled with
NamedShardings; XLA/GSPMD inserts the all-reduces the reference's
multi_devices_graph_pass inserted by hand, fuses them (fuse_all_reduce_op
pass ≙ XLA collective combining), and overlaps them with compute.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework import jit as fjit
from ..framework.random import default_generator
from ..framework.tensor import Tensor
from .mesh import mesh_scope
from .sharding import DEFAULT_RULES, shard_batch, shard_state

__all__ = ["sharded_train_step", "ShardedTrainStep"]


class ShardedTrainStep(fjit.TrainStepFn):
    """TrainStepFn partitioned over a device mesh.

    The loss gradient is averaged over the dp axis implicitly: the batch is
    sharded on dp, the loss is a global mean, so d(loss)/d(params) *is* the
    dp-mean — the allreduce the reference inserts per-gradient
    (framework/details/all_reduce_op_handle.cc) falls out of GSPMD.
    """

    def __init__(self, model, optimizer, loss_fn, mesh, rules=None,
                 batch_axes=("dp",), donate=True):
        self.mesh = mesh
        self.rules = rules or DEFAULT_RULES
        self.batch_axes = batch_axes
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        with mesh_scope(mesh):
            self.state = fjit.init_opt_state(model, optimizer)
            self.state_shardings = shard_state(self.state, self.rules, mesh)
            # place initial state according to the shardings
            self.state = jax.tree_util.tree_map(
                lambda a, s: jax.device_put(a, s),
                self.state,
                self.state_shardings,
            )
            self.pure = self._build_pure()
            self.compiled = jax.jit(
                self.pure,
                in_shardings=(
                    self.state_shardings,
                    None,  # batch shardings applied via device_put
                    NamedSharding(mesh, P()),
                    NamedSharding(mesh, P()),
                ),
                out_shardings=(self.state_shardings, None),
                donate_argnums=(0,) if donate else (),
            )
        self._rng = default_generator().split()

    def __call__(self, *batch):
        arrs = tuple(
            b._array if isinstance(b, Tensor) else jnp.asarray(b) for b in batch
        )
        with mesh_scope(self.mesh):
            shardings = shard_batch(arrs, self.mesh, self.batch_axes)
            arrs = jax.tree_util.tree_map(jax.device_put, arrs, shardings)
            lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
            self._rng, sub = jax.random.split(self._rng)
            self.state, metrics = self.compiled(self.state, arrs, lr, sub)
        return metrics


    def sync(self, gather=True):
        """Write device state back into the eager objects.

        gather=True (default) materializes host-local copies so the eager
        model is usable on any backend afterwards (paddle semantics:
        state_dict/save/eval after training); gather=False keeps the
        mesh-sharded layout (fast path when the state will only feed
        another sharded step).
        """
        state = self.state
        if gather:
            import numpy as np

            state = jax.tree_util.tree_map(
                lambda a: jnp.asarray(np.asarray(a)), state
            )
        else:
            # copy: restore_state aliases arrays into the live objects and
            # the next step() donates self.state
            state = jax.tree_util.tree_map(jnp.copy, state)
        fjit.restore_state(self.model, state, self.optimizer)
        return self


def sharded_train_step(model, optimizer, loss_fn, mesh, rules=None,
                       batch_axes=("dp",), donate=True):
    return ShardedTrainStep(
        model, optimizer, loss_fn, mesh, rules=rules,
        batch_axes=batch_axes, donate=donate,
    )
