"""Sharded (multi-device) train steps.

Reference parity: CompiledProgram.with_data_parallel + ParallelExecutor
(python/paddle/fluid/compiler.py:160, framework/parallel_executor.cc) —
replicate the step across devices and keep gradients in sync. TPU-native:
the functionalized step (framework/jit.py) is pjit-compiled with
NamedShardings; XLA/GSPMD inserts the all-reduces the reference's
multi_devices_graph_pass inserted by hand, fuses them (fuse_all_reduce_op
pass ≙ XLA collective combining), and overlaps them with compute.

DistributedStrategy consumption (fleet meta-optimizer parity — the
reference composes program-rewriting meta-optimizers via
base/strategy_compiler.py; here the strategy configures the step builder):
  recompute       → jax.checkpoint over the forward
                    (fluid/optimizer.py:4685 RecomputeOptimizer)
  gradient_merge  → k-step grad accumulation inside the compiled step
                    (meta_optimizers/gradient_merge_optimizer.py)
  sharding        → ZeRO-1 optimizer-state sharding over dp
                    (capability absent in the reference; TPU-first design)
  localsgd        → per-device divergent replicas + periodic param
                    averaging (meta_optimizers/localsgd_optimizer.py)
  amp             → bf16 autocast around the loss fn
  dgc / a_sync    → not implementable on this runtime: loud error, never a
                    silent no-op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework import jit as fjit
from ..framework.random import default_generator
from ..framework.tensor import Tensor
from ..monitor import registry as _mon
from ..profiler import RecordEvent
from .mesh import mesh_scope
from .sharding import DEFAULT_RULES, shard_batch, shard_state, zero1_shard_opt

__all__ = [
    "sharded_train_step",
    "ShardedTrainStep",
    "LocalSGDTrainStep",
    "consume_strategy",
]


def consume_strategy(strategy):
    """Translate a fleet DistributedStrategy into step-builder options.

    Every accepted flag either maps to a real behavior or raises — the
    reference's StrategyCompiler selects meta-optimizers the same way
    (base/strategy_compiler.py); silently ignoring a flag is never allowed.
    """
    if strategy is None:
        return {}
    if getattr(strategy, "dgc", False):
        raise NotImplementedError(
            "DistributedStrategy.dgc: deep gradient compression is a "
            "NCCL-ring bandwidth optimization (reference "
            "details/sparse_all_reduce_op_handle.cc); on TPU the gradient "
            "all-reduce rides ICI inside the XLA program and cannot be "
            "sparsified post-hoc. Use gradient_merge or localsgd to cut "
            "communication instead."
        )
    if getattr(strategy, "a_sync", False):
        # parameter-server mode (distributed/ps): trainers run
        # independent dense steps (no dp collective), sparse tables sync
        # through the table servers via PSEmbedding/GeoPSEmbedding.
        # k_steps > 0 in a_sync_configs selects geo mode — the reference's
        # sync/async/geo triple (distribute_transpiler.py:256,
        # geo_sgd_transpiler.py).
        conflicting = [
            f for f in ("recompute", "amp", "sharding", "localsgd",
                        "gradient_merge", "pipeline", "lars", "lamb")
            if getattr(strategy, f, False)
        ]
        if conflicting:
            raise NotImplementedError(
                f"DistributedStrategy.a_sync cannot combine with "
                f"{conflicting}: parameter-server trainers run plain "
                "local dense steps (the reference's PS path has the same "
                "separation from the collective meta-optimizers)"
            )
        cfg = getattr(strategy, "a_sync_configs", None)
        # the reference documents both the attr form and plain dict
        # assignment (strategy.a_sync_configs = {"k_steps": N})
        k = (cfg.get("k_steps", 0) if isinstance(cfg, dict)
             else getattr(cfg, "k_steps", 0))
        return {
            "a_sync": True,
            "geo_k_steps": int(k or 0),
            "recompute": False, "amp": False, "grad_accum_steps": 1,
            "grad_accum_avg": True, "zero1": False, "localsgd": False,
            "localsgd_k": 1, "rules": None,
        }
    if getattr(strategy, "pipeline", False):
        raise NotImplementedError(
            "DistributedStrategy.pipeline cannot split an arbitrary eager "
            "model automatically; build the stages explicitly with "
            "parallel.GPipe over a mesh with pp_degree > 1 "
            "(parallel/pipeline.py)."
        )
    opts = {
        "recompute": bool(getattr(strategy, "recompute", False)),
        "amp": bool(getattr(strategy, "amp", False)),
        "grad_accum_steps": 1,
        "grad_accum_avg": True,
        "zero1": bool(getattr(strategy, "sharding", False)),
        "localsgd": bool(getattr(strategy, "localsgd", False)),
        "localsgd_k": 1,
        "rules": getattr(strategy, "sharding_rules", None),
    }
    if getattr(strategy, "gradient_merge", False):
        cfg = strategy.gradient_merge_configs
        opts["grad_accum_steps"] = int(cfg.k_steps)
        opts["grad_accum_avg"] = bool(cfg.avg)
    if opts["localsgd"]:
        opts["localsgd_k"] = int(strategy.localsgd_configs.k_steps)
        if opts["grad_accum_steps"] > 1 or opts["zero1"]:
            raise NotImplementedError(
                "localsgd cannot be combined with gradient_merge/sharding "
                "(params diverge per-replica; there is no global optimizer "
                "state to shard)"
            )
    return opts


def _amp_wrap(loss_fn, strategy):
    """Wrap a loss fn in bf16 autocast per strategy.amp_configs."""
    cfg = getattr(strategy, "amp_configs", None)
    white = list(getattr(cfg, "custom_white_list", []) or [])
    black = list(getattr(cfg, "custom_black_list", []) or [])

    def wrapped(model, *batch):
        from .. import amp as amp_mod

        with amp_mod.auto_cast(
            custom_white_list=white or None,
            custom_black_list=black or None,
        ):
            return loss_fn(model, *batch)

    return wrapped


class ShardedTrainStep(fjit.TrainStepFn):
    """TrainStepFn partitioned over a device mesh.

    The loss gradient is averaged over the dp axis implicitly: the batch is
    sharded on dp, the loss is a global mean, so d(loss)/d(params) *is* the
    dp-mean — the allreduce the reference inserts per-gradient
    (framework/details/all_reduce_op_handle.cc) falls out of GSPMD.
    """

    def __init__(self, model, optimizer, loss_fn, mesh, rules=None,
                 batch_axes=("dp",), donate=True, strategy=None,
                 recompute=False, grad_accum_steps=1, grad_accum_avg=True,
                 zero1=False):
        opts = consume_strategy(strategy)
        if opts:
            recompute = recompute or opts["recompute"]
            if opts["grad_accum_steps"] > 1:
                grad_accum_steps = opts["grad_accum_steps"]
                grad_accum_avg = opts["grad_accum_avg"]
            zero1 = zero1 or opts["zero1"]
            rules = rules or opts["rules"]
            if opts["amp"]:
                loss_fn = _amp_wrap(loss_fn, strategy)
        self.mesh = mesh
        self.rules = rules or DEFAULT_RULES
        self.batch_axes = batch_axes
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.recompute = bool(recompute)
        self.grad_accum_steps = int(grad_accum_steps)
        self.grad_accum_avg = bool(grad_accum_avg)
        self.zero1 = bool(zero1)
        with mesh_scope(mesh):
            self.state = fjit.init_opt_state(model, optimizer)
            if self.grad_accum_steps > 1:
                from collections import OrderedDict

                self.state["gm"] = {
                    "acc": OrderedDict(
                        (n, jnp.zeros_like(a))
                        for n, a in self.state["params"].items()
                    ),
                    "count": jnp.asarray(0, jnp.int32),
                }
            self.state_shardings = shard_state(self.state, self.rules, mesh)
            if self.zero1:
                zero1_shard_opt(self.state_shardings, self.state, mesh)
            # place initial state according to the shardings
            self.state = jax.tree_util.tree_map(
                lambda a, s: jax.device_put(a, s),
                self.state,
                self.state_shardings,
            )
            self.pure = self._build_pure()
            self.compiled = jax.jit(
                self.pure,
                in_shardings=(
                    self.state_shardings,
                    None,  # batch shardings applied via device_put
                    NamedSharding(mesh, P()),
                    NamedSharding(mesh, P()),
                ),
                out_shardings=(self.state_shardings, None),
                donate_argnums=(0,) if donate else (),
            )
        self._rng = default_generator().split()

    def __call__(self, *batch):
        with RecordEvent("train::step"), mesh_scope(self.mesh):
            with RecordEvent("train::shard_batch"):  # H2D + layout
                arrs = tuple(
                    b._array if isinstance(b, Tensor) else jnp.asarray(b)
                    for b in batch
                )
                shardings = shard_batch(arrs, self.mesh, self.batch_axes)
                arrs = jax.tree_util.tree_map(
                    jax.device_put, arrs, shardings)
            lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
            self._rng, sub = jax.random.split(self._rng)
            with RecordEvent("train::step_dispatch"):
                self.state, metrics = self.compiled(
                    self.state, arrs, lr, sub)
            _mon.counter("train/sharded_steps").inc()
        return metrics


    def save_checkpoint(self, path, step=None, async_=None, keep=None,
                        peer_timeout_s=None):
        """Snapshot the device state (per-shard, with PartitionSpec
        metadata) — see distributed/checkpoint.py. Async by default
        (``FLAGS_checkpoint_async``): the step loop pays one device-side
        copy; serialize/fsync/publish run on the writer thread."""
        from ..distributed import checkpoint as _ckpt

        return _ckpt.save_train_step(self, path, step=step, async_=async_,
                                     keep=keep,
                                     peer_timeout_s=peer_timeout_s)

    def load_checkpoint(self, path):
        """Restore a snapshot, re-slicing every leaf (including ZeRO-1
        optimizer shards) onto THIS step's mesh — which may be a
        different world size than the save. Returns the manifest."""
        from ..distributed import checkpoint as _ckpt

        return _ckpt.restore_train_step(self, path)

    def sync(self, gather=True):
        """Write device state back into the eager objects.

        gather=True (default) materializes host-local copies so the eager
        model is usable on any backend afterwards (paddle semantics:
        state_dict/save/eval after training); gather=False keeps the
        mesh-sharded layout (fast path when the state will only feed
        another sharded step).
        """
        state = self.state
        if gather:
            import numpy as np

            state = jax.tree_util.tree_map(
                lambda a: jnp.asarray(np.asarray(a)), state
            )
        else:
            # copy: restore_state aliases arrays into the live objects and
            # the next step() donates self.state
            state = jax.tree_util.tree_map(jnp.copy, state)
        fjit.restore_state(self.model, state, self.optimizer)
        return self


class LocalSGDTrainStep:
    """LocalSGD over the dp mesh axis (meta_optimizers/localsgd_optimizer.py).

    Each dp replica holds its own divergent copy of params + optimizer
    state (stacked on a leading axis, sharded P("dp")) and trains on its
    own batch shard with NO gradient communication; every ``k_steps`` calls
    the replicas' parameters are averaged with one pmean over ICI. The
    reference rewrites the program to insert c_allreduce on params every
    k steps — here the periodic sync is a lax.cond inside one shard_map'd
    XLA program, so off-sync steps run with zero collective traffic.
    """

    def __init__(self, model, optimizer, loss_fn, mesh, k_steps=1,
                 recompute=False, donate=True):
        self.mesh = mesh
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.recompute = bool(recompute)
        self.grad_accum_steps = 1
        self.grad_accum_avg = True
        self.k_steps = int(k_steps)
        self.ndp = int(mesh.shape["dp"])
        if self.ndp <= 1:
            raise ValueError("LocalSGD needs a dp axis of size > 1")

        base = fjit.init_opt_state(model, optimizer)
        stack = lambda a: jnp.broadcast_to(
            a[None], (self.ndp,) + a.shape
        ).astype(a.dtype)
        self.state = {
            "params": jax.tree_util.tree_map(stack, base["params"]),
            # never updated, stays replicated — but copied: donation of
            # aliased leaves would invalidate the live model's arrays
            "frozen": jax.tree_util.tree_map(jnp.copy, base["frozen"]),
            "buffers": jax.tree_util.tree_map(stack, base["buffers"]),
            "opt": jax.tree_util.tree_map(stack, base["opt"]),
        }
        self._count = jnp.asarray(0, jnp.int32)
        # reuse the functional step builder for the per-replica local step
        self.pure_local = fjit.TrainStepFn._build_pure(self)

        k = self.k_steps

        def body(state, count, batch, lr, rng):
            squeeze = lambda a: jnp.squeeze(a, 0)
            local = {
                "params": jax.tree_util.tree_map(squeeze, state["params"]),
                "frozen": state["frozen"],
                "buffers": jax.tree_util.tree_map(squeeze, state["buffers"]),
                "opt": jax.tree_util.tree_map(squeeze, state["opt"]),
            }
            rng = jax.random.fold_in(rng, jax.lax.axis_index("dp"))
            new_local, metrics = self.pure_local(local, batch, lr, rng)
            count = count + 1

            def sync_branch(p):
                return jax.tree_util.tree_map(
                    lambda x: jax.lax.pmean(x, "dp"), p
                )

            do_sync = count >= k
            new_params = jax.lax.cond(
                do_sync, sync_branch, lambda p: p, new_local["params"]
            )
            new_count = jnp.where(do_sync, 0, count).astype(jnp.int32)
            unsq = lambda a: a[None]
            out_state = {
                "params": jax.tree_util.tree_map(unsq, new_params),
                "frozen": state["frozen"],
                "buffers": jax.tree_util.tree_map(
                    unsq, new_local["buffers"]
                ),
                "opt": jax.tree_util.tree_map(unsq, new_local["opt"]),
            }
            loss = jax.lax.pmean(metrics["loss"], "dp")
            return out_state, new_count, {"loss": loss}

        state_specs = {
            "params": P("dp"),
            "frozen": P(),
            "buffers": P("dp"),
            "opt": P("dp"),
        }
        self._sharded = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(state_specs, P(), P("dp"), P(), P()),
            out_specs=(state_specs, P(), P()),
            check_vma=False,
        )
        self.compiled = jax.jit(
            self._sharded, donate_argnums=(0,) if donate else ()
        )
        self._rng = default_generator().split()

    def __call__(self, *batch):
        with RecordEvent("train::step"), mesh_scope(self.mesh):
            with RecordEvent("train::shard_batch"):
                arrs = tuple(
                    b._array if isinstance(b, Tensor) else jnp.asarray(b)
                    for b in batch
                )
                shardings = shard_batch(arrs, self.mesh, ("dp",))
                arrs = jax.tree_util.tree_map(
                    jax.device_put, arrs, shardings)
            lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
            self._rng, sub = jax.random.split(self._rng)
            with RecordEvent("train::step_dispatch"):
                self.state, self._count, metrics = self.compiled(
                    self.state, self._count, arrs, lr, sub
                )
            _mon.counter("train/localsgd_steps").inc()
        return metrics

    def sync(self, gather=True):
        """Average replicas and write back into the eager objects."""
        import numpy as np

        mean0 = lambda a: jnp.mean(
            jnp.asarray(np.asarray(a)).astype(jnp.float32), axis=0
        ).astype(a.dtype) if a.dtype in (
            jnp.float32, jnp.bfloat16, jnp.float16
        ) else jnp.asarray(np.asarray(a))[0]
        state = {
            "params": jax.tree_util.tree_map(mean0, self.state["params"]),
            "frozen": jax.tree_util.tree_map(
                lambda a: jnp.asarray(np.asarray(a)), self.state["frozen"]
            ),
            "buffers": jax.tree_util.tree_map(mean0, self.state["buffers"]),
            "opt": jax.tree_util.tree_map(mean0, self.state["opt"]),
        }
        fjit.restore_state(self.model, state, self.optimizer)
        return self


def sharded_train_step(model, optimizer, loss_fn, mesh, rules=None,
                       batch_axes=("dp",), donate=True, strategy=None,
                       **kwargs):
    """Build a mesh-partitioned train step, consuming a fleet strategy.

    With ``strategy.localsgd`` on, returns a LocalSGDTrainStep (divergent
    replicas + periodic sync); otherwise a GSPMD ShardedTrainStep.
    """
    opts = consume_strategy(strategy)
    if opts.get("localsgd"):
        if rules is not None or tuple(batch_axes) != ("dp",) or kwargs:
            raise NotImplementedError(
                "localsgd replicas are whole-model (no tensor sharding): "
                "rules/batch_axes/extra step options are not supported "
                f"(got rules={rules}, batch_axes={batch_axes}, "
                f"kwargs={sorted(kwargs)})"
            )
        loss_fn2 = _amp_wrap(loss_fn, strategy) if opts["amp"] else loss_fn
        return LocalSGDTrainStep(
            model, optimizer, loss_fn2, mesh,
            k_steps=opts["localsgd_k"], recompute=opts["recompute"],
            donate=donate,
        )
    return ShardedTrainStep(
        model, optimizer, loss_fn, mesh, rules=rules,
        batch_axes=batch_axes, donate=donate, strategy=strategy, **kwargs,
    )
