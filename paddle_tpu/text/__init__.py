"""Text datasets (paddle.text.datasets surface).

Reference parity: python/paddle/dataset/{imdb.py, imikolov.py, wmt14.py,
wmt16.py, conll05.py, movielens.py} reader creators and the 2.x
map-style wrappers (incubate/hapi/text + paddle/text/datasets/).

Offline discipline (same as vision/datasets.py): zero network egress, so
each dataset loads the reference's cached on-disk format when present
under ``PADDLE_TPU_DATA_HOME`` and otherwise synthesizes a deterministic
corpus with the SAME shapes/vocab structure — and, crucially, with
LEARNABLE signal (sentiment words correlate with labels, translations
are a deterministic token mapping) so book tests can train to a
decreasing loss rather than fit noise. Every instance sets
``self.synthetic`` so tests can tell real data from stand-in data.
"""
from .datasets import (  # noqa: F401
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
    MQ2007,
    Sentiment,
    UCIHousing,
    WMT14,
    WMT16,
)

__all__ = [
    "Imdb", "Imikolov", "Movielens", "WMT14", "WMT16", "Conll05st",
    "UCIHousing", "Sentiment", "MQ2007",
]
