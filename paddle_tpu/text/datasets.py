"""Text dataset implementations. See package docstring for the offline
synthesis contract.

Reference formats honored when real files are present:
- Imdb: aclImdb tar.gz with {train,test}/{pos,neg}/*.txt
  (/root/reference/python/paddle/dataset/imdb.py:1)
- Imikolov: simple-examples tar.gz ptb.{train,valid}.txt
  (dataset/imikolov.py)
- WMT14/WMT16: token-id parallel corpora are synthesized only (the
  reference downloads preprocessed dicts; no egress here)
  (dataset/wmt14.py, wmt16.py)
- Conll05st: SRL tuples, synthesized (dataset/conll05.py)
- Movielens: ml-1m ratings triples (dataset/movielens.py)
- UCIHousing: 13-feature regression rows (dataset/uci_housing.py)
"""
from __future__ import annotations

import os
import tarfile

import numpy as np

from ..io import Dataset

from ..utils.data_home import DATA_HOME, warn_synthetic as _warn_synthetic

# shared deterministic word inventory for synthetic corpora
_POS_WORDS = ["good", "great", "excellent", "wonderful", "best", "love"]
_NEG_WORDS = ["bad", "awful", "terrible", "boring", "worst", "hate"]
_NEUTRAL = ["the", "a", "movie", "film", "plot", "actor", "scene", "story",
            "it", "was", "and", "of", "in", "to"]


class Imdb(Dataset):
    """IMDB sentiment (dataset/imdb.py): samples are (word-id sequence,
    label 0/1). ``word_idx`` maps token → id (0 reserved for OOV/pad)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, seed=None):
        self.mode = mode
        self.synthetic = False
        data_file = data_file or os.path.join(DATA_HOME, "imdb",
                                              "aclImdb_v1.tar.gz")
        if os.path.exists(data_file):
            self._load_archive(data_file, mode, cutoff)
        else:
            self._synthesize(
                n=512 if mode == "train" else 128,
                seed=7 if mode == "train" else 8,
            )

    def _load_archive(self, path, mode, cutoff):
        import collections
        import re

        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        freq = collections.Counter()
        docs = []
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                g = pat.match(m.name)
                if not g:
                    continue
                words = tf.extractfile(m).read().decode(
                    "latin-1").lower().split()
                docs.append((words, 0 if g.group(1) == "neg" else 1))
                freq.update(words)
        vocab = [w for w, c in freq.most_common() if c >= cutoff]
        self.word_idx = {w: i + 1 for i, w in enumerate(vocab)}
        self.docs = [
            (np.asarray([self.word_idx.get(w, 0) for w in ws], np.int64), y)
            for ws, y in docs
        ]

    def _synthesize(self, n, seed):
        rng = np.random.RandomState(seed)
        vocab = _NEUTRAL + _POS_WORDS + _NEG_WORDS
        self.word_idx = {w: i + 1 for i, w in enumerate(vocab)}
        self.docs = []
        for k in range(n):
            y = int(rng.randint(0, 2))
            senti = _POS_WORDS if y else _NEG_WORDS
            length = int(rng.randint(8, 24))
            words = [
                (rng.choice(senti) if rng.rand() < 0.35
                 else rng.choice(_NEUTRAL))
                for _ in range(length)
            ]
            self.docs.append((
                np.asarray([self.word_idx[w] for w in words], np.int64), y,
            ))
        _warn_synthetic(self)
        self.synthetic = True

    @property
    def vocab_size(self):
        return len(self.word_idx) + 1

    def __getitem__(self, i):
        return self.docs[i]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram LM dataset (dataset/imikolov.py): samples are n-tuples
    of word ids (first n-1 = context, last = target)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=2):
        self.synthetic = False
        self.window_size = int(window_size)
        data_file = data_file or os.path.join(
            DATA_HOME, "imikolov", "simple-examples.tgz"
        )
        split = "train" if mode == "train" else "valid"
        if os.path.exists(data_file):
            self._load_archive(data_file, split, min_word_freq)
        else:
            self._synthesize(
                n_sent=256 if mode == "train" else 64,
                seed=11 if mode == "train" else 12,
            )
        self._build(data_type)

    def _load_archive(self, path, split, min_freq):
        import collections

        with tarfile.open(path) as tf:
            name = f"./simple-examples/data/ptb.{split}.txt"
            for cand in (name, name[2:]):
                try:
                    raw = tf.extractfile(cand).read().decode()
                    break
                except KeyError:
                    continue
            else:
                raise FileNotFoundError(f"ptb.{split}.txt not in {path}")
        self.sents = [line.split() for line in raw.splitlines() if line]
        freq = collections.Counter(w for s in self.sents for w in s)
        vocab = sorted(w for w, c in freq.items() if c >= min_freq)
        self.word_idx = {w: i + 1 for i, w in enumerate(vocab)}

    def _synthesize(self, n_sent, seed):
        # markov-ish chains over a small vocab: n-gram prediction is
        # genuinely learnable (each word prefers a fixed successor)
        rng = np.random.RandomState(seed)
        vocab = _NEUTRAL + _POS_WORDS
        self.word_idx = {w: i + 1 for i, w in enumerate(vocab)}
        succ = {w: vocab[(i * 7 + 3) % len(vocab)]
                for i, w in enumerate(vocab)}
        self.sents = []
        for _ in range(n_sent):
            w = vocab[int(rng.randint(len(vocab)))]
            sent = [w]
            for _ in range(int(rng.randint(6, 14))):
                w = succ[w] if rng.rand() < 0.8 else vocab[
                    int(rng.randint(len(vocab)))]
                sent.append(w)
            self.sents.append(sent)
        _warn_synthetic(self)
        self.synthetic = True

    def _build(self, data_type):
        n = self.window_size
        self.samples = []
        for s in self.sents:
            ids = [self.word_idx.get(w, 0) for w in s]
            if data_type.upper() == "SEQ":
                self.samples.append(np.asarray(ids, np.int64))
                continue
            for k in range(len(ids) - n + 1):
                self.samples.append(np.asarray(ids[k:k + n], np.int64))

    @property
    def vocab_size(self):
        return len(self.word_idx) + 1

    def __getitem__(self, i):
        return self.samples[i]

    def __len__(self):
        return len(self.samples)


class _ParallelCorpus(Dataset):
    """Shared machinery for WMT14/WMT16: (src_ids, trg_in, trg_next)
    triples with <s>=1, <e>=2, OOV/pad=0 (dataset/wmt14.py id layout)."""

    BOS, EOS, PAD = 1, 2, 0

    def __init__(self, dict_size, mode, seed, n_train=384, n_test=96,
                 max_len=12):
        _warn_synthetic(self, fallback=False)
        self.synthetic = True
        self.dict_size = int(dict_size)
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        n = n_train if mode == "train" else n_test
        lo = 3  # ids below 3 are specials
        hi = max(lo + 1, self.dict_size)
        self.pairs = []
        for _ in range(n):
            length = int(rng.randint(3, max_len))
            src = rng.randint(lo, hi, length).astype(np.int64)
            trg = self._translate(src, hi, lo)
            trg_in = np.concatenate([[self.BOS], trg]).astype(np.int64)
            trg_next = np.concatenate([trg, [self.EOS]]).astype(np.int64)
            self.pairs.append((src, trg_in, trg_next))

    @staticmethod
    def _translate(src, hi, lo):
        # deterministic "language": reverse + fixed vocab permutation —
        # a seq2seq model can actually learn it (book-test requirement)
        return ((src[::-1] - lo) * 3 + 1) % (hi - lo) + lo

    def get_dict(self, reverse=False):
        d = {i: f"w{i}" for i in range(self.dict_size)}
        d[self.BOS], d[self.EOS], d[self.PAD] = "<s>", "<e>", "<unk>"
        if reverse:
            return {v: k for k, v in d.items()}
        return d

    def __getitem__(self, i):
        return self.pairs[i]

    def __len__(self):
        return len(self.pairs)

    def padded_arrays(self, max_len=None):
        """Batch the whole split into padded [N, L] arrays (book tests)."""
        L = max_len or max(len(s) for s, _, _ in self.pairs)
        Lt = (max_len or max(len(t) for _, t, _ in self.pairs))
        n = len(self.pairs)
        src = np.zeros((n, L), np.int64)
        tin = np.zeros((n, Lt), np.int64)
        tnx = np.zeros((n, Lt), np.int64)
        for i, (s, ti, tn) in enumerate(self.pairs):
            src[i, :min(L, len(s))] = s[:L]
            tin[i, :min(Lt, len(ti))] = ti[:Lt]
            tnx[i, :min(Lt, len(tn))] = tn[:Lt]
        return src, tin, tnx


class WMT14(_ParallelCorpus):
    """dataset/wmt14.py (dict_size-truncated en→fr)."""

    def __init__(self, data_file=None, mode="train", dict_size=64):
        super().__init__(dict_size, mode, seed=21)


class WMT16(_ParallelCorpus):
    """dataset/wmt16.py (BPE en↔de); same id layout, different seed."""

    def __init__(self, data_file=None, mode="train", src_dict_size=64,
                 trg_dict_size=64, lang="en"):
        super().__init__(max(src_dict_size, trg_dict_size), mode, seed=22)


class Conll05st(Dataset):
    """CoNLL-2005 SRL (dataset/conll05.py): samples are
    (word_ids, predicate_id, mark, label_ids) with BIO label space."""

    LABELS = ["O", "B-A0", "I-A0", "B-A1", "I-A1", "B-V"]

    def __init__(self, data_file=None, mode="train"):
        rng = np.random.RandomState(31 if mode == "train" else 32)
        _warn_synthetic(self)
        self.synthetic = True
        vocab = _NEUTRAL + _POS_WORDS + _NEG_WORDS
        self.word_idx = {w: i + 1 for i, w in enumerate(vocab)}
        self.label_idx = {l: i for i, l in enumerate(self.LABELS)}
        self.samples = []
        for _ in range(192 if mode == "train" else 48):
            length = int(rng.randint(5, 12))
            words = rng.randint(1, len(vocab) + 1, length).astype(np.int64)
            pred_pos = int(rng.randint(1, length - 1))
            mark = np.zeros(length, np.int64)
            mark[pred_pos] = 1
            labels = np.zeros(length, np.int64)  # O
            labels[pred_pos] = self.label_idx["B-V"]
            if pred_pos > 0:
                labels[0] = self.label_idx["B-A0"]
                labels[1:pred_pos] = self.label_idx["I-A0"]
            if pred_pos < length - 1:
                labels[pred_pos + 1] = self.label_idx["B-A1"]
                labels[pred_pos + 2:] = self.label_idx["I-A1"]
            self.samples.append((words, np.int64(words[pred_pos]), mark,
                                 labels))

    @property
    def vocab_size(self):
        return len(self.word_idx) + 1

    @property
    def num_labels(self):
        return len(self.LABELS)

    def __getitem__(self, i):
        return self.samples[i]

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """MovieLens ratings (dataset/movielens.py): samples are
    (user_id, gender, age, occupation, movie_id, category, rating)."""

    NUM_USERS = 400
    NUM_MOVIES = 200
    NUM_CATEGORIES = 8
    NUM_OCCUPATIONS = 10

    def __init__(self, data_file=None, mode="train"):
        rng = np.random.RandomState(41 if mode == "train" else 42)
        _warn_synthetic(self)
        self.synthetic = True
        n = 2048 if mode == "train" else 512
        users = rng.randint(1, self.NUM_USERS + 1, n)
        movies = rng.randint(1, self.NUM_MOVIES + 1, n)
        # learnable signal: rating ~ affinity(user bucket, movie category)
        cat = movies % self.NUM_CATEGORIES
        affinity = (users % 5)[:, None] == (cat % 5)[:, None]
        rating = np.clip(
            3 + affinity[:, 0].astype(int) * 1.5
            + rng.randn(n) * 0.5, 1, 5,
        )
        self.samples = [
            (np.int64(u), np.int64(u % 2), np.int64(u % 7),
             np.int64(u % self.NUM_OCCUPATIONS), np.int64(m),
             np.int64(m % self.NUM_CATEGORIES), np.float32(r))
            for u, m, r in zip(users, movies, rating)
        ]

    def __getitem__(self, i):
        return self.samples[i]

    def __len__(self):
        return len(self.samples)


class UCIHousing(Dataset):
    """Boston housing regression (dataset/uci_housing.py): 13 features →
    price. Synthetic: price = linear(features) + noise."""

    FEATURE_DIM = 13

    def __init__(self, data_file=None, mode="train"):
        data_file = data_file or os.path.join(DATA_HOME, "uci_housing",
                                              "housing.data")
        self.synthetic = False
        if os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
            # normalize with FULL-corpus stats before the reference's
            # 404/102 split (uci_housing.py does the same: one
            # feature_range over all rows), so train/test share scaling
            feats_all = raw[:, :-1]
            mu = feats_all.mean(0)
            sd = feats_all.std(0) + 1e-6
            raw = raw[:404] if mode == "train" else raw[404:]
            feats = (raw[:, :-1] - mu) / sd
            self.features = feats
            self.prices = raw[:, -1].astype(np.float32)
            return
        else:
            rng = np.random.RandomState(51 if mode == "train" else 52)
            n = 404 if mode == "train" else 102
            feats = rng.randn(n, self.FEATURE_DIM).astype(np.float32)
            w = np.linspace(-1.0, 1.0, self.FEATURE_DIM).astype(np.float32)
            prices = feats @ w + 22.5 + rng.randn(n).astype(np.float32) * 0.5
            _warn_synthetic(self)
            self.synthetic = True
        # normalize like the reference loader (feature_range scaling)
        mu, sd = feats.mean(0), feats.std(0) + 1e-6
        self.features = (feats - mu) / sd
        self.prices = prices.astype(np.float32)

    def __getitem__(self, i):
        return self.features[i], np.asarray([self.prices[i]], np.float32)

    def __len__(self):
        return len(self.features)


class Sentiment(Dataset):
    """NLTK movie_reviews sentiment (dataset/sentiment.py): samples are
    (word-id sequence, label 0/1), vocabulary sorted by corpus frequency
    (sentiment.py:70 get_word_dict), interleaved neg/pos file order for
    cross reading (sentiment.py:91 sort_files), 1600/400 train/test
    split (NUM_TRAINING_INSTANCES).

    Real data: a movie_reviews directory (or zip layout extracted) with
    {pos,neg}/*.txt under ``data_file``. Otherwise loud synthetic.
    """

    NUM_TRAINING_INSTANCES = 1600
    NUM_TOTAL_INSTANCES = 2000

    def __init__(self, data_file=None, mode="train", seed=None):
        self.mode = mode
        self.synthetic = False
        self._seed = seed
        data_file = data_file or os.path.join(DATA_HOME, "corpora",
                                              "movie_reviews")
        if os.path.isdir(data_file):
            self._load_dir(data_file, mode)
        else:
            self._synthesize(mode)

    def _docs_to_ids(self, docs, labels, mode):
        import collections

        freq = collections.Counter()
        for words in docs:
            freq.update(words)
        # frequency-sorted vocabulary (ties broken by insertion order,
        # matching the reference's stable sort over iteritems)
        self.word_idx = {
            w: i for i, (w, _) in enumerate(
                sorted(freq.items(), key=lambda kv: -kv[1]))
        }
        data = [
            (np.asarray([self.word_idx[w] for w in words], np.int64), lab)
            for words, lab in zip(docs, labels)
        ]
        split = int(len(data) * self.NUM_TRAINING_INSTANCES
                    / self.NUM_TOTAL_INSTANCES)
        self.data = data[:split] if mode == "train" else data[split:]

    def _load_dir(self, root, mode):
        import glob as _glob

        neg = sorted(_glob.glob(os.path.join(root, "neg", "*.txt")))
        pos = sorted(_glob.glob(os.path.join(root, "pos", "*.txt")))
        if not neg or not pos or len(neg) != len(pos):
            raise ValueError(
                f"Sentiment: {root!r} exists but does not look like a "
                f"movie_reviews layout (found {len(neg)} neg / "
                f"{len(pos)} pos .txt files; need equal non-zero counts "
                "under neg/ and pos/)")
        docs, labels = [], []
        # interleave neg/pos (sort_files cross-reading order)
        for nf, pf in zip(neg, pos):
            for path, lab in ((nf, 0), (pf, 1)):
                with open(path, errors="ignore") as f:
                    docs.append(f.read().split())
                labels.append(lab)
        self._docs_to_ids(docs, labels, mode)

    def _synthesize(self, mode):
        _warn_synthetic(self)
        self.synthetic = True
        # seed=None keeps the historical fixed corpus (RandomState(31))
        rng = np.random.RandomState(31 if self._seed is None
                                    else self._seed)
        docs, labels = [], []
        for i in range(200):  # scaled-down corpus, same structure
            for lab, bank in ((0, _NEG_WORDS), (1, _POS_WORDS)):
                n = rng.randint(8, 24)
                words = [
                    (bank if rng.rand() < 0.4 else _NEUTRAL)[
                        rng.randint(0, 6)]
                    for _ in range(n)
                ]
                docs.append(words)
                labels.append(lab)
        self._docs_to_ids(docs, labels, mode)

    def get_word_dict(self):
        """[(word, rank)] sorted by frequency (sentiment.py:70)."""
        return sorted(self.word_idx.items(), key=lambda kv: kv[1])

    def __getitem__(self, i):
        return self.data[i]

    def __len__(self):
        return len(self.data)


class MQ2007(Dataset):
    """LETOR MQ2007 learning-to-rank (dataset/mq2007.py).

    Parses the LETOR line format ``rel qid:<id> 1:<v> ... 46:<v> #docid
    = <d>`` into per-query groups and yields samples in one of the
    reference's formats (mq2007.py:295 __reader__):

    - "pointwise": (feature [46], score)
    - "pairwise":  (feature_left [46], feature_right [46]) with
      rel(left) > rel(right) (full partial order, mq2007.py:189)
    - "listwise":  (label_list [n], feature_list [n, 46])
    """

    FEATURE_DIM = 46

    def __init__(self, data_file=None, format="pairwise", mode="train",
                 fill_missing=-1.0):
        if format not in ("pointwise", "pairwise", "listwise"):
            raise ValueError(f"unknown MQ2007 format {format!r}")
        self.format = format
        self.synthetic = False
        data_file = data_file or os.path.join(
            DATA_HOME, "MQ2007", "Fold1",
            {"train": "train.txt", "test": "test.txt",
             "vali": "vali.txt"}[mode])
        if os.path.exists(data_file):
            queries = self._load_text(data_file, fill_missing)
        else:
            queries = self._synthesize(mode)
        self._build(queries)

    def _load_text(self, path, fill_missing):
        queries = {}
        with open(path) as f:
            for line in f:
                body = line.split("#")[0].split()
                if len(body) < 2:
                    continue
                rel = int(body[0])
                qid = int(body[1].split(":")[1])
                feat = np.full(self.FEATURE_DIM, fill_missing, np.float32)
                for tok in body[2:]:
                    k, v = tok.split(":")
                    feat[int(k) - 1] = float(v)
                queries.setdefault(qid, []).append((rel, feat))
        return queries

    def _synthesize(self, mode):
        _warn_synthetic(self)
        self.synthetic = True
        rng = np.random.RandomState(61 if mode == "train" else 62)
        queries = {}
        w = rng.randn(self.FEATURE_DIM).astype(np.float32)
        for qid in range(24):
            docs = []
            for _ in range(rng.randint(4, 12)):
                feat = rng.randn(self.FEATURE_DIM).astype(np.float32)
                # relevance correlates with a hidden linear score
                rel = int(np.clip(feat @ w / 4 + rng.randn() * 0.3 + 1,
                                  0, 2))
                docs.append((rel, feat))
            queries[qid] = docs
        return queries

    def _build(self, queries):
        self.data = []
        for qid in sorted(queries):
            docs = queries[qid]
            if self.format == "pointwise":
                for rel, feat in docs:
                    self.data.append((feat, np.float32(rel)))
            elif self.format == "pairwise":
                for i, (ri, fi) in enumerate(docs):
                    for rj, fj in docs[i + 1:]:
                        if ri > rj:
                            self.data.append((fi, fj))
                        elif rj > ri:
                            self.data.append((fj, fi))
            else:  # listwise
                labels = np.asarray([r for r, _ in docs], np.float32)
                feats = np.stack([f for _, f in docs])
                self.data.append((labels, feats))

    def __getitem__(self, i):
        return self.data[i]

    def __len__(self):
        return len(self.data)
