"""Hardware-utilization accounting: XLA cost models + device peak tables.

The stack's north star is "as fast as the hardware allows", but tokens/sec
alone cannot say how fast that *is*: a 10% regression hides inside run-to-
run noise unless the number is normalized by what the compiled program
*should* cost. This module owns both halves of that ratio:

- **What a program costs** — on every compile (executor RunPlan jit,
  framework/jit.py compiled steps, hapi fit) the caller captures XLA's own
  ``cost_analysis()`` (FLOPs, bytes accessed — the numbers the compiler
  schedules against, not a formula that drifts from the implementation)
  and ``memory_analysis()`` (argument/output/temp sizes, i.e. the
  program's HBM footprint) into a :class:`CostRecord`, keyed by the same
  identity the plan/jit caches use.
- **What the hardware offers** — a per-device-kind peak table (MXU
  FLOPs/s, HBM bytes/s, ICI bytes/s), overridable via
  ``FLAGS_device_peaks`` for new silicon or derated SKUs.

Dividing the two gives MFU (the Gemma-on-TPU comparison papers' headline
denominator), HBM bandwidth utilization, and a roofline classification
(compute- vs memory-bound) per step — surfaced in the TrainingMonitor
line, the Prometheus dump, and the ``/costz`` debug endpoint; the cluster
aggregator (:mod:`monitor.cluster`) ships them cross-rank.
"""
from __future__ import annotations

import threading
import time

from ..flags import flag
from . import registry as _reg

__all__ = [
    "CostRecord",
    "analyze_cost", "analyze_memory", "flops_and_bytes",
    "capture", "note_run",
    "cost_records", "latest_record", "reset_cost_records",
    "device_peaks", "mfu", "hbm_bw_util", "roofline_class",
    "costz_payload",
]

# ---------------------------------------------------------------------------
# XLA analysis normalization (the ONE guard for every call site)
# ---------------------------------------------------------------------------


def analyze_cost(stage) -> dict | None:
    """``stage.cost_analysis()`` normalized to one plain dict, or None.

    ``stage`` is a jax ``Lowered`` or ``Compiled`` (both expose the
    client-side HLO cost analysis). Backends differ: some return a dict,
    some a one-element list of dicts (per-partition), some ``None`` or an
    empty mapping, and proxy/tunneled backends may raise — every caller
    used to hand-roll this guard; now there is exactly one.
    """
    if stage is None:
        return None
    try:
        ca = stage.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    return dict(ca)


_MEM_ATTRS = ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes")


def analyze_memory(compiled) -> dict | None:
    """``compiled.memory_analysis()`` as a plain dict, or None.

    Only a ``Compiled`` carries the backend buffer-assignment sizes; a
    backend without the API (or one returning a partial stats object)
    degrades to None / missing keys rather than raising.
    """
    if compiled is None:
        return None
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for attr in _MEM_ATTRS:
        v = getattr(ma, attr, None)
        if v is not None:
            out[attr] = int(v)
    return out or None


def flops_and_bytes(stage):
    """(flops, bytes_accessed) of a Lowered/Compiled, or None when the
    backend publishes no cost analysis — the shared shape of the old
    ad-hoc call sites (hapi layer costing, the HLO dump tools)."""
    ca = analyze_cost(stage)
    if ca is None:
        return None
    return (float(ca.get("flops", 0.0) or 0.0),
            float(ca.get("bytes accessed", 0.0) or 0.0))


# ---------------------------------------------------------------------------
# CostRecord registry
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_records: dict = {}          # key -> CostRecord (insertion-ordered, LRU-ish)
_RECORDS_LIMIT = 256         # long-lived processes fed many programs


class CostRecord:
    """One compiled program's static cost sheet.

    ``flops``/``bytes_accessed`` come from XLA's HLO cost analysis of the
    whole module (one training step = one record); the ``*_bytes`` memory
    fields from the backend buffer assignment. ``runs`` counts dispatches
    (bumped by :func:`note_run`), so ``flops * runs`` is the executed-work
    ledger the MFU window math consumes via the registry counters.
    """

    __slots__ = ("key", "label", "flops", "bytes_accessed",
                 "argument_bytes", "output_bytes", "temp_bytes",
                 "alias_bytes", "peak_hbm_bytes", "partial", "meta",
                 "runs", "created_t", "predicted_peak_bytes",
                 "plan_accuracy", "predicted_op_us", "measured_op_us",
                 "time_accuracy")

    def __init__(self, key, label, cost, mem, meta):
        self.key = key
        self.label = label
        self.flops = float((cost or {}).get("flops", 0.0) or 0.0)
        self.bytes_accessed = float(
            (cost or {}).get("bytes accessed", 0.0) or 0.0)
        mem = mem or {}
        self.argument_bytes = int(mem.get("argument_size_in_bytes", 0))
        self.output_bytes = int(mem.get("output_size_in_bytes", 0))
        self.temp_bytes = int(mem.get("temp_size_in_bytes", 0))
        # donated input/output pairs share one buffer; alias_bytes is
        # that shared size, so arg+out+temp-alias is the true resident
        # footprint (the planner's actual-side comparison, analysis/
        # memory.note_actual)
        self.alias_bytes = int(mem.get("alias_size_in_bytes", 0))
        # the program's live-HBM high-water mark: inputs + outputs + XLA
        # scratch (aliased pairs count on BOTH sides here — the historic
        # gauge semantics; subtract alias_bytes for the true resident
        # footprint, as note_actual does)
        self.peak_hbm_bytes = (self.argument_bytes + self.output_bytes
                               + self.temp_bytes)
        self.partial = cost is None or mem is None
        self.meta = dict(meta)
        self.runs = 0
        self.created_t = time.time()
        # closed by analysis.memory.note_actual after the first dispatch
        # of a statically-planned program (predicted peak vs this
        # record's arg+out+temp-alias)
        self.predicted_peak_bytes = None
        self.plan_accuracy = None
        # closed by monitor.opprof.profile_program: calibrated-roofline
        # predicted per-op µs vs the replay-measured total (the time-
        # accuracy analog of plan_accuracy; ratio, 1.0 = perfect)
        self.predicted_op_us = None
        self.measured_op_us = None
        self.time_accuracy = None

    def to_dict(self) -> dict:
        return {
            "key": str(self.key), "label": self.label,
            "flops": self.flops, "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "alias_bytes": self.alias_bytes,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "predicted_peak_bytes": self.predicted_peak_bytes,
            "plan_accuracy": (round(self.plan_accuracy, 4)
                              if self.plan_accuracy is not None else None),
            "predicted_op_us": self.predicted_op_us,
            "measured_op_us": self.measured_op_us,
            "time_accuracy": (round(self.time_accuracy, 4)
                              if self.time_accuracy is not None else None),
            "arithmetic_intensity": (
                self.flops / self.bytes_accessed
                if self.bytes_accessed else 0.0),
            "roofline": roofline_class(self.flops, self.bytes_accessed),
            "partial": self.partial, "runs": self.runs,
            "meta": self.meta,
        }


def capture(label, lowered=None, compiled=None, key=None, **meta):
    """Record one compiled program's cost sheet (idempotent per ``key``).

    Cost comes from ``compiled`` when it publishes an analysis, else from
    ``lowered`` (client-side HLO pass — some backends only implement one);
    memory needs ``compiled``. A backend returning nothing still yields a
    record (``partial=True``, zero FLOPs) so ``/costz`` says "analysis
    unavailable" instead of silently showing no program at all.

    Per-label gauges (``cost/<label>/flops`` etc.) land in the registry so
    the Prometheus dump carries the latest program's static costs.
    """
    if key is None:
        key = label
    cost = analyze_cost(compiled)
    if cost is None:
        cost = analyze_cost(lowered)
    mem = analyze_memory(compiled)
    rec = CostRecord(key, label, cost, mem, meta)
    with _lock:
        _records.pop(key, None)
        _records[key] = rec
        while len(_records) > _RECORDS_LIMIT:
            _records.pop(next(iter(_records)))
    for field in ("flops", "bytes_accessed", "peak_hbm_bytes"):
        _reg.gauge(f"cost/{label}/{field}").set(getattr(rec, field))
    try:
        from . import flight_recorder as _flight

        _flight.record_event(
            "cost_capture", label=label, flops=rec.flops,
            bytes_accessed=rec.bytes_accessed,
            peak_hbm_bytes=rec.peak_hbm_bytes, partial=rec.partial,
            **{k: str(v)[:120] for k, v in meta.items()})
    except Exception:
        pass
    return rec


def note_run(record, n=1):
    """Account ``n`` dispatches of a captured program into the executed-
    work ledger (``cost/executed_flops``, ``cost/executed_bytes``) the
    TrainingMonitor's MFU window math differences. Hot-path cheap: two
    counter adds; a ``None`` record (capture failed/disabled) is free."""
    if record is None:
        return
    record.runs += n
    if record.flops:
        _reg.counter("cost/executed_flops").inc(record.flops * n)
    if record.bytes_accessed:
        _reg.counter("cost/executed_bytes").inc(record.bytes_accessed * n)


def cost_records() -> dict:
    """Live CostRecords by key (insertion order)."""
    with _lock:
        return dict(_records)


def latest_record(label=None):
    """Most recently captured record (optionally filtered by label)."""
    with _lock:
        for rec in reversed(list(_records.values())):
            if label is None or rec.label == label:
                return rec
    return None


def reset_cost_records():
    with _lock:
        _records.clear()


# ---------------------------------------------------------------------------
# Device peak table
# ---------------------------------------------------------------------------

# (device_kind substring match, ordered most-specific first) -> peaks in
# FLOP/s (bf16 dense MXU), HBM bytes/s, ICI bytes/s, and HBM CAPACITY
# bytes per chip. Published per-chip numbers; new silicon or derated
# SKUs override any subset via FLAGS_device_peaks. hbm_bytes is the
# memory-budget denominator the static planner admits against
# (analysis/memory.check_memory_budget, FLAGS_memory_budget_check).
_PEAKS_TABLE = (
    ("v6", {"flops": 918e12, "hbm_bw": 1640e9, "ici_bw": 448e9,
            "hbm_bytes": 32e9}),
    ("v5p", {"flops": 459e12, "hbm_bw": 2765e9, "ici_bw": 600e9,
             "hbm_bytes": 95e9}),
    ("v5 lite", {"flops": 197e12, "hbm_bw": 819e9, "ici_bw": 200e9,
                 "hbm_bytes": 16e9}),
    ("v5e", {"flops": 197e12, "hbm_bw": 819e9, "ici_bw": 200e9,
             "hbm_bytes": 16e9}),
    ("v5", {"flops": 459e12, "hbm_bw": 2765e9, "ici_bw": 600e9,
            "hbm_bytes": 95e9}),
    ("v4", {"flops": 275e12, "hbm_bw": 1228e9, "ici_bw": 300e9,
            "hbm_bytes": 32e9}),
    ("v3", {"flops": 123e12, "hbm_bw": 900e9, "ici_bw": 140e9,
            "hbm_bytes": 32e9}),
    ("v2", {"flops": 45e12, "hbm_bw": 700e9, "ici_bw": 100e9,
            "hbm_bytes": 16e9}),
)

# CPU / unknown backends get NOMINAL peaks (order-of-magnitude host
# numbers) so the utilization plumbing works everywhere — the absolute
# MFU is only meaningful on known silicon or with FLAGS_device_peaks set,
# and the payload says so via "nominal": true.
_NOMINAL_PEAKS = {"flops": 1e11, "hbm_bw": 5e10, "ici_bw": 1e10,
                  "hbm_bytes": 8e9}

_detected_kind = [None]  # cache: jax backend init is not free
_parse_memo = [None, {}]  # [last raw flag string, its parsed overrides]


def _device_kind() -> str:
    if _detected_kind[0] is None:
        try:
            import jax

            _detected_kind[0] = str(jax.local_devices()[0].device_kind)
        except Exception:
            _detected_kind[0] = "unknown"
    return _detected_kind[0]


def _parse_peaks_flag(raw: str) -> dict:
    """``FLAGS_device_peaks``: comma-separated ``k=v`` floats over
    {flops, hbm_bw, ici_bw, hbm_bytes} (units: FLOP/s, B/s, B/s, B).
    Unknown keys and unparseable entries are ignored loudly-enough (they
    simply don't override), so a typo degrades to the detected table,
    not a crash."""
    out = {}
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        k, _, v = part.partition("=")
        k = k.strip().lower()
        if k not in ("flops", "hbm_bw", "ici_bw", "hbm_bytes"):
            continue
        try:
            out[k] = float(v)
        except ValueError:
            continue
    return out


def device_peaks(kind=None) -> dict:
    """Peak throughput/capacity sheet for the detected (or given) device
    kind: ``{"kind", "flops", "hbm_bw", "ici_bw", "hbm_bytes",
    "nominal"}`` — the MFU/bandwidth/roofline denominators plus the HBM
    capacity the static memory planner budgets against.
    ``FLAGS_device_peaks`` overrides any subset; an override clears the
    nominal marker (the operator asserted real numbers)."""
    kind = kind if kind is not None else _device_kind()
    lowered = kind.lower()
    peaks, nominal = None, True
    for sub, vals in _PEAKS_TABLE:
        if sub in lowered:
            peaks, nominal = dict(vals), False
            break
    if peaks is None:
        peaks = dict(_NOMINAL_PEAKS)
    try:
        raw = str(flag("device_peaks"))
        if raw != _parse_memo[0]:  # memo: skip re-parsing per call
            _parse_memo[0], _parse_memo[1] = raw, _parse_peaks_flag(raw)
        override = _parse_memo[1]
    except Exception:
        override = {}
    if override:
        peaks.update(override)
        nominal = False
    peaks["kind"] = kind
    peaks["nominal"] = nominal
    return peaks


# ---------------------------------------------------------------------------
# Utilization math
# ---------------------------------------------------------------------------


def mfu(flops_per_s, peaks=None) -> float:
    """Model FLOPs utilization: achieved FLOP/s over the chip's peak."""
    peaks = peaks or device_peaks()
    return float(flops_per_s) / peaks["flops"] if peaks["flops"] else 0.0


def hbm_bw_util(bytes_per_s, peaks=None) -> float:
    """Achieved HBM traffic over the chip's peak memory bandwidth."""
    peaks = peaks or device_peaks()
    return float(bytes_per_s) / peaks["hbm_bw"] if peaks["hbm_bw"] else 0.0


def roofline_class(flops, bytes_accessed, peaks=None) -> str:
    """Roofline classification of a program (or a step window): compare
    its arithmetic intensity (FLOPs per HBM byte) against the machine's
    ridge point (peak FLOPs / peak bandwidth). Left of the ridge the
    program cannot reach peak FLOPs no matter how good the schedule —
    it is ``memory-bound``; right of it, ``compute-bound``."""
    if not flops or not bytes_accessed:
        return "unknown"
    peaks = peaks or device_peaks()
    if not peaks["hbm_bw"] or not peaks["flops"]:
        return "unknown"
    ridge = peaks["flops"] / peaks["hbm_bw"]
    return ("compute-bound" if (flops / bytes_accessed) >= ridge
            else "memory-bound")


def costz_payload() -> dict:
    """The ``/costz`` debug-endpoint body: device peaks + every captured
    program's cost sheet + the executed-work ledger."""
    return {
        "device_peaks": device_peaks(),
        "executed_flops": _reg.counter("cost/executed_flops").value,
        "executed_bytes": _reg.counter("cost/executed_bytes").value,
        "records": [rec.to_dict() for rec in cost_records().values()],
    }
