"""Per-op device-time attribution (``/profilez``): stamped scopes, trace
folding, and the replay profiler.

The cost model (cost_model.py) predicts FLOPs/bytes per program and the
goodput/SLO planes account wall time — this module closes the loop at the
granularity everything else argues about: **individual Program ops**. Three
legs, matching the TVM-style measured-cost feedback loop (PAPERS.md):

1. **Attribution stamping.** Every op the executor traces gets a stable
   identity ``op.type#<block>/<index>`` (:func:`op_scope_name`) pushed
   through ``jax.named_scope`` (static/executor.py), so XLA HLO location
   metadata and ``jax.profiler`` device traces carry per-op identity.
   :func:`attribute_trace` parses the profiler's emitted
   ``*.trace.json.gz`` files and folds device events back onto stamped
   ops, reporting a **coverage ratio** = stamped device time / total
   device time (on the timelines that carry stamps at all — the python
   tracer's ``$``-prefixed host rows are excluded by construction).

2. **Replay profiler.** :func:`profile_program` re-runs a program
   op-by-op through the REGISTRY kernels: per-op ``jax.jit`` (the jitted
   callable is *named with the stamp*, so even CPU traces — where XLA
   thunks carry no HLO metadata — self-identify as
   ``PjitFunction(matmul#0/3)``), warmup + best-of-N timing behind
   ``block_until_ready`` barriers. Yields µs, share, achieved FLOP/s,
   per-op MFU and roofline class (cost_model peaks), plus the
   **time-accuracy closure**: roofline-predicted µs (from a per-process
   *calibrated* machine model, :func:`calibration`) vs measured µs per op
   and per program, landing on the executor's CostRecord and ``/costz``
   exactly like memplan's ``plan_accuracy``.

3. **Surfaces.** :func:`profilez_payload` backs ``/profilez`` (debug
   server + both serving server kinds, ``?program=``/``?topk=``),
   :func:`top_ops` the ``/statz`` top-K table, the
   ``opprof/op_time_ms`` labeled histogram family lands on ``/metrics``,
   and :func:`chrome_events` appends a per-op track to
   ``export_merged_chrome_trace``.

Accuracy contract: *replay* timings are per-op kernel latencies measured
in isolation (no inter-op fusion, no overlap) — an upper bound on each
op's standalone cost and the right currency for comparing a fused kernel
against the chain it replaced. *Trace attribution* measures ops inside
the real fused program — authoritative for shares, but only as complete
as its coverage ratio. Report both; trust trace shares when coverage
>= 0.9, replay deltas for A/B kernel decisions.

Overhead contract: stamping happens at jax *trace* time only (once per
compile) — the steady-state dispatch path never formats a stamp, so
profiling-idle overhead is ~0 (bench.py ``opprof_overhead``). Replay and
trace parsing run only on demand.
"""
from __future__ import annotations

import glob
import gzip
import json
import math
import os
import re
import tempfile
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "TIME_ACCURACY_ENVELOPE",
    "op_scope_name",
    "parse_op_scope",
    "load_trace_events",
    "attribute_trace",
    "calibration",
    "predict_op_us",
    "profile_program",
    "record_profile",
    "profiles",
    "latest_profile",
    "reset_profiles",
    "top_ops",
    "opprof_stats",
    "profilez_payload",
    "chrome_events",
]

# Program-level predicted-vs-measured envelope asserted by
# tools/opprof_smoke.py: the calibrated roofline prediction must land
# within this factor of the measured replay total, either direction
# (time_accuracy in [1/ENVELOPE, ENVELOPE]). An order of magnitude is
# deliberately wide: on the CPU CI runner the "device" is a shared host,
# per-op kernels sit microseconds from the dispatch floor, and ambient
# load inflates measured totals ~2x run-to-run (observed band on the
# smoke programs: 0.15-0.9). The gate exists to catch the model or the
# measurement going off the rails, not to certify the CPU runner; on a
# real TPU, where kernels dwarf the dispatch floor, the same model
# tracks far tighter.
TIME_ACCURACY_ENVELOPE = 10.0


# ---------------------------------------------------------------------------
# Leg 1a: the stamp grammar (shared with static/executor.py)
# ---------------------------------------------------------------------------

# stamp = <op.type>#<block>/<index>. The op type charset matches the
# registry's names (incl. "grad::mul" colons); '#' and '/' never appear
# in an op type, so the grammar is unambiguous and survives embedding in
# longer scope paths ("jit(main)/matmul#0/3/dot_general",
# "PjitFunction(matmul#0/3)").
_STAMP_RE = re.compile(r"(?P<type>[A-Za-z0-9_.:\-]+)#(?P<block>\d+)/(?P<index>\d+)")


def op_scope_name(op_type, block_idx, op_index) -> str:
    """The stable per-op scope identity: ``op.type#<block>/<index>``."""
    return f"{op_type}#{int(block_idx)}/{int(op_index)}"


def parse_op_scope(name):
    """Extract ``(op_type, block_idx, op_index)`` from a scope/event name
    carrying a stamp anywhere inside it, or None."""
    m = _STAMP_RE.search(str(name))
    if m is None:
        return None
    return m.group("type"), int(m.group("block")), int(m.group("index"))


# ---------------------------------------------------------------------------
# Leg 1b: trace parsing + attribution folding
# ---------------------------------------------------------------------------


def load_trace_events(trace_dir):
    """All chrome traceEvents under ``trace_dir`` (recursive,
    ``*.trace.json[.gz]``) as ``(events, files_ok, files_skipped)``.

    A truncated/corrupt file (the profiler died mid-write) is counted in
    ``files_skipped`` and never raises — the edge table in
    tests/test_opprof.py holds this to it.
    """
    events, ok, skipped = [], 0, 0
    if not trace_dir or not os.path.isdir(trace_dir):
        return events, ok, skipped
    names = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(trace_dir, "**", "*.trace.json"),
                    recursive=True))
    for fn in names:
        try:
            if fn.endswith(".gz"):
                with gzip.open(fn, "rt") as f:
                    trace = json.load(f)
            else:
                with open(fn) as f:
                    trace = json.load(f)
            evs = trace.get("traceEvents", []) if isinstance(trace, dict) \
                else []
        except Exception:
            skipped += 1
            continue
        ok += 1
        if isinstance(evs, list):
            events.extend(e for e in evs if isinstance(e, dict))
    return events, ok, skipped


def _union_us(intervals) -> float:
    """Total covered span of ``[(ts, dur), ...]`` with overlaps/nesting
    folded (a stamped scope containing a stamped sub-scope must not count
    its device time twice)."""
    total, end = 0.0, None
    for ts, dur in sorted(intervals):
        s, e = ts, ts + dur
        if end is None or s >= end:
            total += e - s
            end = e
        elif e > end:
            total += e - end
            end = e
    return total


def attribute_trace(trace_dir) -> dict:
    """Fold a profiler trace directory into a per-op attribution table.

    Only timelines (pid, tid) that carry at least one stamped event are
    scored — device/op rows, not the python tracer or unrelated host
    threads (python-tracer rows are additionally excluded by their ``$``
    name prefix). Within each scored timeline, time is interval-folded
    so nested scopes never double count. Events with *no* stamp on a
    scored timeline count against coverage but never crash the parse.

    Returns ``{"status", "coverage", "total_us", "stamped_us",
    "unattributed_us", "files", "files_skipped", "timelines", "ops"}``
    where ``ops`` rows carry ``scope/op_type/block/index/time_us/share/
    events``. An empty or missing dir is ``status="no-data"`` — a clean
    payload, not a 500.
    """
    events, ok, skipped = load_trace_events(trace_dir)
    # (pid, tid) -> {"all": [(ts, dur)], "stamped": [...],
    #                "per_op": {stamp: [...]}}
    lanes = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = str(ev.get("name", ""))
        if name.startswith("$"):
            continue  # python-tracer host row
        try:
            ts = float(ev["ts"])
            dur = float(ev.get("dur", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        if dur <= 0.0:
            continue
        lane = lanes.setdefault((ev.get("pid"), ev.get("tid")), {
            "all": [], "stamped": [], "per_op": {}})
        lane["all"].append((ts, dur))
        parsed = parse_op_scope(name)
        if parsed is not None:
            stamp = op_scope_name(*parsed)
            lane["stamped"].append((ts, dur))
            lane["per_op"].setdefault(stamp, []).append((ts, dur))
    scored = {k: v for k, v in lanes.items() if v["stamped"]}
    total = sum(_union_us(v["all"]) for v in scored.values())
    stamped = sum(_union_us(v["stamped"]) for v in scored.values())
    per_op = {}
    n_events = {}
    for lane in scored.values():
        for stamp, ivals in lane["per_op"].items():
            per_op[stamp] = per_op.get(stamp, 0.0) + _union_us(ivals)
            n_events[stamp] = n_events.get(stamp, 0) + len(ivals)
    ops = []
    for stamp, us in sorted(per_op.items(), key=lambda kv: -kv[1]):
        op_type, blk, idx = parse_op_scope(stamp)
        ops.append({
            "scope": stamp, "op_type": op_type, "block": blk, "index": idx,
            "time_us": round(us, 3),
            "share": round(us / total, 4) if total else 0.0,
            "events": n_events[stamp],
        })
    if not scored:
        return {"status": "no-data", "coverage": None, "total_us": 0.0,
                "stamped_us": 0.0, "unattributed_us": 0.0, "files": ok,
                "files_skipped": skipped, "timelines": 0, "ops": []}
    return {
        "status": "ok",
        "coverage": round(stamped / total, 4) if total else None,
        "total_us": round(total, 3),
        "stamped_us": round(stamped, 3),
        "unattributed_us": round(max(total - stamped, 0.0), 3),
        "files": ok,
        "files_skipped": skipped,
        "timelines": len(scored),
        "ops": ops,
    }


# ---------------------------------------------------------------------------
# Leg 2a: the calibrated machine model (time prediction)
# ---------------------------------------------------------------------------

_CALIB: dict = {}
_calib_lock = threading.Lock()


def _best_of_us(fn, *args, warmup=1, repeats=5) -> float:
    for _ in range(max(warmup, 0)):
        jax.block_until_ready(fn(*args))
    best = math.inf
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e6


def calibration(refresh=False) -> dict:
    """The per-process calibrated machine model behind
    :func:`predict_op_us`.

    ``device_peaks()`` are *nominal* datasheet numbers (and pure
    placeholders on CPU) — honest MFU denominators, hopeless µs
    predictors. Instead measure, once per process: the per-call dispatch
    floor (tiny elementwise op), effective FLOP/s (reference 256³
    matmul) and effective memory bandwidth (large strided elementwise
    op). Predicted time is then ``floor + max(flops/eff_flops,
    bytes/eff_bw)`` — the roofline shape with empirical ceilings.
    Cached; ~tens of ms to (re)build.
    """
    with _calib_lock:
        if _CALIB and not refresh:
            return dict(_CALIB)
    # references are AOT-compiled and timed with the SAME warmup/best-of
    # discipline the replay uses for real ops: replay calls AOT
    # executables (no jit C++ dispatch fastpath), so the floor must be
    # an AOT call's floor — a jit-wrapper floor is several times lower
    # and would skew every small-op prediction
    def _aot(fn, *args):
        return jax.jit(fn).lower(*args).compile()

    tiny = jnp.ones((8,), jnp.float32)
    floor_us = _best_of_us(_aot(lambda x: x + 1.0, tiny), tiny,
                           warmup=2, repeats=3)
    # AOT argument processing is per-argument python work — charge
    # multi-input ops for it (layer_norm's 3 tensors cost real µs on
    # the dispatch floor even when their math is trivial)
    many = [tiny] * 8

    def _sum8(*xs):
        y = xs[0]
        for x in xs[1:]:
            y = y + x
        return y

    sum8_us = _best_of_us(_aot(_sum8, *many), *many, warmup=2, repeats=3)
    per_arg_us = max((sum8_us - floor_us) / 7.0, 0.0)
    n = 256
    a = jnp.ones((n, n), jnp.float32)
    mm_us = _best_of_us(_aot(lambda x, y: x @ y, a, a), a, a)
    mm_flops = 2.0 * n * n * n
    eff_flops = mm_flops / max((mm_us - floor_us) * 1e-6, 1e-9)
    # convolutions run a different code path with a much lower achieved
    # FLOP/s ceiling than the contraction reference (drastically so on
    # the CPU runner) — calibrate the conv family separately
    img = jnp.ones((4, 8, 16, 16), jnp.float32)
    ker = jnp.ones((8, 8, 3, 3), jnp.float32)

    def _conv(x, k):
        return jax.lax.conv_general_dilated(x, k, (1, 1), "VALID")

    conv_us = _best_of_us(_aot(_conv, img, ker), img, ker)
    conv_flops = 2.0 * 4 * 8 * 14 * 14 * 8 * 3 * 3
    eff_conv = conv_flops / max((conv_us - floor_us) * 1e-6, 1e-9)
    big = jnp.ones((4 << 20,), jnp.float32)  # 16 MiB
    bw_us = _best_of_us(_aot(lambda x: x * 1.5 + 2.0, big), big)
    bw_bytes = 2.0 * big.size * 4  # read + write
    eff_bw = bw_bytes / max((bw_us - floor_us) * 1e-6, 1e-9)
    calib = {
        "dispatch_floor_us": round(floor_us, 3),
        "per_arg_us": round(per_arg_us, 3),
        "eff_flops_per_s": float(eff_flops),
        "eff_conv_flops_per_s": float(eff_conv),
        "eff_bytes_per_s": float(eff_bw),
    }
    with _calib_lock:
        _CALIB.clear()
        _CALIB.update(calib)
    return dict(calib)


def predict_op_us(flops, bytes_accessed, op_type=None, n_args=1) -> float:
    """Calibrated-roofline predicted kernel time in µs (conv-family ops
    use the conv FLOP/s ceiling; extra arguments pay the per-arg
    dispatch charge)."""
    c = calibration()
    ceiling = c["eff_conv_flops_per_s"] if "conv" in str(op_type or "") \
        else c["eff_flops_per_s"]
    roof_s = max(
        (float(flops) / ceiling) if flops else 0.0,
        (float(bytes_accessed) / c["eff_bytes_per_s"]) if bytes_accessed
        else 0.0)
    return (c["dispatch_floor_us"]
            + c.get("per_arg_us", 0.0) * max(int(n_args) - 1, 0)
            + roof_s * 1e6)


def _symmetric_ratio(predicted, measured):
    """time_accuracy: predicted/measured (1.0 = perfect), None if either
    side is missing — the plan_accuracy convention."""
    if not predicted or not measured:
        return None
    return float(predicted) / float(measured)


# ---------------------------------------------------------------------------
# Leg 2b: the replay profiler
# ---------------------------------------------------------------------------


def _flag_int(name, fallback):
    from ..flags import flag

    try:
        return int(str(flag(name)).strip() or fallback)
    except (KeyError, ValueError):
        return fallback


def profile_program(program, feed=None, fetch_list=None, *, scope=None,
                    name=None, warmup=None, repeats=None, with_trace=True,
                    record=True) -> dict:
    """Replay ``program``'s top block op-by-op through the REGISTRY
    kernels and measure each op in isolation.

    Every op gets its own ``jax.jit`` whose callable is *named with the
    op's stamp* (so the jax.profiler trace taken around the timed pass
    self-identifies per op even on CPU), AOT-compiled once, then timed
    warmup + best-of-N behind ``block_until_ready``. Inputs come from
    ``feed`` plus the scope's persistables — run the program through the
    Executor once first so parameters/constants are materialized.

    Control-flow (`cond/scan/while`) and ``grad::`` ops are not
    replayable in isolation; they are reported with ``replayed: False``
    and their downstream consumers degrade the same way — replay targets
    inference-shaped programs (the /profilez contract; train steps get
    trace attribution instead).

    Returns the profile dict (also stored for ``/profilez`` under
    ``name``). When ``record`` is set, the time-accuracy closure lands
    on the latest executor CostRecord like memplan's ``plan_accuracy``.
    """
    from ..ops.registry import EAGER_ONLY_OPS, has_op, kernel
    from ..static import executor as _exec
    from . import cost_model as _cost
    from . import registry as _registry

    scope = scope or _exec.global_scope()
    warmup = _flag_int("opprof_warmup", 1) if warmup is None else int(warmup)
    repeats = _flag_int("opprof_repeats", 3) if repeats is None \
        else int(repeats)
    block = program.global_block()
    name = name or f"program{getattr(program, '_identity_token', id(program))}"

    env = {}
    for n in scope.var_names():
        env[n] = scope.get(n)
    for k, v in (feed or {}).items():
        env[k] = v if isinstance(v, jax.Array) else jnp.asarray(np.asarray(v))

    peaks = _cost.device_peaks()
    base_key = jax.random.PRNGKey(0)
    rows, runnable = [], []
    for i, op in enumerate(block.ops):
        stamp = op_scope_name(op.type, block.idx, i)
        row = {"scope": stamp, "op_type": op.type, "block": block.idx,
               "index": i, "replayed": False, "time_us": None}
        rows.append(row)
        if op.type in _exec._BLOCK_OPS or op.type.startswith("grad::"):
            row["reason"] = "control-flow/grad op (not replayable)"
            continue
        if not has_op(op.type):
            row["reason"] = "no registry kernel"
            continue
        if op.type in EAGER_ONLY_OPS:
            row["reason"] = "eager-only kernel (unjittable)"
            continue
        in_names = _exec.op_in_names(op)
        missing = [n for n in in_names if n not in env]
        if missing:
            row["reason"] = f"missing inputs {missing[:3]}"
            continue
        f_attrs = {k: v for k, v in op.attrs.items()
                   if not k.startswith("__")}
        if op.attrs.get("__rng__"):
            f_attrs["key"] = _exec._op_key(base_key, op)
        fn_k = kernel(op.type)

        def _call(*arrays, _fn=fn_k, _attrs=f_attrs):
            return _fn(*arrays, **_attrs)

        # the stamp IS the callable name: trace events become
        # PjitFunction(<stamp>) and attribute_trace folds them with zero
        # backend cooperation (CPU has no HLO-metadata device rows)
        _call.__name__ = stamp
        _call.__qualname__ = stamp
        arrays = [env[n] for n in in_names]
        try:
            lowered = jax.jit(_call).lower(*arrays)
            compiled = lowered.compile()
            out = compiled(*arrays)
        except Exception as e:  # keep profiling the rest of the program
            row["reason"] = f"compile/run failed: {e}"
            continue
        results = list(out) if isinstance(out, (tuple, list)) else [out]
        for out_name, value in zip(_exec.op_out_names(op), results):
            if out_name and value is not None:
                env[out_name] = value
        fb = _cost.flops_and_bytes(compiled) or (0, 0)
        row["flops"], row["bytes"] = int(fb[0] or 0), int(fb[1] or 0)
        row["n_args"] = len(arrays)
        runnable.append((row, compiled, arrays))

    # timed pass, optionally under a jax.profiler trace so one profiling
    # run also yields the attribution table (+ coverage) from real trace
    # events. Compilation happened above — the trace sees steady state.
    trace_dir, tracing = None, False
    if with_trace:
        trace_dir = tempfile.mkdtemp(prefix="opprof_trace_")
        try:
            jax.profiler.start_trace(trace_dir)
            tracing = True
        except Exception:
            tracing = False  # an outer trace is live: skip, never break it
    try:
        for row, compiled, arrays in runnable:
            row["time_us"] = round(
                _best_of_us(compiled, *arrays, warmup=warmup,
                            repeats=repeats), 3)
            row["replayed"] = True
    finally:
        if tracing:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass

    total_us = sum(r["time_us"] for r in rows if r["replayed"])
    pred_total = 0.0
    hist = _registry.histogram(
        "opprof/op_time_ms",
        buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                 50.0, 100.0, 500.0),
        help="replay-measured per-op device time (opprof)")
    for row in rows:
        if not row["replayed"]:
            continue
        us = row["time_us"]
        row["share"] = round(us / total_us, 4) if total_us else 0.0
        secs = max(us * 1e-6, 1e-12)
        row["flops_per_s"] = row["flops"] / secs
        row["mfu"] = round(_cost.mfu(row["flops_per_s"], peaks), 6)
        row["roofline"] = _cost.roofline_class(row["flops"], row["bytes"],
                                               peaks)
        row["predicted_us"] = round(
            predict_op_us(row["flops"], row["bytes"], row["op_type"],
                          n_args=row.get("n_args", 1)), 3)
        pred_total += row["predicted_us"]
        row["time_accuracy"] = ta = _symmetric_ratio(row["predicted_us"], us)
        if ta is not None:
            row["time_accuracy"] = round(ta, 4)
        hist.labels(op_type=row["op_type"]).observe(us / 1e3)

    attribution = attribute_trace(trace_dir) if trace_dir else {
        "status": "no-data", "coverage": None, "ops": []}
    accuracy = _symmetric_ratio(pred_total, total_us)
    profile = {
        "name": name,
        "n_ops": len(rows),
        "replayed_ops": sum(1 for r in rows if r["replayed"]),
        "total_us": round(total_us, 3),
        "predicted_total_us": round(pred_total, 3),
        "time_accuracy": round(accuracy, 4) if accuracy else None,
        "coverage": attribution.get("coverage"),
        "warmup": warmup,
        "repeats": repeats,
        "ops": rows,
        "attribution": attribution,
        "calibration": calibration(),
        "created_t": time.time(),
    }
    record_profile(profile)
    if record:
        # the /costz closure: predicted vs measured per-op time on the
        # program's CostRecord, the exact shape plan_accuracy landed as
        rec = _cost.latest_record("executor")
        if rec is not None and accuracy is not None:
            rec.predicted_op_us = round(pred_total, 3)
            rec.measured_op_us = round(total_us, 3)
            rec.time_accuracy = round(accuracy, 4)
    return profile


# ---------------------------------------------------------------------------
# the profile store (+ /statz /profilez /metrics chrome surfaces)
# ---------------------------------------------------------------------------

_PROFILES: dict = {}  # name -> profile, insertion-ordered
_profiles_lock = threading.Lock()
_STORE_CAP = 16


def record_profile(profile):
    with _profiles_lock:
        _PROFILES.pop(profile["name"], None)
        _PROFILES[profile["name"]] = profile
        while len(_PROFILES) > _STORE_CAP:
            _PROFILES.pop(next(iter(_PROFILES)))


def profiles() -> list:
    with _profiles_lock:
        return list(_PROFILES)


def latest_profile(name=None):
    with _profiles_lock:
        if name is not None:
            return _PROFILES.get(name)
        return next(reversed(_PROFILES.values()), None) if _PROFILES \
            else None


def reset_profiles():
    with _profiles_lock:
        _PROFILES.clear()


def top_ops(k=None) -> list:
    """Top-K replayed ops by measured device time from the most recent
    profile — the /statz table."""
    k = _flag_int("opprof_topk", 10) if k is None else int(k)
    prof = latest_profile()
    if prof is None:
        return []
    rows = sorted((r for r in prof["ops"] if r.get("replayed")),
                  key=lambda r: -(r["time_us"] or 0.0))
    return [{"scope": r["scope"], "op_type": r["op_type"],
             "time_us": r["time_us"], "share": r.get("share", 0.0),
             "mfu": r.get("mfu"), "roofline": r.get("roofline")}
            for r in rows[:max(k, 0)]]


def opprof_stats() -> dict:
    """The /statz opprof block: stored programs + top-K op table."""
    prof = latest_profile()
    return {
        "programs": profiles(),
        "latest": None if prof is None else {
            "name": prof["name"], "total_us": prof["total_us"],
            "time_accuracy": prof["time_accuracy"],
            "coverage": prof["coverage"],
        },
        "top_ops": top_ops(),
    }


def profilez_payload(query=None):
    """``(status, payload)`` for GET /profilez.

    ``?program=<name>`` selects a stored profile (404 when unknown),
    ``?topk=N`` trims the op table. With nothing profiled yet the
    payload is a clean ``status="no-data"`` hint, not an error.
    """
    query = query or {}
    with _profiles_lock:
        names = list(_PROFILES)
    if not names:
        return 200, {
            "status": "no-data", "programs": [],
            "hint": "run paddle_tpu.monitor.opprof.profile_program(...) "
                    "(or tools/opprof_smoke.py) to populate"}
    want = query.get("program")
    if want is not None and latest_profile(want) is None:
        return 404, {"status": "unknown-program", "program": want,
                     "programs": names}
    prof = latest_profile(want)
    try:
        topk = int(query.get("topk", _flag_int("opprof_topk", 10)))
    except (TypeError, ValueError):
        topk = _flag_int("opprof_topk", 10)
    ops = sorted((r for r in prof["ops"] if r.get("replayed")),
                 key=lambda r: -(r["time_us"] or 0.0))[:max(topk, 0)]
    skipped = [{"scope": r["scope"], "reason": r.get("reason", "")}
               for r in prof["ops"] if not r.get("replayed")]
    attribution = dict(prof["attribution"])
    attribution["ops"] = attribution.get("ops", [])[:max(topk, 0)]
    return 200, {
        "status": "ok",
        "programs": names,
        "program": prof["name"],
        "summary": {
            "n_ops": prof["n_ops"],
            "replayed_ops": prof["replayed_ops"],
            "total_us": prof["total_us"],
            "predicted_total_us": prof["predicted_total_us"],
            "time_accuracy": prof["time_accuracy"],
            "time_accuracy_envelope": TIME_ACCURACY_ENVELOPE,
            "coverage": prof["coverage"],
            "warmup": prof["warmup"],
            "repeats": prof["repeats"],
        },
        "ops": ops,
        "skipped": skipped,
        "attribution": attribution,
        "calibration": prof["calibration"],
    }


def chrome_events() -> list:
    """Per-op replay tracks for ``export_merged_chrome_trace``: one
    synthetic thread per stored profile, ops laid end-to-end at their
    measured durations (relative layout — replay times ops in isolation,
    so only durations, shares and order are meaningful)."""
    with _profiles_lock:
        profs = list(_PROFILES.values())
    if not profs:
        return []
    pid = os.getpid()
    events = []
    for ti, prof in enumerate(profs):
        tid = f"opprof:{prof['name']}"
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid,
                       "args": {"name": f"opprof replay [{prof['name']}]"}})
        t = 0.0
        for row in prof["ops"]:
            if not row.get("replayed"):
                continue
            events.append({
                "name": row["scope"], "ph": "X", "pid": pid, "tid": tid,
                "ts": t, "dur": row["time_us"], "cat": "opprof",
                "args": {"mfu": row.get("mfu"),
                         "roofline": row.get("roofline"),
                         "predicted_us": row.get("predicted_us")},
            })
            t += row["time_us"]
    return events
