"""Live debug endpoint: a stdlib-http.server window into a running job.

The reference's production story leaned on VLOG levels and gperftools
ports; serving-scale TPU jobs (Gemma-on-Cloud-TPU ops runbooks) expect a
/statusz-style HTTP surface instead. This one serves, on
``127.0.0.1:<FLAGS_debug_port + rank>``:

- ``/healthz``       — JSON liveness: pid/rank/uptime, progress-clock age
  (the hang watchdog's input), watchdog state, recorder depth.
- ``/metrics``       — the Prometheus text dump (monitor.export), i.e. a
  scrape target for free.
- ``/flightrecorder``— the live flight-recorder snapshot (ring events,
  per-group collective tails, thread stacks, flags) as JSON.
- ``/threadz``       — every Python thread's stack, plain text.
- ``/flagz``         — the FLAGS registry (core.globals() view) as JSON.
- ``/costz``         — per-program XLA cost sheets (FLOPs, bytes, HBM
  footprint) + the device peak table (monitor.cost_model).
- ``/clusterz``      — every rank's published metric snapshot (step time,
  MFU, input-wait) + straggler verdicts (monitor.cluster).
- ``/tracez``        — the tail-sampled trace store (monitor.tracing):
  retained-trace list, one span tree by ``?id=``, chrome-trace view via
  ``?id=...&format=chrome``.
- ``/metricz``       — alias of ``/metrics`` matching the serving
  servers' scrape route (one target path fleet-wide).
- ``/sloz``          — error-budget burn per installed SLO
  (monitor.slo): fast/slow window burn rates, alert state.
- ``/goodputz``      — the lifetime training goodput ledger
  (monitor.goodput): exclusive phase seconds, goodput ratio,
  lost-work/resume accounting, conservation check.
- ``/profilez``      — per-op device-time profiles (monitor.opprof):
  replay-measured op table with MFU/roofline per op, trace-attribution
  coverage, time-accuracy closure; ``?program=``/``?topk=`` views.

Loopback-bound on purpose: the debug surface exposes run internals, so
reaching it from outside the host goes through whatever port-forwarding
the deployment already trusts (same stance as the PS trust model).
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..flags import flag
from . import flight_recorder as _flight

__all__ = ["DebugServer", "start_debug_server", "stop_debug_server",
           "debug_server", "healthz"]

_t0 = time.monotonic()


def healthz() -> dict:
    """The /healthz payload (also importable for tests/tools)."""
    rec = _flight.get_recorder()
    wd = _flight.watchdog()
    return {
        "ok": True,
        "pid": os.getpid(),
        "rank": _flight._safe_rank(),
        "world": _flight._safe_world(),
        "uptime_s": round(time.monotonic() - _t0, 3),
        "last_progress_age_s": round(_flight.last_progress_age_s(), 3),
        "last_progress": _flight.last_progress_what(),
        "flight_recorder": {
            "enabled": rec.enabled,
            # same semantics as the dump's field of this name: total ever
            # recorded, NOT current ring occupancy
            "events_recorded": rec.total_recorded,
            "events_in_ring": len(rec.events()),
            "capacity": rec.capacity,
        },
        "watchdog": (
            {"alive": wd.alive, "timeout_s": wd.timeout_s,
             "trips": wd.trips, "last_dump": wd.last_dump}
            if wd is not None else None),
    }


def _threadz_text() -> str:
    blocks = []
    for name, frames in sorted(_flight.thread_stacks().items()):
        blocks.append(f"--- thread {name} ---\n" + "\n".join(frames))
    return "\n\n".join(blocks) + "\n"


def _index_text(routes) -> str:
    lines = ["paddle_tpu debugz — live fault-diagnosis endpoint", ""]
    lines += [f"  {r}" for r in sorted(routes)]
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server_version = "ptpu-debugz/1"

    def log_message(self, *args):  # no per-request stderr chatter
        pass

    def _routes(self):
        from . import cluster as _cluster
        from . import cost_model as _cost
        from . import goodput as _goodput
        from . import slo as _slo
        from .export import PROMETHEUS_CONTENT_TYPE, prometheus_text

        return {
            "/healthz": lambda: (
                json.dumps(healthz(), indent=1), "application/json"),
            "/metrics": lambda: (
                prometheus_text(), PROMETHEUS_CONTENT_TYPE),
            # scrape-target alias matching the serving servers' route
            "/metricz": lambda: (
                prometheus_text(), PROMETHEUS_CONTENT_TYPE),
            "/sloz": lambda: (
                json.dumps(_slo.sloz_payload(), indent=1, default=str),
                "application/json"),
            "/goodputz": lambda: (
                json.dumps(_goodput.goodputz_payload(), indent=1,
                           default=str), "application/json"),
            "/flightrecorder": lambda: (
                json.dumps(_flight.get_recorder().snapshot(reason="debugz"),
                           indent=1, default=str), "application/json"),
            "/threadz": lambda: (_threadz_text(), "text/plain"),
            "/flagz": lambda: (
                json.dumps(_flight._safe_flags(), indent=1, default=str),
                "application/json"),
            # hardware-utilization accounting: per-program cost sheets +
            # device peaks, and the rank-aggregated cluster view with
            # straggler verdicts (rank 0 is the natural place to curl it,
            # but any rank collects the same published snapshots)
            "/costz": lambda: (
                json.dumps(_cost.costz_payload(), indent=1, default=str),
                "application/json"),
            "/clusterz": lambda: (
                json.dumps(_cluster.clusterz_payload(), indent=1,
                           default=str), "application/json"),
        }

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path != "/" and path.endswith("/"):
            path = path.rstrip("/")
        routes = self._routes()
        try:
            if path in ("/", "/debugz", "/index"):
                body = _index_text(list(routes) + ["/tracez", "/profilez"])
                ctype, status = "text/plain", 200
            elif path == "/profilez":
                # query-carrying route (?program=, ?topk=): the per-op
                # replay/attribution profiles (monitor.opprof) — 404 for
                # an unknown program name keeps its real status
                from . import opprof as _opprof
                from . import tracing as _tracing

                status, payload = _opprof.profilez_payload(
                    _tracing.parse_query(self.path))
                body = json.dumps(payload, indent=1, default=str)
                ctype = "application/json"
            elif path == "/tracez":
                # query-carrying route (?id=, ?format=chrome): handled
                # outside the zero-arg routes table so the 404 for a
                # sampled-away trace keeps its real status
                from . import tracing as _tracing

                status, payload = _tracing.tracez_payload(
                    _tracing.parse_query(self.path))
                body = json.dumps(payload, indent=1, default=str)
                ctype = "application/json"
            elif path in routes:
                body, ctype = routes[path]()
                status = 200
            else:
                body = f"404: unknown path {path!r}; try {sorted(routes)}\n"
                ctype, status = "text/plain", 404
        except Exception as e:  # a broken handler must not kill the server
            import traceback

            body = (f"500: {type(e).__name__}: {e}\n"
                    + traceback.format_exc())
            ctype, status = "text/plain", 500
        payload = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", f"{ctype}; charset=utf-8")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        try:
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            pass


class DebugServer:
    """Threaded HTTP debug server; ``port=0`` binds an ephemeral port
    (tests / debugz-smoke). Serving happens on a daemon thread, so the
    endpoint stays reachable while the main thread is hung — which is
    precisely when it matters."""

    def __init__(self, port=0, host="127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"ptpu-debugz:{self.port}", daemon=True)
            self._thread.start()
            _flight.record_event("debug_server_start", port=self.port,
                                 host=self.host)
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None
        _flight.record_event("debug_server_stop", port=self.port)


_server = [None]


def debug_server() -> DebugServer | None:
    return _server[0]


def start_debug_server(port=None, host="127.0.0.1") -> DebugServer | None:
    """Start the global debug server (idempotent). ``port=None`` reads
    ``FLAGS_debug_port`` (0 there means disabled → None); an explicit
    ``port=0`` binds an ephemeral port."""
    srv = _server[0]
    if srv is not None:
        return srv
    if port is None:
        port = int(flag("debug_port"))
        if port <= 0:
            return None
    srv = DebugServer(port=port, host=host).start()
    _server[0] = srv
    return srv


def stop_debug_server():
    srv = _server[0]
    if srv is not None:
        srv.stop()
    _server[0] = None
