"""Step-level training monitor.

The reference surfaced per-step health through trainer VLOG lines fed by
the monitor.h stats and the profiler's CostInfo summaries; serving-scale
tuning (BASELINE.md's roofline work, the Gemma TPU fine-tuning/serving
recipe) starts from exactly these numbers: where did the step's wall
time go — compute, input wait, or retrace?

:class:`TrainingMonitor` wraps each step (context manager or
begin/end pair), aggregates a window of steps, and every
``FLAGS_monitor_interval`` steps emits one parseable log line:

    [monitor:train] step=300 step_ms=12.41 examples_per_sec=10312.9
    input_wait_ratio=0.031 plan_cache_hit_rate=1.000
    jit_cache_hit_rate=1.000 compiles=0 hbm_peak_bytes=123456

Every field also lands in the metrics registry (histograms/gauges), so
the Prometheus dump and the periodic line can never disagree.
"""
from __future__ import annotations

import time
import weakref

from .. import profiler
from ..flags import flag
from . import cost_model as _cost
from . import goodput as _goodput
from . import registry as _reg
from . import tracing as _tracing

__all__ = ["TrainingMonitor", "record_input_wait_ms", "active_monitor"]

# most recently constructed monitor (weak: a dropped monitor must not be
# kept alive by telemetry) — the cluster aggregator snapshots it
_active = [None]


def active_monitor():
    """The live TrainingMonitor the cluster metrics snapshot reads
    (latest constructed wins), or None."""
    ref = _active[0]
    return ref() if ref is not None else None


def record_input_wait_ms(ms: float):
    """Account time a consumer spent blocked waiting on input (called by
    the DataLoader/prefetcher wait paths); feeds the monitor's
    input-wait ratio and the goodput ledger's ``input_wait`` phase.

    The canonical series is the COUNTER ``io/input_wait_ms_total``
    (monotone accumulation — the prometheus type the add-only semantics
    always were); the same value still mirrors into the legacy gauge
    ``io/input_wait_ms`` because external readers (kernel smoke, fused-
    kernel tests) fetch that name by kind — a same-name kind migration
    would TypeError at every such site."""
    ms = float(ms)
    _reg.counter("io/input_wait_ms_total").inc(ms)
    # deprecated back-compat alias; remove once nothing reads the gauge
    _reg.gauge("io/input_wait_ms").add(ms)
    led = _goodput.active_ledger()
    if led is not None:
        led.note_phase("input_wait", ms / 1e3)


def _cache_rate(hits, misses):
    total = hits + misses
    return hits / total if total else 1.0


def _fmt_util(v: float) -> str:
    """Utilization ratio for the log line: fixed-point in the normal
    range, scientific below it (a CPU smoke's 4e-5 MFU must not print as
    an indistinguishable 0.0000)."""
    return f"{v:.4f}" if (v == 0.0 or v >= 1e-3) else f"{v:.2e}"


class _StepSpan:
    def __init__(self, mon, examples, global_step=None):
        self._mon = mon
        self._examples = examples
        self._global_step = global_step

    def __enter__(self):
        self._mon.step_begin()
        return self._mon

    def __exit__(self, *exc):
        if exc[0] is None:
            self._mon.step_end(examples=self._examples,
                               global_step=self._global_step)
        else:
            # a failed step must not pollute the aggregates OR leave the
            # begun-state armed (a stale _t_begin would let a later bare
            # step_end() "succeed" with a bogus wall time)
            self._mon.step_abort()
        return False


class TrainingMonitor:
    """Aggregate per-step wall time, examples/sec, input-wait ratio,
    executor cache hit rates, compile events, and the HBM watermark.

    Usage::

        mon = monitor.TrainingMonitor("train")
        for batch in loader:
            with mon.step(examples=len(batch)):
                train_step(batch)

    ``interval`` defaults to ``FLAGS_monitor_interval`` read at each
    step-end (so set_flags takes effect mid-run); 0 silences the line
    but aggregation continues.
    """

    def __init__(self, name="train", interval=None, devices=None,
                 log_fn=None):
        self.name = name
        self._interval = interval
        self._devices = devices
        self._log_fn = log_fn or print
        self.step_count = 0
        self.last_line = None
        self._step_ms = _reg.histogram(f"monitor/{name}/step_ms")
        self._examples = _reg.counter(f"monitor/{name}/examples")
        self._steps = _reg.counter(f"monitor/{name}/steps")
        # lifetime goodput ledger: one env var (FLAGS_goodput_dir) turns
        # it on for any monitored run; None when the flag is unset
        _goodput.maybe_start_from_flags()
        # jax compile events (registry-fed by the jax.monitoring
        # listeners) expose retrace storms in the periodic line
        _reg.install_jax_listeners()
        self._t_begin = None
        self._span = None
        self._tscope = None
        self._closed = False
        self._reset_window()
        _active[0] = weakref.ref(self)

    # -- window bookkeeping -------------------------------------------------

    def _counter_basis(self):
        c = profiler.counters()
        return {
            "plan_hit": c.get("executor::plan_cache_hit", 0),
            "plan_miss": c.get("executor::plan_cache_miss", 0),
            "jit_hit": c.get("executor::jit_cache_hit", 0),
            "jit_miss": c.get("executor::jit_cache_miss", 0),
            "compiles": self._compile_events(),
            "input_wait_ms": _reg.counter("io/input_wait_ms_total").value,
            # executed-work ledger (cost_model.note_run): differencing it
            # over the window gives the window's FLOPs/bytes for MFU
            "flops": _reg.counter("cost/executed_flops").value,
            "bytes": _reg.counter("cost/executed_bytes").value,
        }

    @staticmethod
    def _compile_events():
        total = 0
        for name, m in _reg.all_metrics().items():
            if name.startswith("jax/") and "compile" in name \
                    and m.kind == "counter":
                total += m.value
        return total

    def _reset_window(self):
        self._win_t0 = time.perf_counter()
        self._win_steps = 0
        self._win_examples = 0
        self._win_step_ms = 0.0
        self._win_basis = self._counter_basis()

    # -- step API -----------------------------------------------------------

    def step(self, examples=None, global_step=None):
        """Context manager wrapping one training step. ``global_step``
        (the run's global step index, surviving restarts) drives the
        goodput ledger's lost-work attribution on resume."""
        return _StepSpan(self, examples, global_step=global_step)

    def step_begin(self):
        led = _goodput.active_ledger()
        if led is not None:
            led.step_begin()
        self._span = profiler.RecordEvent(
            f"monitor::{self.name}::step").begin()
        # step-scoped trace: everything the step touches (executor runs,
        # flight-recorder events, a NaN or watchdog dump) can cite this
        # trace_id; retention rides the same tail sampler as serving
        # (aborted steps are flagged errored and always kept)
        self._tscope = _tracing.start_trace(
            f"train::{self.name}::step", step=self.step_count + 1)
        self._tscope.__enter__()
        self._t_begin = time.perf_counter()
        return self

    def _trace_end(self, error=None):
        ts, self._tscope = self._tscope, None
        if ts is None:
            return
        if error is not None and ts.span:
            ts.span.set_error(error)
        ts.__exit__(None, None, None)

    def step_abort(self):
        """Discard an in-flight step (the body raised): drop its span,
        disarm the begin-state, and count it separately. The step's wall
        time does NOT vanish — it lands in the goodput ledger's
        ``aborted`` badput, and a flight event names the step, so an
        abort storm is visible in both the lifetime accounting and the
        post-mortem dump."""
        dt_ms = (0.0 if self._t_begin is None
                 else (time.perf_counter() - self._t_begin) * 1e3)
        self._t_begin = None
        if self._span is not None:
            self._span = None  # never end()ed: the span is not recorded
        self._trace_end(error="step aborted")
        _reg.counter(f"monitor/{self.name}/aborted_steps").inc()
        _reg.counter(f"monitor/{self.name}/aborted_step_ms").inc(dt_ms)
        led = _goodput.active_ledger()
        if led is not None:
            led.step_abort()
        from . import flight_recorder as _flight

        _flight.record_event(
            "step_aborted", monitor=self.name,
            step=self.step_count + 1, ms=round(dt_ms, 3))

    def step_end(self, examples=None, global_step=None):
        """Close the step; returns the log line if this step emitted one
        (None otherwise)."""
        if self._t_begin is None:
            raise RuntimeError("step_end() without step_begin()")
        dt_ms = (time.perf_counter() - self._t_begin) * 1e3
        self._t_begin = None
        if self._span is not None:
            self._span.end()
            self._span = None
        self._trace_end()
        led = _goodput.active_ledger()
        if led is not None:
            # global_step stays None when the caller doesn't thread one:
            # lost-work attribution needs a restart-surviving index, and
            # guessing from the per-life step_count would misfile fresh
            # post-resume steps as recomputation
            led.step_commit(global_step=global_step)
        self.step_count += 1
        self._steps.inc()
        self._step_ms.observe(dt_ms)
        self._win_steps += 1
        self._win_step_ms += dt_ms
        if examples:
            self._examples.inc(int(examples))
            self._win_examples += int(examples)
        interval = (self._interval if self._interval is not None
                    else flag("monitor_interval"))
        if interval and self.step_count % interval == 0:
            return self._emit()
        return None

    # -- reporting ----------------------------------------------------------

    def snapshot(self) -> dict:
        """Current-window aggregates as plain data (the line's fields)."""
        now = time.perf_counter()
        wall_s = max(now - self._win_t0, 1e-9)
        basis = self._win_basis
        cur = self._counter_basis()
        input_wait_ms = cur["input_wait_ms"] - basis["input_wait_ms"]
        steps = self._win_steps
        flops_d = cur["flops"] - basis["flops"]
        bytes_d = cur["bytes"] - basis["bytes"]
        peaks = _cost.device_peaks()
        return {
            "step": self.step_count,
            "step_ms": (self._win_step_ms / steps) if steps else 0.0,
            "steps_per_sec": steps / wall_s,
            "examples_per_sec": self._win_examples / wall_s,
            "input_wait_ratio": min(input_wait_ms / (wall_s * 1e3), 1.0),
            "plan_cache_hit_rate": _cache_rate(
                cur["plan_hit"] - basis["plan_hit"],
                cur["plan_miss"] - basis["plan_miss"]),
            "jit_cache_hit_rate": _cache_rate(
                cur["jit_hit"] - basis["jit_hit"],
                cur["jit_miss"] - basis["jit_miss"]),
            "compiles": cur["compiles"] - basis["compiles"],
            "hbm_peak_bytes": _reg.hbm_watermark_bytes(self._devices),
            # hardware-utilization accounting (cost_model): window FLOPs/
            # bytes over wall time, normalized by the chip's peaks — 0.0
            # until a compile was cost-captured (nothing to claim yet)
            "mfu": _cost.mfu(flops_d / wall_s, peaks),
            "hbm_bw_util": _cost.hbm_bw_util(bytes_d / wall_s, peaks),
            "roofline": _cost.roofline_class(flops_d, bytes_d, peaks),
        }

    def _emit(self):
        s = self.snapshot()
        _reg.gauge(f"monitor/{self.name}/examples_per_sec").set(
            s["examples_per_sec"])
        _reg.gauge(f"monitor/{self.name}/input_wait_ratio").set(
            s["input_wait_ratio"])
        _reg.gauge(f"monitor/{self.name}/mfu").set(s["mfu"])
        _reg.gauge(f"monitor/{self.name}/hbm_bw_util").set(
            s["hbm_bw_util"])
        line = (
            f"[monitor:{self.name}] step={s['step']} "
            f"step_ms={s['step_ms']:.2f} "
            f"examples_per_sec={s['examples_per_sec']:.1f} "
            f"input_wait_ratio={s['input_wait_ratio']:.3f} "
            f"plan_cache_hit_rate={s['plan_cache_hit_rate']:.3f} "
            f"jit_cache_hit_rate={s['jit_cache_hit_rate']:.3f} "
            f"compiles={s['compiles']} "
            f"hbm_peak_bytes={s['hbm_peak_bytes']} "
            f"mfu={_fmt_util(s['mfu'])} "
            f"hbm_bw_util={_fmt_util(s['hbm_bw_util'])} "
            f"roofline={s['roofline']}"
        )
        self.last_line = line
        self._log_fn(line)
        # the lifetime ledger reports on the same cadence: one window
        # line (rates) + one goodput line (where the wall time went)
        led = _goodput.active_ledger()
        if led is not None:
            led.flush_metrics()
            led.emit_line(self._log_fn)
        self._reset_window()
        return line

    def close(self):
        """Flush a final partial-window line and detach (idempotent).

        A run shorter than ``FLAGS_monitor_interval`` never reaches an
        emit boundary — without this flush it would end silently, which
        for a smoke run is exactly when the line matters most. Interval 0
        still means silent (the documented off switch); an in-flight step
        (close inside an exception unwind) is aborted, not counted.
        Returns the emitted line (None when nothing was flushed)."""
        if self._closed:
            return None
        self._closed = True
        # detach: a closed monitor must stop feeding cluster snapshots
        # (a later evaluate()'s executed work would silently accrue to
        # this dead window otherwise)
        ref = _active[0]
        if ref is not None and ref() is self:
            _active[0] = None
        if self._t_begin is not None:
            self.step_abort()
        interval = (self._interval if self._interval is not None
                    else flag("monitor_interval"))
        line = self._emit() if (self._win_steps and interval) else None
        # final ledger sync even when no window line flushed: the last
        # partial window's seconds must not be lost on a short run
        led = _goodput.active_ledger()
        if led is not None:
            led.close()
        return line
