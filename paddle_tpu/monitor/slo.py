"""Error-budget SLOs: declarative objectives + multi-window burn rate.

The serving metrics (PR 6+) say what the fleet IS doing; nothing in the
stack says what it SHOULD be doing. This module adds the objective
layer: an :class:`SLO` declares a target over one metric selector
("99% of predict requests under 250ms over 1h"), and the
:class:`SLOEngine` samples the live registry, turning the good/total
deltas into Google-SRE-style multi-window burn rates — how many times
faster than sustainable the error budget is being spent.

Two windows, both over the alert threshold, page: the slow window
(``window_s``, canonically 1h) proves the burn is sustained, the fast
window (``window_s/12``, canonically 5m) proves it is still happening —
one window alone either flaps on blips or keeps alerting long after
recovery. The default threshold 14.4 is the SRE-workbook convention: a
14.4x burn exhausts a 30-day budget in ~2 days.

Selectors are label-aware (``serving/e2e_ms{kind=predict}``): labels
subset-match the family's labeled children (registry ``labels()``
series), so one objective can cover one tenant, one kind, or the bare
aggregate. Latency objectives count good events from the cumulative
buckets (interpolating inside the threshold's bucket — exact at bucket
bounds); error objectives ratio two counters.

Fleet wiring: every serving entrypoint calls :func:`install_from_flags`
(``FLAGS_slo_objectives``), ``/sloz`` serves :func:`sloz_payload` on
both server kinds + router + debug server, alert transitions record a
``slo_burn`` flight event, and :func:`current_burn` feeds
``FleetSignals.slo_burn`` so the autoscaler reacts to objective
violation, not just queue depth.
"""
from __future__ import annotations

import re
import threading
import time
from collections import deque

from ..errors import InvalidArgumentError
from . import registry as _reg

__all__ = [
    "SLO", "SLOEngine",
    "parse_selector", "parse_objective",
    "engine", "reset_engine",
    "install_slo", "install_from_flags",
    "sloz_payload", "current_burn",
]


_SELECTOR_RE = re.compile(r"^\s*([^{}\s]+)\s*(?:\{(.*)\})?\s*$")


def parse_selector(selector):
    """``metric`` or ``metric{k=v,k2="v2"}`` -> (metric, labels dict).

    Labels subset-match a family's labeled series: an empty dict selects
    the bare parent (the aggregate over labels for counters/histograms).
    """
    m = _SELECTOR_RE.match(str(selector))
    if not m:
        raise InvalidArgumentError(
            f"bad SLO selector {selector!r}: expected "
            "metric or metric{k=v,...}")
    name, body = m.group(1), m.group(2)
    labels = {}
    for part in (body or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise InvalidArgumentError(
                f"bad label match {part!r} in selector {selector!r} "
                "(expected k=v)")
        k, v = part.split("=", 1)
        labels[k.strip()] = v.strip().strip('"')
    return name, labels


class SLO:
    """One declarative objective over a metric selector.

    Exactly one of ``threshold_ms`` (latency mode: good = observations
    at or under the threshold, total = histogram count) or
    ``error_ratio`` (error mode: ``selector`` names the BAD-events
    counter, ``error_ratio`` is the selector of the total counter; good
    = total - bad) must be given. ``target`` is the good fraction the
    objective promises (budget = 1 - target); ``window_s`` is the slow
    burn window, with the fast window at ``max(60, window_s / 12)`` —
    the canonical 1h/5m pairing at the default 3600.
    """

    def __init__(self, name, selector, threshold_ms=None, error_ratio=None,
                 target=0.999, window_s=3600.0, alert_burn=None):
        if (threshold_ms is None) == (error_ratio is None):
            raise InvalidArgumentError(
                f"SLO {name!r}: exactly one of threshold_ms / "
                "error_ratio required")
        target = float(target)
        if not 0.0 < target < 1.0:
            raise InvalidArgumentError(
                f"SLO {name!r}: target must be in (0, 1), got {target}")
        self.name = str(name)
        self.selector = str(selector)
        self.metric, self.labels = parse_selector(selector)
        self.threshold_ms = (None if threshold_ms is None
                             else float(threshold_ms))
        self.total_selector = (None if error_ratio is None
                               else str(error_ratio))
        if self.total_selector is not None:
            self.total_metric, self.total_labels = parse_selector(
                self.total_selector)
        self.target = target
        self.window_s = float(window_s)
        if self.window_s <= 0:
            raise InvalidArgumentError(
                f"SLO {name!r}: window_s must be > 0")
        self.fast_window_s = max(60.0, self.window_s / 12.0)
        # per-objective override of FLAGS_slo_burn_alert (None = flag)
        self.alert_burn = (None if alert_burn is None
                           else float(alert_burn))

    @property
    def mode(self) -> str:
        return "latency" if self.threshold_ms is not None else "error"

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def describe(self) -> dict:
        out = {"name": self.name, "selector": self.selector,
               "mode": self.mode, "target": self.target,
               "window_s": self.window_s,
               "fast_window_s": self.fast_window_s}
        if self.mode == "latency":
            out["threshold_ms"] = self.threshold_ms
        else:
            out["total_selector"] = self.total_selector
        return out


def parse_objective(entry) -> SLO:
    """One FLAGS_slo_objectives entry -> :class:`SLO`.

    Grammar: ``name|selector|field=value|...`` with fields
    ``threshold_ms``, ``error_ratio``, ``target``, ``window_s``,
    ``alert_burn`` — e.g.
    ``predict-p99|serving/e2e_ms{kind=predict}|threshold_ms=250|target=0.99``.
    """
    parts = [p.strip() for p in str(entry).split("|")]
    if len(parts) < 3:
        raise InvalidArgumentError(
            f"bad SLO objective {entry!r}: expected "
            "name|selector|field=value[|...]")
    kwargs = {}
    for field in parts[2:]:
        if "=" not in field:
            raise InvalidArgumentError(
                f"bad SLO field {field!r} in {entry!r} (expected k=v)")
        k, v = (s.strip() for s in field.split("=", 1))
        if k in ("threshold_ms", "target", "window_s", "alert_burn"):
            kwargs[k] = float(v)
        elif k == "error_ratio":
            kwargs[k] = v
        else:
            raise InvalidArgumentError(
                f"unknown SLO field {k!r} in {entry!r} (have: "
                "threshold_ms, error_ratio, target, window_s, "
                "alert_burn)")
    return SLO(parts[0], parts[1], **kwargs)


# -- good/total measurement ---------------------------------------------------

def _matching_snaps(snap, labels):
    """Sub-snapshots of ``snap`` whose labels contain every selector
    pair (subset match); the parent itself when the selector is bare."""
    if not labels:
        return [snap]
    out = []
    for sub in (snap.get("series") or {}).values():
        sl = sub.get("labels") or {}
        if all(sl.get(k) == v for k, v in labels.items()):
            out.append(sub)
    return out


def _good_total_latency(snaps, threshold_ms):
    """(good, total) events across histogram snapshots: good = count of
    observations <= threshold_ms from the cumulative buckets, linearly
    interpolated inside the bucket the threshold falls in (exact when
    the threshold sits on a bucket bound — pick thresholds there for
    golden-stable SLOs). +Inf-bucket observations are never good."""
    good = total = 0.0
    for s in snaps:
        total += s["count"]
        lo = 0.0
        for bound, c in zip(s["bounds"], s["buckets"]):
            if threshold_ms >= bound:
                good += c
                lo = bound
                continue
            if threshold_ms > lo and c:
                good += c * (threshold_ms - lo) / (bound - lo)
            break
    return good, total


def _counter_value(metric, labels):
    m = _reg.all_metrics().get(metric)
    if m is None:
        return 0.0
    snap = m.snapshot()
    if "value" not in snap:
        return 0.0
    if not labels:
        return float(snap["value"])
    return float(sum(s.get("value", 0.0)
                     for s in _matching_snaps(snap, labels)))


def _measure(slo: SLO):
    """Current cumulative (good, total) for one objective; (0, 0) when
    the metric does not exist yet (a backend that has not served)."""
    if slo.mode == "latency":
        m = _reg.all_metrics().get(slo.metric)
        if m is None or m.kind != "histogram":
            return 0.0, 0.0
        return _good_total_latency(
            _matching_snaps(m.snapshot(), slo.labels), slo.threshold_ms)
    bad = _counter_value(slo.metric, slo.labels)
    total = _counter_value(slo.total_metric, slo.total_labels)
    return max(0.0, total - bad), total


def _alert_threshold(slo: SLO) -> float:
    if slo.alert_burn is not None:
        return slo.alert_burn
    try:
        from ..flags import flag

        return float(flag("slo_burn_alert"))
    except Exception:
        return 14.4


# -- the engine ---------------------------------------------------------------

class _Tracked:
    __slots__ = ("slo", "samples", "alerting")

    def __init__(self, slo):
        self.slo = slo
        # (t, good, total) cumulative samples, oldest first; pruned to
        # one slow window (plus the reference sample at its edge)
        self.samples = deque()
        self.alerting = False


class SLOEngine:
    """Samples good/total for installed objectives and computes
    multi-window burn rates over the sample history.

    ``clock`` is injectable (tests drive deterministic windows by
    passing explicit ``now`` values to :meth:`sample` /
    :meth:`sloz_payload`); production uses time.monotonic via the
    background sampler thread (:meth:`start`).
    """

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._tracked: dict[str, _Tracked] = {}
        self._stop = threading.Event()
        self._thread = None
        self._interval_s = None

    # -- objective management --

    def add(self, slo: SLO) -> SLO:
        """Install (or replace — same-name installs are idempotent so
        entrypoints can re-run install_from_flags) one objective."""
        with self._lock:
            self._tracked[slo.name] = _Tracked(slo)
        return slo

    def remove(self, name):
        with self._lock:
            self._tracked.pop(name, None)

    def objectives(self) -> list:
        with self._lock:
            return [tr.slo for tr in self._tracked.values()]

    # -- sampling + burn math --

    def sample(self, now=None):
        """Take one good/total sample per objective, prune history past
        the slow window, and fire alert-transition events. The sampler
        thread calls this on FLAGS_slo_sample_interval_s; tests call it
        directly with explicit ``now``."""
        now = float(self._clock() if now is None else now)
        with self._lock:
            tracked = list(self._tracked.values())
        for tr in tracked:
            good, total = _measure(tr.slo)
            with self._lock:
                tr.samples.append((now, good, total))
                # keep one sample at/before the slow-window start so
                # the slow delta always has its reference point
                horizon = now - tr.slo.window_s
                while (len(tr.samples) > 2
                       and tr.samples[1][0] <= horizon):
                    tr.samples.popleft()
            self._check_alert(tr, now)

    def _burn(self, tr: _Tracked, window_s: float, now: float):
        """Burn rate over the trailing window: the bad fraction of the
        good/total delta between the newest sample and the reference
        sample at/before the window start, divided by the error budget.
        None until two samples exist; computed over whatever history
        exists when the engine is younger than the window."""
        with self._lock:
            samples = list(tr.samples)
        if len(samples) < 2:
            return None
        cur = samples[-1]
        start = now - window_s
        ref = samples[0]
        for s in samples:
            if s[0] <= start:
                ref = s
            else:
                break
        d_total = cur[2] - ref[2]
        if d_total <= 0:
            return 0.0
        d_bad = max(0.0, d_total - (cur[1] - ref[1]))
        return (d_bad / d_total) / tr.slo.budget

    def _check_alert(self, tr: _Tracked, now: float):
        slo = tr.slo
        fast = self._burn(tr, slo.fast_window_s, now)
        slow = self._burn(tr, slo.window_s, now)
        alert = _alert_threshold(slo)
        alerting = (fast is not None and slow is not None
                    and fast >= alert and slow >= alert)
        if alerting and not tr.alerting:
            # entering alert is the budget-page moment: one flight
            # event per transition, not per sample
            try:
                from . import flight_recorder as _flight

                _flight.record_event(
                    "slo_burn", slo=slo.name, selector=slo.selector,
                    fast_burn=round(fast, 3), slow_burn=round(slow, 3),
                    alert_burn=alert, target=slo.target)
            except Exception:
                pass
            _reg.counter("slo/alerts_total").inc()
        tr.alerting = alerting

    def max_confirmed_burn(self) -> float:
        """Max over objectives of min(fast, slow) burn — the double-
        window-confirmed rate the autoscaler treats as pressure (0.0
        with no objectives or insufficient samples)."""
        out = 0.0
        with self._lock:
            tracked = list(self._tracked.values())
        for tr in tracked:
            with self._lock:
                if not tr.samples:
                    continue
                now = tr.samples[-1][0]
            fast = self._burn(tr, tr.slo.fast_window_s, now)
            slow = self._burn(tr, tr.slo.window_s, now)
            if fast is not None and slow is not None:
                out = max(out, min(fast, slow))
        return out

    def sloz_payload(self, now=None) -> dict:
        """The /sloz document: per objective, the live good/total, both
        window burns, and the alert verdict."""
        with self._lock:
            tracked = list(self._tracked.values())
        rows = []
        for tr in tracked:
            slo = tr.slo
            with self._lock:
                n_samples = len(tr.samples)
                last_t = tr.samples[-1][0] if tr.samples else None
            at = float(now) if now is not None else last_t
            good, total = _measure(slo)
            fast = slow = None
            if at is not None:
                fast = self._burn(tr, slo.fast_window_s, at)
                slow = self._burn(tr, slo.window_s, at)
            row = slo.describe()
            row.update({
                "budget": round(slo.budget, 9),
                "good": round(good, 3),
                "total": round(total, 3),
                "bad_fraction": (round(1.0 - good / total, 9)
                                 if total else None),
                "burn": {"fast": (None if fast is None
                                  else round(fast, 4)),
                         "slow": (None if slow is None
                                  else round(slow, 4))},
                "alert_burn": _alert_threshold(slo),
                "alerting": tr.alerting,
                "samples": n_samples,
            })
            rows.append(row)
        return {"slos": rows,
                "sampler": {"alive": self.sampler_alive,
                            "interval_s": self._interval_s}}

    # -- background sampler --

    @property
    def sampler_alive(self) -> bool:
        return bool(self._thread is not None and self._thread.is_alive())

    def start(self, interval_s=None):
        """Start the daemon sampler (idempotent); interval defaults to
        FLAGS_slo_sample_interval_s."""
        if interval_s is None:
            try:
                from ..flags import flag

                interval_s = float(flag("slo_sample_interval_s"))
            except Exception:
                interval_s = 10.0
        self._interval_s = max(0.05, float(interval_s))
        if self.sampler_alive:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self._interval_s):
                try:
                    self.sample()
                except Exception:
                    pass  # a bad objective must not kill the sampler

        self._thread = threading.Thread(
            target=_loop, name="slo-sampler", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None


# -- module-level engine ------------------------------------------------------

_engine_lock = threading.Lock()
_engine: list = [None]


def engine() -> SLOEngine:
    """The process-wide engine (created on first use)."""
    with _engine_lock:
        if _engine[0] is None:
            _engine[0] = SLOEngine()
        return _engine[0]


def reset_engine():
    """Stop the sampler and drop all objectives (tests)."""
    with _engine_lock:
        eng, _engine[0] = _engine[0], None
    if eng is not None:
        eng.stop()


def install_slo(slo: SLO) -> SLO:
    return engine().add(slo)


def install_from_flags(start_sampler=True) -> list:
    """Install objectives from ``FLAGS_slo_objectives`` (';'-separated
    :func:`parse_objective` entries) and start the sampler. The hook
    every fleet entrypoint (serving backend main, router main) calls,
    so a subprocess launched with the flag in its env serves a live
    /sloz with zero code. Returns the installed SLOs ([] when the flag
    is empty)."""
    try:
        from ..flags import flag

        spec = str(flag("slo_objectives")).strip()
    except Exception:
        spec = ""
    if not spec:
        return []
    installed = [install_slo(parse_objective(e))
                 for e in spec.split(";") if e.strip()]
    if installed and start_sampler:
        engine().start()
    return installed


def sloz_payload() -> dict:
    """The /sloz document for this process ({"slos": []} when no
    objectives are installed — endpoints serve it unconditionally)."""
    with _engine_lock:
        eng = _engine[0]
    if eng is None:
        return {"slos": [],
                "sampler": {"alive": False, "interval_s": None}}
    return eng.sloz_payload()


def current_burn() -> float:
    """Double-window-confirmed burn for FleetSignals (0.0 when no
    engine/objectives/samples exist — never raises)."""
    with _engine_lock:
        eng = _engine[0]
    if eng is None:
        return 0.0
    try:
        return eng.max_confirmed_burn()
    except Exception:
        return 0.0
