"""Metrics registry: counters, gauges, bucketed histograms.

Reference parity: paddle/fluid/platform/monitor.h — the STAT_INT /
STAT_FLOAT registry (DEFINE_INT_STATUS / StatRegistry::Instance) that
every subsystem bumps and the exporters walk. The reference keys stats
by string name in a global singleton; so does this module, guarded by
one lock (stat updates are rare relative to the work they measure).

TPU-native additions the reference's registry never needed:
- HBM gauges fed from the PJRT arena counters
  (``jax.local_devices()[i].memory_stats()``) — the reference polled its
  own allocator, XLA owns ours.
- jax.monitoring listeners: XLA compile/retrace events arrive as named
  monitoring events; they land here as counters + duration histograms so
  a retrace storm is visible in the same dump as everything else.
"""
from __future__ import annotations

import bisect
import threading

__all__ = [
    "Counter", "Gauge", "Histogram",
    "counter", "gauge", "histogram",
    "STAT_INT", "STAT_FLOAT", "stat_add", "stat_reset",
    "registry_snapshot", "reset_registry", "all_metrics",
    "histogram_quantile", "merge_histogram_snapshots",
    "format_labels",
    "collect_hbm_gauges", "hbm_watermark_bytes",
    "install_jax_listeners",
]

_lock = threading.Lock()
_metrics: dict[str, "_Metric"] = {}

# default latency-ish buckets (ms): sub-ms to minutes, roughly 4x apart
DEFAULT_BUCKETS = (0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0, 500.0,
                   1000.0, 5000.0, 30000.0)

# label value every dimension collapses to once a family hits
# FLAGS_metrics_max_series — one shared series absorbs the overflow so
# a hostile/unbounded dimension can never grow memory past the bound
OVERFLOW_LABEL_VALUE = "other"


def _escape_label_value(v) -> str:
    """Escape a label VALUE per the prometheus exposition format:
    backslash, double-quote and newline are the three characters with
    wire meaning inside a quoted label value."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def format_labels(labels) -> str:
    """Canonical selector body for one label set — sorted keys, escaped
    values: ``k="v",k2="v2"``. This exact string keys the ``series``
    dict in snapshots and is what :func:`prometheus_text` emits inside
    ``{}``, so snapshot consumers and scrapers agree on series identity.
    Accepts a dict or an iterable of (key, value) pairs."""
    items = sorted(labels.items() if isinstance(labels, dict) else labels)
    return ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in items)


def _max_series() -> int:
    # lazy flag read: the registry is imported before flags in some
    # entrypoints, and set_flags must apply to live families
    try:
        from ..flags import flag

        return int(flag("metrics_max_series"))
    except Exception:
        return 64


class _Metric:
    kind = "untyped"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        # labeled child series (prometheus label semantics), keyed by
        # the sorted ((key, value), ...) tuple. For counters and
        # histograms every child update propagates into the parent, so
        # the bare family stays the exact aggregate over its labels and
        # label-free readers (/statz, /histz merges) see totals.
        self._children: dict = {}
        self._label_keys = None  # fixed by the first labels() call
        self._labels = ()        # ((k, v), ...) — set on children only
        self._parent = None
        self._overflowed = False

    def _new_child(self):
        return type(self)(self.name, help=self.help)

    def labels(self, **dims):
        """Child metric for one label set (``labels(kind="predict",
        tenant="a")``), get-or-create. The family's label KEYS are
        fixed by the first call; a later call with different keys
        raises — mixed key sets would make series identity ambiguous.

        Cardinality is hard-bounded by ``FLAGS_metrics_max_series``:
        once the family holds that many distinct label sets, every NEW
        set collapses into one shared series whose label values are all
        ``"other"`` (recording a single ``metric_series_overflow``
        flight event), so an unbounded dimension — a hostile tenant
        header — costs one series, never unbounded memory."""
        if self._parent is not None:
            raise ValueError(
                f"metric {self.name!r}: labels() called on a labeled "
                "child; call it on the family root")
        if not dims:
            raise ValueError(
                f"metric {self.name!r}: labels() needs at least one "
                "label")
        keys = tuple(sorted(dims))
        key = tuple((k, str(dims[k])) for k in keys)
        first_overflow = False
        with self._lock:
            if self._label_keys is None:
                self._label_keys = keys
            elif keys != self._label_keys:
                raise ValueError(
                    f"metric {self.name!r} labeled with keys "
                    f"{list(self._label_keys)}, got {list(keys)}; a "
                    "family's label keys are fixed by its first use")
            child = self._children.get(key)
            if child is None and len(self._children) >= _max_series():
                key = tuple((k, OVERFLOW_LABEL_VALUE) for k in keys)
                child = self._children.get(key)
                first_overflow = not self._overflowed
                self._overflowed = True
            if child is None:
                child = self._new_child()
                child._parent = self
                child._labels = key
                self._children[key] = child
        if first_overflow:
            try:
                from . import flight_recorder as _flight

                _flight.record_event(
                    "metric_series_overflow", metric=self.name,
                    max_series=_max_series())
            except Exception:
                pass
        return child

    def series(self) -> dict:
        """Live labeled children by selector body (``k="v",...``)."""
        with self._lock:
            children = list(self._children.values())
        return {format_labels(c._labels): c for c in children}

    def _series_snapshots(self) -> dict:
        with self._lock:
            children = list(self._children.values())
        out = {}
        for c in children:
            s = c.snapshot()
            s["labels"] = dict(c._labels)
            out[format_labels(c._labels)] = s
        return out

    def _reset_children(self):
        with self._lock:
            children = list(self._children.values())
        for c in children:
            c._reset()


class Counter(_Metric):
    """Monotonic counter (STAT_INT's common use: only ever added to)."""

    kind = "counter"

    def __init__(self, name, help=""):
        super().__init__(name, help)
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n
        if self._parent is not None:
            self._parent.inc(n)

    @property
    def value(self):
        with self._lock:
            return self._value

    def snapshot(self):
        snap = {"kind": self.kind, "value": self.value}
        series = self._series_snapshots()
        if series:
            snap["series"] = series
        return snap

    def _reset(self):
        with self._lock:
            self._value = 0
        self._reset_children()


class Gauge(_Metric):
    """Set-to-current-value stat (HBM in use, queue depth, lr).

    Gauge children do NOT propagate into the parent: "sum of last-set
    values" has no meaning for a set-semantics stat, so the parent and
    each labeled child are independent series."""

    kind = "gauge"

    def __init__(self, name, help=""):
        super().__init__(name, help)
        self._value = 0.0

    def set(self, v):
        with self._lock:
            self._value = v

    def add(self, v):
        with self._lock:
            self._value += v

    @property
    def value(self):
        with self._lock:
            return self._value

    def snapshot(self):
        snap = {"kind": self.kind, "value": self.value}
        series = self._series_snapshots()
        if series:
            snap["series"] = series
        return snap

    def _reset(self):
        with self._lock:
            self._value = 0.0
        self._reset_children()


class Histogram(_Metric):
    """Cumulative bucketed histogram (prometheus semantics: bucket i
    counts observations <= bounds[i]; +Inf bucket is implicit)."""

    kind = "histogram"

    def __init__(self, name, buckets=None, help=""):
        super().__init__(name, help)
        bounds = tuple(sorted(buckets if buckets is not None
                              else DEFAULT_BUCKETS))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = > max bound (+Inf)
        self._sum = 0.0
        self._count = 0

    def _new_child(self):
        # children must share the family's bucket ladder or label-aware
        # merges would mis-bin
        return Histogram(self.name, buckets=self.bounds, help=self.help)

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
        if self._parent is not None:
            self._parent.observe(v)

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def bucket_counts(self):
        """Per-bucket (non-cumulative) counts, +Inf bucket last."""
        with self._lock:
            return list(self._counts)

    def cumulative_counts(self):
        """Prometheus-style cumulative counts per le bound, +Inf last."""
        out, acc = [], 0
        with self._lock:
            for c in self._counts:
                acc += c
                out.append(acc)
        return out

    def snapshot(self):
        with self._lock:
            snap = {
                "kind": self.kind, "sum": self._sum, "count": self._count,
                "bounds": list(self.bounds), "buckets": list(self._counts),
            }
        series = self._series_snapshots()
        if series:
            snap["series"] = series
        return snap

    def _reset(self):
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0
        self._reset_children()


def _get(name, cls, **kwargs):
    with _lock:
        m = _metrics.get(name)
        if m is None:
            m = cls(name, **kwargs)
            _metrics[name] = m
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}")
        return m


def counter(name, help="") -> Counter:
    """Get-or-create the named counter."""
    return _get(name, Counter, help=help)


def gauge(name, help="") -> Gauge:
    return _get(name, Gauge, help=help)


def histogram(name, buckets=None, help="") -> Histogram:
    h = _get(name, Histogram, buckets=buckets, help=help)
    # explicit bounds that disagree with the registered metric must fail
    # loudly — silently observing into someone else's buckets corrupts
    # both callers' data (same contract as the kind-collision TypeError)
    if buckets is not None and tuple(sorted(buckets)) != h.bounds:
        raise ValueError(
            f"histogram {name!r} already registered with bounds "
            f"{h.bounds}, requested {tuple(sorted(buckets))}")
    return h


# -- STAT_INT / STAT_FLOAT parity -------------------------------------------
# The reference macros (platform/monitor.h DEFINE_INT_STATUS) define a
# named stat once and bump it anywhere via STAT_ADD/STAT_RESET; both int
# and float stats are gauges with add semantics here.

def STAT_INT(name) -> Gauge:
    """DEFINE_INT_STATUS equivalent: named integer stat (gauge w/ add)."""
    return gauge(f"stat/int/{name}")


def STAT_FLOAT(name) -> Gauge:
    return gauge(f"stat/float/{name}")


def stat_add(name, v=1):
    """STAT_ADD(name, v) — int stat add by name."""
    STAT_INT(name).add(v)


def stat_reset(name):
    """STAT_RESET(name)."""
    STAT_INT(name).set(0)


def histogram_quantile(h: Histogram, q: float):
    """Approximate quantile from the bucketed counts (prometheus
    histogram_quantile semantics: linear interpolation inside the
    matching bucket; observations in the +Inf bucket clamp to the
    largest finite bound). Returns ``None`` on an empty histogram —
    0.0 would be indistinguishable from a real 0ms quantile on a
    merged/fleet view, so callers render the series as absent."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    snap = h.snapshot()
    total = snap["count"]
    if total == 0:
        return None
    target = q * total
    acc, lo = 0, 0.0
    for bound, c in zip(snap["bounds"], snap["buckets"]):
        if c and acc + c >= target:
            return lo + (bound - lo) * (target - acc) / c
        acc += c
        lo = bound
    return float(snap["bounds"][-1])


def merge_histogram_snapshots(snapshots, name="merged") -> Histogram:
    """Merge histogram ``snapshot()`` dicts from several sources (e.g. N
    serving backends' ``/histz`` payloads) into one UNREGISTERED
    :class:`Histogram` whose bucket counts are the elementwise sums —
    feed it to :func:`histogram_quantile` for fleet-wide p50/p99.

    Bucketed histograms merge exactly: summing per-bucket counts over
    backends is identical to having observed every sample into one
    pooled histogram (same bounds), so the router's merged quantiles
    match the single-histogram golden. All snapshots must share the
    same bounds; a mismatch raises rather than silently mis-binning.

    Label-aware: snapshots carrying a ``series`` dict (labeled
    families) get their per-selector child snapshots merged the same
    elementwise way; the merged children hang off the returned
    histogram's :meth:`~_Metric.series` so fleet quantiles exist per
    labeled series too. A series only some sources carry merges over
    the sources that have it.
    """
    snapshots = [s for s in snapshots if s]
    if not snapshots:
        raise ValueError("merge_histogram_snapshots needs >= 1 snapshot")
    bounds = tuple(snapshots[0]["bounds"])
    h = Histogram(name, buckets=bounds)
    counts = [0] * (len(bounds) + 1)
    total, sum_ = 0, 0.0
    for s in snapshots:
        if tuple(s["bounds"]) != bounds:
            raise ValueError(
                f"histogram bounds mismatch: {tuple(s['bounds'])} vs "
                f"{bounds}; backends must share one bucket ladder")
        if len(s["buckets"]) != len(counts):
            raise ValueError(
                f"histogram has {len(s['buckets'])} buckets, expected "
                f"{len(counts)} (bounds + the +Inf bucket)")
        for i, c in enumerate(s["buckets"]):
            counts[i] += int(c)
        total += int(s["count"])
        sum_ += float(s["sum"])
    h._counts = counts
    h._count = total
    h._sum = sum_
    per_series: dict = {}
    for s in snapshots:
        for sub in (s.get("series") or {}).values():
            labels = tuple(sorted((sub.get("labels") or {}).items()))
            per_series.setdefault(labels, []).append(sub)
    for labels, subs in per_series.items():
        child = merge_histogram_snapshots(subs, name=name)
        # static merged data: labeled for series(), but no parent link —
        # nothing observes into a merge result
        child._labels = labels
        h._children[labels] = child
    return h


def all_metrics() -> dict:
    """Live metric objects by name (ordered by registration)."""
    with _lock:
        return dict(_metrics)


def registry_snapshot() -> dict:
    """Plain-data snapshot of every metric (JSON-safe)."""
    return {name: m.snapshot() for name, m in all_metrics().items()}


def reset_registry(unregister=False):
    """Zero every metric; ``unregister=True`` also drops the definitions
    (tests use this so registrations can't leak across files)."""
    with _lock:
        if unregister:
            _metrics.clear()
            return
        metrics = list(_metrics.values())
    for m in metrics:
        m._reset()


# -- HBM gauges --------------------------------------------------------------

_HBM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_free_block_bytes")


def collect_hbm_gauges(devices=None) -> dict:
    """Populate per-device HBM gauges from PJRT arena counters.

    Sets ``hbm/device<i>/<key>`` gauges for every counter the backend
    publishes and returns the values set. Backends that publish none
    (CPU; tunneled TPU proxies) contribute nothing rather than zeros —
    a zero gauge would read as "no memory in use", which is a lie.
    ``devices`` is injectable for tests; defaults to jax.local_devices().
    """
    if devices is None:
        import jax

        devices = jax.local_devices()
    out = {}
    for i, d in enumerate(devices):
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        for key in _HBM_KEYS:
            if key in stats:
                name = f"hbm/device{i}/{key}"
                gauge(name).set(int(stats[key]))
                out[name] = int(stats[key])
    return out


def hbm_watermark_bytes(devices=None) -> int:
    """Max peak_bytes_in_use across local devices (0 if unpublished)."""
    vals = collect_hbm_gauges(devices)
    peaks = [v for k, v in vals.items() if k.endswith("peak_bytes_in_use")]
    return max(peaks) if peaks else 0


# -- jax.monitoring listeners ------------------------------------------------

_jax_listeners_installed = [False]


def install_jax_listeners() -> bool:
    """Route jax.monitoring events (XLA compile, cache hits, retraces)
    into the registry: every event bumps ``jax/<event>``; duration events
    also observe ``jax/<event>/duration_ms``. Idempotent; returns whether
    the listeners are active (False on a jax without jax.monitoring).

    jax emits keys like ``/jax/core/compile`` — each fresh compile of a
    jitted function is one event, so a retrace storm (unstable shapes or
    hash-unstable static args) shows up as this counter racing the step
    counter.
    """
    if _jax_listeners_installed[0]:
        return True
    try:
        from jax import monitoring as jmon
    except Exception:
        return False

    def _flight_record(event, **fields):
        # XLA compile events land in the flight recorder too: a dump of a
        # hung/dying run shows whether a retrace storm preceded the stall
        # (lazy import: flight_recorder must stay importable first)
        try:
            from . import flight_recorder as _flight

            _flight.record_event("xla_event", event=event, **fields)
        except Exception:
            pass

    def _on_event(event, **kwargs):
        counter(f"jax/{event.lstrip('/')}").inc()
        _flight_record(event)

    def _on_duration(event, duration_secs, **kwargs):
        counter(f"jax/{event.lstrip('/')}").inc()
        histogram(f"jax/{event.lstrip('/')}/duration_ms").observe(
            duration_secs * 1e3)
        _flight_record(event, duration_ms=round(duration_secs * 1e3, 3))

    # mark installed as soon as the FIRST registration lands: there is no
    # public unregister, so a retry after a partial failure must never
    # re-register _on_event (duplicate listeners would double-count every
    # compile). A jax missing the duration API degrades to counters-only.
    try:
        jmon.register_event_listener(_on_event)
    except Exception:
        return False
    _jax_listeners_installed[0] = True
    try:
        jmon.register_event_duration_secs_listener(_on_duration)
    except Exception:
        pass
    return True
