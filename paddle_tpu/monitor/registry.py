"""Metrics registry: counters, gauges, bucketed histograms.

Reference parity: paddle/fluid/platform/monitor.h — the STAT_INT /
STAT_FLOAT registry (DEFINE_INT_STATUS / StatRegistry::Instance) that
every subsystem bumps and the exporters walk. The reference keys stats
by string name in a global singleton; so does this module, guarded by
one lock (stat updates are rare relative to the work they measure).

TPU-native additions the reference's registry never needed:
- HBM gauges fed from the PJRT arena counters
  (``jax.local_devices()[i].memory_stats()``) — the reference polled its
  own allocator, XLA owns ours.
- jax.monitoring listeners: XLA compile/retrace events arrive as named
  monitoring events; they land here as counters + duration histograms so
  a retrace storm is visible in the same dump as everything else.
"""
from __future__ import annotations

import bisect
import threading

__all__ = [
    "Counter", "Gauge", "Histogram",
    "counter", "gauge", "histogram",
    "STAT_INT", "STAT_FLOAT", "stat_add", "stat_reset",
    "registry_snapshot", "reset_registry", "all_metrics",
    "histogram_quantile", "merge_histogram_snapshots",
    "collect_hbm_gauges", "hbm_watermark_bytes",
    "install_jax_listeners",
]

_lock = threading.Lock()
_metrics: dict[str, "_Metric"] = {}

# default latency-ish buckets (ms): sub-ms to minutes, roughly 4x apart
DEFAULT_BUCKETS = (0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0, 500.0,
                   1000.0, 5000.0, 30000.0)


class _Metric:
    kind = "untyped"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotonic counter (STAT_INT's common use: only ever added to)."""

    kind = "counter"

    def __init__(self, name, help=""):
        super().__init__(name, help)
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def snapshot(self):
        return {"kind": self.kind, "value": self.value}

    def _reset(self):
        with self._lock:
            self._value = 0


class Gauge(_Metric):
    """Set-to-current-value stat (HBM in use, queue depth, lr)."""

    kind = "gauge"

    def __init__(self, name, help=""):
        super().__init__(name, help)
        self._value = 0.0

    def set(self, v):
        with self._lock:
            self._value = v

    def add(self, v):
        with self._lock:
            self._value += v

    @property
    def value(self):
        with self._lock:
            return self._value

    def snapshot(self):
        return {"kind": self.kind, "value": self.value}

    def _reset(self):
        with self._lock:
            self._value = 0.0


class Histogram(_Metric):
    """Cumulative bucketed histogram (prometheus semantics: bucket i
    counts observations <= bounds[i]; +Inf bucket is implicit)."""

    kind = "histogram"

    def __init__(self, name, buckets=None, help=""):
        super().__init__(name, help)
        bounds = tuple(sorted(buckets if buckets is not None
                              else DEFAULT_BUCKETS))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = > max bound (+Inf)
        self._sum = 0.0
        self._count = 0

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def bucket_counts(self):
        """Per-bucket (non-cumulative) counts, +Inf bucket last."""
        with self._lock:
            return list(self._counts)

    def cumulative_counts(self):
        """Prometheus-style cumulative counts per le bound, +Inf last."""
        out, acc = [], 0
        with self._lock:
            for c in self._counts:
                acc += c
                out.append(acc)
        return out

    def snapshot(self):
        with self._lock:
            return {
                "kind": self.kind, "sum": self._sum, "count": self._count,
                "bounds": list(self.bounds), "buckets": list(self._counts),
            }

    def _reset(self):
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0


def _get(name, cls, **kwargs):
    with _lock:
        m = _metrics.get(name)
        if m is None:
            m = cls(name, **kwargs)
            _metrics[name] = m
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}")
        return m


def counter(name, help="") -> Counter:
    """Get-or-create the named counter."""
    return _get(name, Counter, help=help)


def gauge(name, help="") -> Gauge:
    return _get(name, Gauge, help=help)


def histogram(name, buckets=None, help="") -> Histogram:
    h = _get(name, Histogram, buckets=buckets, help=help)
    # explicit bounds that disagree with the registered metric must fail
    # loudly — silently observing into someone else's buckets corrupts
    # both callers' data (same contract as the kind-collision TypeError)
    if buckets is not None and tuple(sorted(buckets)) != h.bounds:
        raise ValueError(
            f"histogram {name!r} already registered with bounds "
            f"{h.bounds}, requested {tuple(sorted(buckets))}")
    return h


# -- STAT_INT / STAT_FLOAT parity -------------------------------------------
# The reference macros (platform/monitor.h DEFINE_INT_STATUS) define a
# named stat once and bump it anywhere via STAT_ADD/STAT_RESET; both int
# and float stats are gauges with add semantics here.

def STAT_INT(name) -> Gauge:
    """DEFINE_INT_STATUS equivalent: named integer stat (gauge w/ add)."""
    return gauge(f"stat/int/{name}")


def STAT_FLOAT(name) -> Gauge:
    return gauge(f"stat/float/{name}")


def stat_add(name, v=1):
    """STAT_ADD(name, v) — int stat add by name."""
    STAT_INT(name).add(v)


def stat_reset(name):
    """STAT_RESET(name)."""
    STAT_INT(name).set(0)


def histogram_quantile(h: Histogram, q: float) -> float:
    """Approximate quantile from the bucketed counts (prometheus
    histogram_quantile semantics: linear interpolation inside the
    matching bucket; observations in the +Inf bucket clamp to the
    largest finite bound). Returns 0.0 on an empty histogram."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    snap = h.snapshot()
    total = snap["count"]
    if total == 0:
        return 0.0
    target = q * total
    acc, lo = 0, 0.0
    for bound, c in zip(snap["bounds"], snap["buckets"]):
        if c and acc + c >= target:
            return lo + (bound - lo) * (target - acc) / c
        acc += c
        lo = bound
    return float(snap["bounds"][-1])


def merge_histogram_snapshots(snapshots, name="merged") -> Histogram:
    """Merge histogram ``snapshot()`` dicts from several sources (e.g. N
    serving backends' ``/histz`` payloads) into one UNREGISTERED
    :class:`Histogram` whose bucket counts are the elementwise sums —
    feed it to :func:`histogram_quantile` for fleet-wide p50/p99.

    Bucketed histograms merge exactly: summing per-bucket counts over
    backends is identical to having observed every sample into one
    pooled histogram (same bounds), so the router's merged quantiles
    match the single-histogram golden. All snapshots must share the
    same bounds; a mismatch raises rather than silently mis-binning.
    """
    snapshots = [s for s in snapshots if s]
    if not snapshots:
        raise ValueError("merge_histogram_snapshots needs >= 1 snapshot")
    bounds = tuple(snapshots[0]["bounds"])
    h = Histogram(name, buckets=bounds)
    counts = [0] * (len(bounds) + 1)
    total, sum_ = 0, 0.0
    for s in snapshots:
        if tuple(s["bounds"]) != bounds:
            raise ValueError(
                f"histogram bounds mismatch: {tuple(s['bounds'])} vs "
                f"{bounds}; backends must share one bucket ladder")
        if len(s["buckets"]) != len(counts):
            raise ValueError(
                f"histogram has {len(s['buckets'])} buckets, expected "
                f"{len(counts)} (bounds + the +Inf bucket)")
        for i, c in enumerate(s["buckets"]):
            counts[i] += int(c)
        total += int(s["count"])
        sum_ += float(s["sum"])
    h._counts = counts
    h._count = total
    h._sum = sum_
    return h


def all_metrics() -> dict:
    """Live metric objects by name (ordered by registration)."""
    with _lock:
        return dict(_metrics)


def registry_snapshot() -> dict:
    """Plain-data snapshot of every metric (JSON-safe)."""
    return {name: m.snapshot() for name, m in all_metrics().items()}


def reset_registry(unregister=False):
    """Zero every metric; ``unregister=True`` also drops the definitions
    (tests use this so registrations can't leak across files)."""
    with _lock:
        if unregister:
            _metrics.clear()
            return
        metrics = list(_metrics.values())
    for m in metrics:
        m._reset()


# -- HBM gauges --------------------------------------------------------------

_HBM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_free_block_bytes")


def collect_hbm_gauges(devices=None) -> dict:
    """Populate per-device HBM gauges from PJRT arena counters.

    Sets ``hbm/device<i>/<key>`` gauges for every counter the backend
    publishes and returns the values set. Backends that publish none
    (CPU; tunneled TPU proxies) contribute nothing rather than zeros —
    a zero gauge would read as "no memory in use", which is a lie.
    ``devices`` is injectable for tests; defaults to jax.local_devices().
    """
    if devices is None:
        import jax

        devices = jax.local_devices()
    out = {}
    for i, d in enumerate(devices):
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        for key in _HBM_KEYS:
            if key in stats:
                name = f"hbm/device{i}/{key}"
                gauge(name).set(int(stats[key]))
                out[name] = int(stats[key])
    return out


def hbm_watermark_bytes(devices=None) -> int:
    """Max peak_bytes_in_use across local devices (0 if unpublished)."""
    vals = collect_hbm_gauges(devices)
    peaks = [v for k, v in vals.items() if k.endswith("peak_bytes_in_use")]
    return max(peaks) if peaks else 0


# -- jax.monitoring listeners ------------------------------------------------

_jax_listeners_installed = [False]


def install_jax_listeners() -> bool:
    """Route jax.monitoring events (XLA compile, cache hits, retraces)
    into the registry: every event bumps ``jax/<event>``; duration events
    also observe ``jax/<event>/duration_ms``. Idempotent; returns whether
    the listeners are active (False on a jax without jax.monitoring).

    jax emits keys like ``/jax/core/compile`` — each fresh compile of a
    jitted function is one event, so a retrace storm (unstable shapes or
    hash-unstable static args) shows up as this counter racing the step
    counter.
    """
    if _jax_listeners_installed[0]:
        return True
    try:
        from jax import monitoring as jmon
    except Exception:
        return False

    def _flight_record(event, **fields):
        # XLA compile events land in the flight recorder too: a dump of a
        # hung/dying run shows whether a retrace storm preceded the stall
        # (lazy import: flight_recorder must stay importable first)
        try:
            from . import flight_recorder as _flight

            _flight.record_event("xla_event", event=event, **fields)
        except Exception:
            pass

    def _on_event(event, **kwargs):
        counter(f"jax/{event.lstrip('/')}").inc()
        _flight_record(event)

    def _on_duration(event, duration_secs, **kwargs):
        counter(f"jax/{event.lstrip('/')}").inc()
        histogram(f"jax/{event.lstrip('/')}/duration_ms").observe(
            duration_secs * 1e3)
        _flight_record(event, duration_ms=round(duration_secs * 1e3, 3))

    # mark installed as soon as the FIRST registration lands: there is no
    # public unregister, so a retry after a partial failure must never
    # re-register _on_event (duplicate listeners would double-count every
    # compile). A jax missing the duration API degrades to counters-only.
    try:
        jmon.register_event_listener(_on_event)
    except Exception:
        return False
    _jax_listeners_installed[0] = True
    try:
        jmon.register_event_duration_secs_listener(_on_duration)
    except Exception:
        pass
    return True
