"""Run telemetry: metrics registry, whole-stack spans, training monitor.

Reference parity: paddle/fluid/platform/monitor.h (the STAT_INT/
STAT_FLOAT registry), the profiler's CostInfo summaries, and the device
tracer's chrome-trace export — unified here on top of
``paddle_tpu.profiler`` (which owns the RAII spans and the always-on
dispatch counters from PR 1).

Three layers:

- :mod:`monitor.registry` — counters, gauges, bucketed histograms;
  STAT_INT/STAT_FLOAT parity helpers; HBM gauges from the PJRT arena
  counters; jax.monitoring listeners turning XLA compile/retrace events
  into metrics.
- :mod:`monitor.training_monitor` — step-level aggregation (wall time,
  examples/sec, input-wait ratio, executor cache hit rates, HBM
  watermark) with a periodic log line behind ``FLAGS_monitor_interval``.
- :mod:`monitor.export` — Prometheus text dump + merged chrome trace
  (host spans and jax device trace in one JSON); summarize either with
  ``tools/trace_summary.py``.
- :mod:`monitor.cost_model` — hardware-utilization accounting: XLA
  ``cost_analysis``/``memory_analysis`` captured per compiled program
  (executor RunPlan jits, framework/jit train steps), a per-device-kind
  peak table (``FLAGS_device_peaks`` override), MFU / HBM-bandwidth /
  roofline math; served on ``/costz``.
- :mod:`monitor.cluster` — cluster-wide aggregation: per-rank metric
  snapshots over the jax.distributed KV side channel, rank-0
  ``/clusterz`` fleet view with straggler verdicts
  (``FLAGS_straggler_threshold``).
- :mod:`monitor.tracing` — distributed request tracing: contextvar
  trace context with W3C-style ``traceparent`` propagation across the
  router->backend hop, structured spans through batcher/executor/
  generation, and a tail-sampled trace store (always keep error/
  deadline/retried traces plus the slowest-K per window) served on
  ``/tracez``.
- :mod:`monitor.slo` — error-budget objectives: declarative
  :class:`SLO` definitions over (label-aware) metric selectors,
  multi-window burn-rate evaluation (fast 5m / slow 1h), ``/sloz``
  payloads, ``slo_burn`` flight events at alert transitions, and the
  confirmed-burn signal the autoscaler consumes.
- :mod:`monitor.goodput` — lifetime training goodput/badput ledger:
  every second of wall time classified into exclusive phases (compute,
  input wait, compile, checkpoint, restore, renegotiate, restart lost
  work, aborted steps, idle) with a crash-surviving ``GOODPUT.json``
  sidecar, ``goodput/seconds_total{phase=…}`` labeled counters, the
  ``/goodputz`` endpoint, per-rank ``/clusterz`` rows, a chrome-trace
  phase track, and an optional burn-rate SLO
  (``FLAGS_goodput_slo_target``).
- :mod:`monitor.flight_recorder` — fault diagnosis: ring-buffer flight
  recorder (executor runs, collectives with per-group sequence numbers
  and fingerprints, PS RPCs, dataloader lifecycle, flag changes, XLA
  compiles), hang watchdog (``FLAGS_watchdog_timeout_s``), cross-rank
  collective desync detection; dumps on crash/SIGUSR1/watchdog trip.
- :mod:`monitor.debug_server` — live ``/healthz`` ``/metrics``
  ``/flightrecorder`` ``/threadz`` ``/flagz`` HTTP endpoint behind
  ``FLAGS_debug_port``; inspect dumps offline with
  ``tools/debug_dump.py``.

The span side is ambient: the executor, DataLoader, collectives, sharded
train steps, and PS client/server already wrap their hot phases in
``profiler.RecordEvent`` — enable with ``profiler.start_profiler()``,
then export the merged picture here.
"""
from __future__ import annotations

from .registry import (  # noqa: F401
    STAT_FLOAT,
    STAT_INT,
    Counter,
    Gauge,
    Histogram,
    all_metrics,
    collect_hbm_gauges,
    counter,
    format_labels,
    gauge,
    hbm_watermark_bytes,
    histogram,
    histogram_quantile,
    install_jax_listeners,
    merge_histogram_snapshots,
    registry_snapshot,
    reset_registry,
    stat_add,
    stat_reset,
)
from .export import (  # noqa: F401
    PROMETHEUS_CONTENT_TYPE,
    export_merged_chrome_trace,
    export_prometheus,
    prometheus_text,
)
from . import cost_model  # noqa: F401
from .cost_model import (  # noqa: F401
    CostRecord,
    device_peaks,
    hbm_bw_util,
    mfu,
    roofline_class,
)
from .training_monitor import (  # noqa: F401
    TrainingMonitor,
    active_monitor,
    record_input_wait_ms,
)
from . import goodput  # noqa: F401
from .goodput import (  # noqa: F401
    GoodputLedger,
    active_ledger,
    goodputz_payload,
    install_goodput_slo,
    start_ledger,
    stop_ledger,
)
from . import tracing  # noqa: F401
from .tracing import (  # noqa: F401
    SpanContext,
    TraceStore,
    annotate,
    current_context,
    current_span,
    format_traceparent,
    parse_traceparent,
    start_span,
    start_trace,
)
from . import cluster  # noqa: F401
from . import flight_recorder  # noqa: F401
# slo.install_from_flags stays module-qualified: the package-level name
# belongs to flight_recorder's (PR 9)
from . import slo  # noqa: F401
from .slo import (  # noqa: F401
    SLO,
    SLOEngine,
    current_burn,
    install_slo,
    sloz_payload,
)
from . import opprof  # noqa: F401
from .opprof import (  # noqa: F401
    TIME_ACCURACY_ENVELOPE,
    attribute_trace,
    op_scope_name,
    parse_op_scope,
    profile_program,
    profilez_payload,
)
from . import debug_server  # noqa: F401
from .flight_recorder import (  # noqa: F401
    FlightRecorder,
    HangWatchdog,
    dump_now,
    install_from_flags,
)
from .debug_server import (  # noqa: F401
    DebugServer,
    start_debug_server,
    stop_debug_server,
)

__all__ = [
    "Counter", "Gauge", "Histogram",
    "counter", "gauge", "histogram",
    "STAT_INT", "STAT_FLOAT", "stat_add", "stat_reset",
    "registry_snapshot", "reset_registry", "all_metrics",
    "histogram_quantile", "merge_histogram_snapshots", "format_labels",
    "collect_hbm_gauges", "hbm_watermark_bytes", "install_jax_listeners",
    "export_prometheus", "prometheus_text", "export_merged_chrome_trace",
    "PROMETHEUS_CONTENT_TYPE",
    "TrainingMonitor", "record_input_wait_ms", "active_monitor",
    "goodput", "GoodputLedger", "start_ledger", "stop_ledger",
    "active_ledger", "goodputz_payload", "install_goodput_slo",
    "cost_model", "CostRecord", "device_peaks", "mfu", "hbm_bw_util",
    "roofline_class", "cluster",
    "tracing", "SpanContext", "TraceStore", "annotate",
    "current_context", "current_span", "format_traceparent",
    "parse_traceparent", "start_span", "start_trace",
    "opprof", "TIME_ACCURACY_ENVELOPE", "op_scope_name", "parse_op_scope",
    "attribute_trace", "profile_program", "profilez_payload",
    "flight_recorder", "debug_server",
    "slo", "SLO", "SLOEngine", "install_slo", "sloz_payload",
    "current_burn",
    "FlightRecorder", "HangWatchdog", "dump_now", "install_from_flags",
    "DebugServer", "start_debug_server", "stop_debug_server",
]
