"""Cluster-wide metrics aggregation + straggler detection.

PR 2/3 gave every rank its own telemetry (``/metrics``) and its own black
box (flight recorder) — but each rank's endpoint is an island: diagnosing
"the fleet is 20% slower" means curling N ports and eyeballing. This
module makes rank 0 (or any rank) a cluster window:

- every rank periodically **publishes** a compact metric snapshot (step
  time, MFU, input-wait ratio, HBM watermark — the TrainingMonitor window
  plus cost-model utilization) over the jax.distributed coordination-
  service KV store — the same side channel the desync exchange already
  rides, so a fleet run needs zero extra transport;
- ``/clusterz`` on the debug server **collects** every rank's latest
  snapshot and renders the fleet in one JSON: per-rank step time, MFU,
  input-wait ratio, and a **straggler verdict** — any rank whose step
  time exceeds ``FLAGS_straggler_threshold`` × the cluster median is
  flagged, and the verdict is recorded into the flight recorder so a
  post-mortem dump carries the same evidence the live endpoint showed.

Single-process worlds degrade to a one-row payload built locally (no
channel needed) — the endpoint renders everywhere.
"""
from __future__ import annotations

import json
import os
import statistics
import threading
import time

from ..flags import flag
from . import cost_model as _cost
from . import flight_recorder as _flight
from . import goodput as _goodput
from . import registry as _reg
from . import training_monitor as _tm

__all__ = [
    "local_snapshot", "publish", "collect", "detect_stragglers",
    "clusterz_payload",
    "add_verdict_listener", "remove_verdict_listener",
    "ClusterPublisher", "start_publisher", "stop_publisher", "publisher",
]

# Straggler-verdict subscribers: every clusterz_payload evaluation feeds
# its full payload to each listener. distributed/elastic.py's eviction
# policy (StragglerTracker) rides this — a persistently flagged rank is
# checkpointed around and the world renegotiated, instead of the whole
# job running at the straggler's pace.
_VERDICT_LISTENERS: list = []


def add_verdict_listener(cb):
    """Register ``cb(payload)`` to observe every straggler evaluation."""
    _VERDICT_LISTENERS.append(cb)
    return cb


def remove_verdict_listener(cb):
    try:
        _VERDICT_LISTENERS.remove(cb)
    except ValueError:
        pass

_KEY_PREFIX = "ptpu/cluster/metrics"


def local_snapshot() -> dict:
    """This rank's metric snapshot (the wire payload): the active
    TrainingMonitor's current window plus identity/uptime. A rank with no
    monitor (pure-serving process, pre-training warmup) still publishes
    identity + HBM so the cluster view has no holes."""
    mon = _tm.active_monitor()
    snap = mon.snapshot() if mon is not None else {}
    led = _goodput.active_ledger()
    if led is not None:
        g = led.snapshot()
        life = g["lifetime"]
        goodput_row = {
            "goodput": round(float(life["goodput"]), 6),
            "goodput_wall_s": round(float(life["wall_s"]), 3),
            "goodput_compute_s": round(
                float(life["phases"]["compute"]), 3),
            "lost_work_s": round(float(life["phases"]["lost_work"]), 3),
            "lost_steps": int(life["lost_steps"]),
            "resumes": int(life["resumes"]),
        }
    else:
        goodput_row = {}
    return {
        # per-rank lifetime goodput (empty when the ledger is off): the
        # fleet aggregate in clusterz_payload is wall-weighted over these
        **goodput_row,
        "rank": _flight._safe_rank(),
        "world": _flight._safe_world(),
        "pid": os.getpid(),
        "time": time.time(),
        "step": int(snap.get("step", 0)),
        "step_ms": float(snap.get("step_ms", 0.0)),
        "steps_per_sec": float(snap.get("steps_per_sec", 0.0)),
        "examples_per_sec": float(snap.get("examples_per_sec", 0.0)),
        "input_wait_ratio": float(snap.get("input_wait_ratio", 0.0)),
        "mfu": float(snap.get("mfu", 0.0)),
        "hbm_bw_util": float(snap.get("hbm_bw_util", 0.0)),
        "roofline": snap.get("roofline", "unknown"),
        "compiles": int(snap.get("compiles", 0)),
        # don't sweep device memory_stats twice: the monitor snapshot
        # already paid for the watermark when one is active
        "hbm_peak_bytes": int(
            snap["hbm_peak_bytes"] if "hbm_peak_bytes" in snap
            else _reg.hbm_watermark_bytes()),
    }


def publish(channel=None, rank=None, snapshot=None) -> bool:
    """Publish this rank's snapshot under a stable per-rank key
    (overwrite semantics: collectors always read the latest). Returns
    whether a channel existed to publish on — single-process/eager runs
    stay harmless no-ops."""
    channel = channel or _flight._default_channel()
    if channel is None:
        return False
    if rank is None:
        rank = _flight._safe_rank()
    snap = snapshot if snapshot is not None else local_snapshot()
    try:
        channel.set(f"{_KEY_PREFIX}/{rank}", json.dumps(snap))
    except Exception as e:
        _flight.record_event("cluster_publish_failed",
                             error=f"{type(e).__name__}: {e}"[:200])
        return False
    return True


def collect(world=None, timeout_s=5.0, channel=None):
    """Every rank's latest published snapshot: ``(by_rank, missing)``.

    Same sweep discipline as the desync exchange: ONE shared deadline, a
    quick short-slice pass first so a dead low rank cannot starve reads
    of higher ranks whose snapshots are already published, then the
    remaining budget split across stragglers. A rank that never published
    lands in ``missing`` — absence is evidence, not an error.

    A world of 1 (or no side channel) returns the local snapshot only:
    the cluster view of a single-process run is that process.
    """
    if world is None:
        world = _flight._safe_world()
    rank = _flight._safe_rank()
    if world <= 1:
        return {rank: local_snapshot()}, []
    channel = channel or _flight._default_channel()
    if channel is None:
        return {rank: local_snapshot()}, sorted(
            set(range(world)) - {rank})
    by_rank = {}
    deadline = time.monotonic() + float(timeout_s)

    def _try_get(r, budget_s):
        try:
            raw = channel.get(f"{_KEY_PREFIX}/{r}", max(budget_s, 0.001))
            if isinstance(raw, bytes):
                raw = raw.decode("utf-8")
            by_rank[r] = json.loads(raw)
            return True
        except Exception:
            return False

    stragglers = [r for r in range(world)
                  if not _try_get(r, min(0.25,
                                         deadline - time.monotonic()))]
    for i, r in enumerate(stragglers):
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        _try_get(r, remaining / (len(stragglers) - i))
    missing = sorted(set(range(world)) - set(by_rank))
    return by_rank, missing


def detect_stragglers(by_rank, threshold=None):
    """Flag ranks whose step time exceeds ``threshold`` × the cluster
    median (``FLAGS_straggler_threshold`` when None). Ranks reporting no
    steps yet (step_ms 0) are excluded from the median — a cold rank is
    "missing evidence", not "infinitely fast". Returns
    ``(stragglers, median_step_ms)`` where each straggler carries its
    rank, step_ms, and the ratio to the median."""
    if threshold is None:
        threshold = float(flag("straggler_threshold"))
    times = {r: float(s.get("step_ms", 0.0)) for r, s in by_rank.items()
             if float(s.get("step_ms", 0.0)) > 0.0}
    if len(times) < 2:
        return [], 0.0
    median = statistics.median(times.values())
    out = []
    for r, ms in sorted(times.items()):
        if median > 0 and ms > threshold * median:
            out.append({"rank": r, "step_ms": ms,
                        "ratio_to_median": round(ms / median, 3)})
    return out, median


def _fleet_goodput(by_rank) -> dict | None:
    """Wall-weighted fleet goodput over the ranks reporting a ledger
    row: sum(compute) / sum(wall) is the job's aggregate ratio (a
    per-rank mean would let a short-lived rank swing the answer).
    None when no rank runs a ledger."""
    rows = [s for s in by_rank.values() if "goodput_wall_s" in s]
    if not rows:
        return None
    wall = sum(float(s.get("goodput_wall_s", 0.0)) for s in rows)
    compute = sum(float(s.get("goodput_compute_s", 0.0)) for s in rows)
    return {
        "ranks_reporting": len(rows),
        "wall_s": round(wall, 3),
        "compute_s": round(compute, 3),
        "goodput": round(compute / wall, 6) if wall > 0 else 0.0,
        "lost_work_s": round(
            sum(float(s.get("lost_work_s", 0.0)) for s in rows), 3),
        "resumes": sum(int(s.get("resumes", 0)) for s in rows),
    }


def clusterz_payload(timeout_s=5.0, channel=None, threshold=None) -> dict:
    """The ``/clusterz`` endpoint body: publish this rank's snapshot,
    collect every peer's, run straggler detection, and record the verdict
    into the flight recorder (a fleet post-mortem must carry the same
    evidence the live view showed)."""
    publish(channel=channel)
    by_rank, missing = collect(timeout_s=timeout_s, channel=channel)
    stragglers, median = detect_stragglers(by_rank, threshold=threshold)
    thr = (float(threshold) if threshold is not None
           else float(flag("straggler_threshold")))
    payload = {
        "rank": _flight._safe_rank(),
        "world": _flight._safe_world(),
        "time": time.time(),
        "ranks": [by_rank[r] for r in sorted(by_rank)],
        "missing_ranks": missing,
        "median_step_ms": round(median, 3),
        "straggler_threshold": thr,
        "stragglers": stragglers,
        "fleet_goodput": _fleet_goodput(by_rank),
    }
    if stragglers or missing:
        _flight.record_event(
            "straggler_verdict",
            stragglers=[s["rank"] for s in stragglers],
            missing_ranks=missing,
            median_step_ms=round(median, 3),
            threshold=thr)
    for cb in list(_VERDICT_LISTENERS):
        try:
            cb(payload)
        except Exception as e:  # a policy bug must not break /clusterz
            _flight.record_event("verdict_listener_failed",
                                 error=f"{type(e).__name__}: {e}"[:200])
    return payload


class ClusterPublisher:
    """Daemon thread publishing this rank's snapshot every ``interval_s``
    seconds (one KV set — overwrite — per period; the collector side pays
    the reads). Started by ``install_from_flags`` on multi-process worlds
    when ``FLAGS_cluster_metrics_interval_s`` > 0."""

    def __init__(self, interval_s, channel=None):
        self.interval_s = float(interval_s)
        if self.interval_s <= 0:
            raise ValueError("publisher interval must be > 0 "
                             "(0 disables — don't construct one)")
        self._channel = channel
        self._stop = threading.Event()
        self._thread = None
        self.published = 0

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self):
        if self.alive:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="ptpu-cluster-publisher", daemon=True)
        self._thread.start()
        _flight.record_event("cluster_publisher_start",
                             interval_s=self.interval_s)
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.interval_s + 1.0)
        self._thread = None

    def _run(self):
        # publish immediately so a collector never waits a full period
        # for the first row, then every interval until stopped
        while True:
            try:
                if publish(channel=self._channel):
                    self.published += 1
            except Exception:  # the publisher must never kill the run
                pass
            if self._stop.wait(self.interval_s):
                return


_publisher = [None]


def publisher() -> ClusterPublisher | None:
    return _publisher[0]


def start_publisher(interval_s=None, channel=None) -> ClusterPublisher | None:
    """Start the global publisher (idempotent). ``interval_s`` defaults
    to ``FLAGS_cluster_metrics_interval_s``; <=0 leaves it off."""
    if interval_s is None:
        interval_s = flag("cluster_metrics_interval_s")
    if not interval_s or float(interval_s) <= 0:
        return None
    pub = _publisher[0]
    if pub is not None and pub.alive:
        return pub
    pub = ClusterPublisher(float(interval_s), channel=channel).start()
    _publisher[0] = pub
    return pub


def stop_publisher():
    pub = _publisher[0]
    if pub is not None:
        pub.stop()
    _publisher[0] = None
