"""Lifetime training goodput/badput ledger.

The windowed TrainingMonitor answers "how fast is the current window";
nothing answered "over this job's LIFETIME, what fraction of wall-clock
produced committed steps, and where did the rest go?" — the question
every TPU cost comparison starts from. This ledger classifies every
second of training wall time into exclusive phases:

- ``compute``      — productive step time (committed steps, minus any
  instrumented sub-phase that ran inside the step frame)
- ``input_wait``   — blocked on the data pipeline (the DataLoader's
  existing ``record_input_wait_ms`` feed)
- ``compile``      — trace + XLA compile (runtime/compiled.py AOT spans)
- ``checkpoint``   — snapshot capture/serialize/publish on the step path
- ``restore``      — checkpoint restore on (re)start
- ``renegotiate``  — elastic world renegotiation
- ``lost_work``    — restart badput: steps RECOMPUTED after a resume
  because they committed after the manifest the job restarted from
- ``aborted``      — wall time of steps whose body raised
- ``idle``         — the unattributed residual (wall − everything else)

Phases are mutually exclusive and conserve by construction: ``idle`` is
the residual, so the categories sum to measured wall exactly unless a
bug double-counts (surfaced as ``conservation_error > 0``). Work noted
from a thread other than the one owning the live step frame (the async
checkpoint writer publishing under compute) is *background* — reported
separately, excluded from the conservation sum, because overlapped work
costs no wall time.

Restart continuity: the ledger persists a ``GOODPUT.json`` sidecar with
the checkpoint discipline (tmp → fsync → atomic rename, embedded CRC32)
on a step-commit cadence (``FLAGS_goodput_publish_interval_s``) and
after every checkpoint publication. A kill -9 restart loads it and
CONTINUES the lifetime accounting: restored totals land under
``lifetime``, the restored ``max_committed_step`` prices the resume's
recomputation window (``note_resume``), and steps re-committed inside
that window are charged to ``lost_work``, not ``compute``.

Surfaces: ``goodput/seconds_total{phase=…}`` labeled counters (plus
``goodput/wall_seconds_total`` / ``goodput/badput_seconds_total`` for
the optional burn-rate SLO — :func:`install_goodput_slo`), the debug
server's ``/goodputz``, per-rank rows in ``/clusterz``, a "goodput
phases" track in ``export_merged_chrome_trace``, and the periodic
``[monitor:goodput]`` line the TrainingMonitor emits alongside its own.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
import zlib

from ..flags import flag
from . import registry as _reg

__all__ = [
    "PHASES",
    "SIDECAR",
    "GoodputLedger",
    "active_ledger",
    "start_ledger",
    "stop_ledger",
    "reset_ledger",
    "maybe_start_from_flags",
    "span",
    "goodputz_payload",
    "install_goodput_slo",
    "chrome_events",
]

# the exclusive foreground phases (idle is the derived residual)
PHASES = ("compute", "input_wait", "compile", "checkpoint", "restore",
          "renegotiate", "lost_work", "aborted")

SIDECAR = "GOODPUT.json"
_FORMAT_VERSION = 1
# synthetic chrome-trace thread id for the phase track (host spans use
# real thread ids; this one must never collide with a live thread name
# row, so it gets its own constant + a thread_name metadata event)
_CHROME_TID = 770077


def _flight():
    from . import flight_recorder

    return flight_recorder


class _Span:
    """Measures one phase interval against the ledger's clock."""

    def __init__(self, ledger, phase):
        self._ledger = ledger
        self._phase = phase
        self._t0 = None

    def __enter__(self):
        self._t0 = self._ledger._clock()
        return self

    def __exit__(self, *exc):
        t1 = self._ledger._clock()
        self._ledger.note_phase(self._phase, t1 - self._t0,
                                t0=self._t0, t1=t1)
        return False


class _NullSpan:
    """Stateless no-op context manager (ledger disabled)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class GoodputLedger:
    """Exclusive-phase wall-time accounting with restart continuity.

    ``dir=None`` keeps the ledger in-memory (unit tests, the bench row);
    ``clock`` is injectable (tests drive a fake clock). All mutators are
    lock-protected: phase notes arrive from the step thread, the async
    checkpoint writer, and the debug-server scrape thread concurrently.
    """

    def __init__(self, dir=None, clock=None, publish_interval_s=None):
        self.dir = str(dir) if dir else None
        self._clock = clock or time.perf_counter
        self._publish_interval_s = publish_interval_s
        self._lock = threading.RLock()
        self.phase_s = {p: 0.0 for p in PHASES}
        self.background_s: dict = {}
        self.steps = 0
        self.lost_steps = 0
        self.resumes = 0
        self.max_committed_step = -1
        self.recompute_until = -1
        self.lost_work_priced_s = 0.0
        self.downtime_s = 0.0
        self.sidecar_loaded = False
        # trailing step times price a resume's lost work before the
        # recomputation has actually been paid for
        self._mean_window = collections.deque(maxlen=32)
        self._restored_mean_step_s = 0.0
        # lifetime totals restored from the sidecar (previous lives)
        self._base_phases = {p: 0.0 for p in PHASES}
        self._base_wall_s = 0.0
        self._base_idle_s = 0.0
        self._base_steps = 0
        self._base_lost_steps = 0
        self._base_resumes = 0
        # live step frame (owner-thread gated)
        self._frame_t0 = None
        self._frame_thread = None
        self._frame_overlap = 0.0
        # bounded phase-interval buffer for the chrome-trace track
        self._intervals: collections.deque = collections.deque(maxlen=4096)
        # prometheus flush watermarks (counters are monotone; idle and
        # badput can transiently shrink while a span is in flight, so
        # flushes clamp at the high-water mark)
        self._flushed: dict = {}
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)
            self._load_sidecar()
        self._t0 = self._clock()
        self._last_publish = self._t0

    # -- step frames --------------------------------------------------------

    def step_begin(self):
        """Open a step frame on the calling thread. Sub-phases noted on
        this thread while the frame is open (compile inside the step,
        input wait, a sync checkpoint) are deducted from the frame's
        compute at commit, keeping the phases exclusive."""
        with self._lock:
            self._frame_t0 = self._clock()
            self._frame_thread = threading.get_ident()
            self._frame_overlap = 0.0

    def step_commit(self, global_step=None):
        """Close the frame as a committed step. ``global_step`` (the
        run's global step index) drives lost-work attribution: a step
        re-committed inside the post-resume recomputation window is
        charged to ``lost_work`` instead of ``compute``."""
        with self._lock:
            if self._frame_t0 is None:
                return
            t1 = self._clock()
            dur = max(0.0, t1 - self._frame_t0)
            overlap = min(self._frame_overlap, dur)
            fg = dur - overlap
            recomputed = (global_step is not None
                          and int(global_step) <= self.recompute_until)
            phase = "lost_work" if recomputed else "compute"
            self.phase_s[phase] += fg
            self._intervals.append((phase, self._frame_t0, t1))
            self.steps += 1
            if recomputed:
                self.lost_steps += 1
            else:
                self._mean_window.append(dur)
            if global_step is not None:
                self.max_committed_step = max(self.max_committed_step,
                                              int(global_step))
            self._frame_t0 = None
            self._frame_thread = None
            self._frame_overlap = 0.0
        self._maybe_publish()

    def step_abort(self):
        """Close the frame as badput: the step body raised, so its wall
        time is ``aborted``, never ``compute``."""
        with self._lock:
            if self._frame_t0 is None:
                return
            t1 = self._clock()
            dur = max(0.0, t1 - self._frame_t0)
            fg = dur - min(self._frame_overlap, dur)
            self.phase_s["aborted"] += fg
            self._intervals.append(("aborted", self._frame_t0, t1))
            self._frame_t0 = None
            self._frame_thread = None
            self._frame_overlap = 0.0

    # -- phase notes --------------------------------------------------------

    def note_phase(self, phase, dur_s, t0=None, t1=None):
        """Account ``dur_s`` seconds of ``phase``. Foreground unless a
        step frame is open on a DIFFERENT thread — then the work ran
        overlapped with compute (the async checkpoint writer) and costs
        no wall time, so it lands in the informational ``background_s``
        side table instead of the conservation sum."""
        if phase not in PHASES:
            raise ValueError(f"unknown goodput phase {phase!r}; "
                             f"one of {PHASES}")
        dur_s = max(0.0, float(dur_s))
        with self._lock:
            frame_open = self._frame_t0 is not None
            me = threading.get_ident()
            if frame_open and me != self._frame_thread:
                self.background_s[phase] = (
                    self.background_s.get(phase, 0.0) + dur_s)
                return
            if frame_open:
                # same thread, inside the step frame: the frame's compute
                # share shrinks by exactly this note at commit
                self._frame_overlap += dur_s
            self.phase_s[phase] += dur_s
            if t0 is not None and t1 is not None and dur_s > 0:
                self._intervals.append((phase, t0, t1))

    def span(self, phase):
        """Context manager timing one foreground/background phase."""
        return _Span(self, phase)

    # -- resume pricing -----------------------------------------------------

    def mean_step_s(self) -> float:
        """Trailing mean committed-step duration (sidecar value until
        this life has committed steps of its own)."""
        with self._lock:
            if self._mean_window:
                return sum(self._mean_window) / len(self._mean_window)
            return self._restored_mean_step_s

    def note_resume(self, manifest_step):
        """Called after a checkpoint restore with the manifest's step:
        every step committed in a previous life AFTER that manifest
        (``max_committed_step`` from the sidecar) must be recomputed, so
        commits up to ``recompute_until`` become ``lost_work``. The
        priced estimate (steps lost × trailing mean step time) is
        recorded immediately so the resume event carries a cost figure
        before the recomputation has actually run."""
        with self._lock:
            manifest_step = int(manifest_step)
            self.resumes += 1
            self.recompute_until = max(self.recompute_until,
                                       self.max_committed_step)
            steps_lost = max(0, self.max_committed_step - manifest_step)
            priced = steps_lost * self.mean_step_s()
            self.lost_work_priced_s += priced
        _flight().record_event(
            "goodput_resume", manifest_step=manifest_step,
            max_committed_step=self.max_committed_step,
            steps_to_recompute=steps_lost,
            priced_lost_work_s=round(priced, 3))

    # -- reporting ----------------------------------------------------------

    def wall_s(self) -> float:
        """This process's measured wall since the ledger started."""
        return max(0.0, self._clock() - self._t0)

    def snapshot(self) -> dict:
        """Phase accounting as plain data: this process + lifetime.
        ``idle`` is the residual, so ``sum(phases) == wall_s`` holds by
        construction; ``conservation_error`` > 0 means a phase was
        double-counted (the contract the smoke asserts ≤ 2%)."""
        with self._lock:
            wall = self.wall_s()
            fg = dict(self.phase_s)
            attributed = sum(fg.values())
            idle = max(0.0, wall - attributed)
            err = max(0.0, attributed - wall) / max(wall, 1e-9)
            life_wall = self._base_wall_s + wall
            life = {p: self._base_phases.get(p, 0.0) + fg[p]
                    for p in PHASES}
            life["idle"] = self._base_idle_s + idle
            life_compute = life["compute"]
            return {
                "enabled": True,
                "dir": self.dir,
                "wall_s": wall,
                "phases": {**fg, "idle": idle},
                "background_s": dict(self.background_s),
                "goodput": fg["compute"] / max(wall, 1e-9),
                "steps": self.steps,
                "lost_steps": self.lost_steps,
                "resumes": self.resumes,
                "max_committed_step": self.max_committed_step,
                "recompute_until": self.recompute_until,
                "mean_step_s": self.mean_step_s(),
                "lost_work_priced_s": self.lost_work_priced_s,
                "downtime_s": self.downtime_s,
                "sidecar_loaded": self.sidecar_loaded,
                "conservation_error": err,
                "lifetime": {
                    "wall_s": life_wall,
                    "phases": life,
                    "goodput": life_compute / max(life_wall, 1e-9),
                    "steps": self._base_steps + self.steps,
                    "lost_steps": self._base_lost_steps + self.lost_steps,
                    "resumes": self._base_resumes + self.resumes,
                },
            }

    def flush_metrics(self):
        """Reflect lifetime totals into the registry: the labeled
        ``goodput/seconds_total{phase=…}`` family plus the wall/badput
        counters the SLO objective reads. Counters are monotone, so each
        phase flushes the positive delta past its high-water mark (idle
        and badput can transiently shrink while a span is in flight)."""
        snap = self.snapshot()
        life = snap["lifetime"]
        fam = _reg.counter(
            "goodput/seconds_total",
            help="lifetime training wall seconds by exclusive phase")
        with self._lock:
            for phase, cur in life["phases"].items():
                prev = self._flushed.get(phase, 0.0)
                if cur > prev:
                    fam.labels(phase=phase).inc(cur - prev)
                    self._flushed[phase] = cur
            pairs = (
                ("__wall__", "goodput/wall_seconds_total",
                 life["wall_s"]),
                ("__badput__", "goodput/badput_seconds_total",
                 life["wall_s"] - life["phases"]["compute"]),
            )
            for key, name, cur in pairs:
                prev = self._flushed.get(key, 0.0)
                if cur > prev:
                    _reg.counter(name).inc(cur - prev)
                    self._flushed[key] = cur
        return snap

    def emit_line(self, log_fn=print):
        """One parseable ``[monitor:goodput]`` line (lifetime values)."""
        from .training_monitor import _fmt_util

        s = self.snapshot()
        life = s["lifetime"]
        ph = life["phases"]
        line = (
            f"[monitor:goodput] wall_s={life['wall_s']:.3f} "
            f"goodput={_fmt_util(life['goodput'])} "
            f"compute_s={ph['compute']:.3f} "
            f"input_wait_s={ph['input_wait']:.3f} "
            f"compile_s={ph['compile']:.3f} "
            f"checkpoint_s={ph['checkpoint']:.3f} "
            f"restore_s={ph['restore']:.3f} "
            f"renegotiate_s={ph['renegotiate']:.3f} "
            f"lost_work_s={ph['lost_work']:.3f} "
            f"aborted_s={ph['aborted']:.3f} "
            f"idle_s={ph['idle']:.3f} "
            f"steps={life['steps']} "
            f"lost_steps={life['lost_steps']} "
            f"resumes={life['resumes']}"
        )
        log_fn(line)
        return line

    def chrome_events(self) -> list:
        """The recorded phase intervals as chrome-trace "X" events on a
        synthetic "goodput phases" track. Interval timestamps share the
        host-span clock family (perf_counter seconds → µs), so the track
        lines up against RecordEvent spans without re-basing."""
        with self._lock:
            intervals = list(self._intervals)
        if not intervals:
            return []
        pid = os.getpid()
        events = [{"name": "thread_name", "ph": "M", "pid": pid,
                   "tid": _CHROME_TID,
                   "args": {"name": "goodput phases"}}]
        for phase, t0, t1 in intervals:
            events.append({
                "name": f"goodput::{phase}", "ph": "X", "pid": pid,
                "tid": _CHROME_TID, "ts": t0 * 1e6,
                "dur": max(t1 - t0, 0.0) * 1e6, "cat": "goodput",
            })
        return events

    # -- sidecar persistence ------------------------------------------------

    def _sidecar_path(self) -> str:
        return os.path.join(self.dir, SIDECAR)

    @staticmethod
    def _body_crc(body) -> int:
        return zlib.crc32(
            json.dumps(body, sort_keys=True).encode("utf-8")) & 0xFFFFFFFF

    def publish(self, force=True):
        """Durably publish lifetime totals: write + fsync a ``.tmp``,
        then one atomic ``os.replace`` — the checkpoint publication
        discipline, so a kill -9 leaves either the old sidecar or the
        new one, never a torn file. The embedded CRC32 catches torn
        WRITES (power loss mid-page) at load time."""
        if not self.dir:
            return None
        snap = self.snapshot()
        life = snap["lifetime"]
        body = {
            "format": _FORMAT_VERSION,
            "wall_s": life["wall_s"],
            "phases": {p: life["phases"][p] for p in PHASES},
            "idle_s": life["phases"]["idle"],
            "steps": life["steps"],
            "lost_steps": life["lost_steps"],
            "resumes": life["resumes"],
            "max_committed_step": self.max_committed_step,
            "mean_step_s": self.mean_step_s(),
            "time": time.time(),
        }
        doc = json.dumps({"crc32": self._body_crc(body), "body": body},
                         sort_keys=True).encode("utf-8")
        final = self._sidecar_path()
        tmp = final + ".tmp"
        with open(tmp, "wb") as f:
            f.write(doc)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        with self._lock:
            self._last_publish = self._clock()
        return final

    def _maybe_publish(self):
        if not self.dir:
            return
        interval = self._publish_interval_s
        if interval is None:
            try:
                interval = float(flag("goodput_publish_interval_s"))
            except Exception:
                interval = 30.0
        if self._clock() - self._last_publish >= interval:
            try:
                self.publish()
            except OSError as e:  # a full disk must not kill the step
                _flight().record_event(
                    "goodput_publish_failed",
                    error=f"{type(e).__name__}: {e}"[:200])

    def _load_sidecar(self):
        path = self._sidecar_path()
        try:
            with open(path, "rb") as f:
                doc = json.loads(f.read().decode("utf-8"))
            body = doc["body"]
            if int(doc["crc32"]) != self._body_crc(body):
                raise ValueError("crc mismatch")
            phases = body["phases"]
            self._base_wall_s = float(body["wall_s"])
            self._base_phases = {p: float(phases.get(p, 0.0))
                                 for p in PHASES}
            self._base_idle_s = float(body.get("idle_s", 0.0))
            self._base_steps = int(body.get("steps", 0))
            self._base_lost_steps = int(body.get("lost_steps", 0))
            self._base_resumes = int(body.get("resumes", 0))
            self.max_committed_step = int(
                body.get("max_committed_step", -1))
            self._restored_mean_step_s = float(
                body.get("mean_step_s", 0.0))
            self.downtime_s = max(0.0,
                                  time.time() - float(body.get("time", 0)))
            self.sidecar_loaded = True
            _flight().record_event(
                "goodput_sidecar_resumed", path=path,
                lifetime_wall_s=round(self._base_wall_s, 3),
                max_committed_step=self.max_committed_step,
                downtime_s=round(self.downtime_s, 3))
        except FileNotFoundError:
            pass  # first life: fresh accounting
        except Exception as e:
            # corrupt/torn/incompatible sidecar: start fresh, loudly —
            # lifetime continuity is best-effort, never a crash
            _flight().record_event(
                "goodput_sidecar_corrupt", path=path,
                error=f"{type(e).__name__}: {e}"[:200])

    def close(self):
        """Final flush: publish the sidecar and sync the registry."""
        try:
            self.flush_metrics()
        finally:
            if self.dir:
                self.publish()


# ---------------------------------------------------------------------------
# module-level singleton + hook facades
# ---------------------------------------------------------------------------


_LEDGER: list = [None]


def active_ledger() -> GoodputLedger | None:
    """The process-wide ledger (or None when goodput is off)."""
    return _LEDGER[0]


def start_ledger(dir=None, clock=None,
                 publish_interval_s=None) -> GoodputLedger:
    """Start (or return) the process-wide ledger — idempotent, so every
    entrypoint can call it without fighting over the wall clock's t0."""
    led = _LEDGER[0]
    if led is None:
        led = GoodputLedger(dir=dir, clock=clock,
                            publish_interval_s=publish_interval_s)
        _LEDGER[0] = led
    return led


def stop_ledger():
    """Close (final publish + metric flush) and detach the ledger."""
    led = _LEDGER[0]
    _LEDGER[0] = None
    if led is not None:
        led.close()


def reset_ledger():
    """Drop the ledger WITHOUT a final publish (test isolation)."""
    _LEDGER[0] = None


def maybe_start_from_flags() -> GoodputLedger | None:
    """Start the ledger iff ``FLAGS_goodput_dir`` is set (the
    TrainingMonitor calls this, so any monitored run is one env var away
    from lifetime accounting). Returns the active ledger either way."""
    led = _LEDGER[0]
    if led is not None:
        return led
    d = str(flag("goodput_dir") or "").strip()
    if not d:
        return None
    return start_ledger(dir=d)


def span(phase):
    """Zero-cost-when-off phase span for instrumentation sites:
    ``with goodput.span("compile"): ...`` — a shared no-op context
    manager when no ledger is active."""
    led = _LEDGER[0]
    return led.span(phase) if led is not None else _NULL_SPAN


def goodputz_payload() -> dict:
    """The ``/goodputz`` endpoint body (registry flushed as a side
    effect, so a scrape right after shows the same totals)."""
    led = _LEDGER[0]
    if led is None:
        return {"enabled": False,
                "hint": "set FLAGS_goodput_dir to enable the ledger"}
    return led.flush_metrics()


def chrome_events() -> list:
    """Phase-track events for export_merged_chrome_trace ([] when the
    ledger is off)."""
    led = _LEDGER[0]
    return led.chrome_events() if led is not None else []


def install_goodput_slo(target=None, window_s=3600.0):
    """Install the goodput-ratio objective through the burn-rate engine:
    error mode with badput as the bad counter over wall as the total, so
    "goodput >= target" alerts exactly like a serving availability SLO.
    ``target`` defaults to ``FLAGS_goodput_slo_target``; <= 0 installs
    nothing and returns None."""
    if target is None:
        target = float(flag("goodput_slo_target"))
    if not target or float(target) <= 0:
        return None
    from . import slo as _slo

    s = _slo.SLO("goodput", "goodput/badput_seconds_total",
                 error_ratio="goodput/wall_seconds_total",
                 target=float(target), window_s=float(window_s))
    return _slo.install_slo(s)
