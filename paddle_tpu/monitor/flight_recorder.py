"""Flight recorder: post-mortem + live fault diagnosis for unhealthy runs.

PR 2 made healthy runs legible; an *unhealthy* run — a hung collective, a
desynced fleet worker, a stalled PS RPC, a NaN blow-up — still died dark.
This module is the black box the whole stack reports into (the role
PyTorch's c10d flight recorder plays for NCCL, and the reference's
VLOG-on-crash breadcrumbs played for the fluid runtime):

- :class:`FlightRecorder` — a lock-cheap fixed-capacity ring buffer of
  structured events: executor run begin/end (program id + plan/jit cache
  disposition), ``program_verify`` verdicts (the IR verifier's pass/fail
  per program version, with the offending op/var on failure — so a
  rejected program is in the black box even when the raising process
  dies), every collective call with a **per-group monotonic sequence
  number** and a shape/dtype/reduce-op **fingerprint**, PS RPC
  send/recv, DataLoader epoch/worker lifecycle, flag changes, XLA compile
  events. Dumped to JSON on unhandled exception, on ``SIGUSR1``, and on
  watchdog trip.
- :class:`HangWatchdog` — a daemon thread behind
  ``FLAGS_watchdog_timeout_s`` that fires when no executor step /
  collective / PS reply completes within the deadline, dumping the
  recorder plus every Python thread's stack.
- **Collective desync detection** — on watchdog trip or barrier timeout,
  ranks exchange their per-group (seq, fingerprint) tails over the
  side channel every multi-process fleet run already has (the
  jax.distributed coordination-service KV store that backed the gloo
  rendezvous) and :func:`first_divergence` names the first mismatched
  call per rank — a mismatched ``all_reduce`` stops being a silent
  deadlock and becomes "group dp diverges at seq 41: rank0 issued
  all_reduce|(1024,)|float32|sum, rank1 issued all_gather|...".

Recording rides hot paths always-on (``FLAGS_flight_recorder``), so the
per-event cost budget is one flag read, one dict build, and one short
lock hold — measured by bench.py's ``flight_recorder_overhead`` row
(<2% on the executor-dispatch micro-bench).
"""
from __future__ import annotations

import collections
import json
import os
import signal
import sys
import tempfile
import threading
import time
import traceback

from ..flags import flag
from . import tracing as _tracing

__all__ = [
    "FlightRecorder", "HangWatchdog",
    "get_recorder", "record_event", "record_collective", "events",
    "reset_recorder", "dump_now", "default_dump_path",
    "notify_progress", "last_progress_age_s",
    "first_divergence", "exchange_and_diagnose",
    "install", "install_from_flags",
    "start_watchdog", "stop_watchdog", "watchdog",
    "thread_stacks",
]

# per-group collective tail length kept for desync diagnosis — long
# enough to reach back past a divergence that happened many calls before
# anyone hung, bounded so a week-long run holds kilobytes, not gigabytes
_TAIL_LEN = 256

_t0_monotonic = time.monotonic()


def _safe_rank() -> int:
    """Process rank WITHOUT touching the XLA backend (the recorder must
    work inside crash handlers, where initializing jax is off the table)."""
    try:
        return int(os.getenv("PADDLE_TRAINER_ID", os.getenv("RANK", "0")))
    except ValueError:
        return 0


def _safe_world() -> int:
    try:
        return int(os.getenv("PADDLE_TRAINERS_NUM",
                             os.getenv("WORLD_SIZE", "1")))
    except ValueError:
        return 1


def _safe_flags() -> dict:
    try:
        from ..flags import globals_view

        return {k: v for k, v in globals_view().items()}
    except Exception:
        return {}


def thread_stacks() -> dict:
    """Every Python thread's current stack (faulthandler-style, but
    structured): ``{"<name>-<tid>": [frame lines...]}``."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        key = f"{names.get(tid, 'unknown')}-{tid}"
        out[key] = [line.rstrip("\n")
                    for line in traceback.format_stack(frame)]
    return out


class FlightRecorder:
    """Fixed-capacity ring buffer of structured runtime events.

    One lock, held only for the deque append / seq bump — recording is a
    hot-path citizen, reading (snapshot/dump) pays the copies. Events are
    plain dicts with ``i`` (global index — monotonic, so ``dropped`` in a
    snapshot says exactly how much history the ring evicted), ``t``
    (epoch seconds) and ``kind``.
    """

    def __init__(self, capacity=None):
        if capacity is None:
            try:
                capacity = int(flag("flight_recorder_capacity"))
            except Exception:
                capacity = 4096
        self._capacity = max(1, int(capacity))
        self._buf = collections.deque(maxlen=self._capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._coll_seq = {}   # group -> next per-group collective seq
        self._tails = {}      # group -> deque[(seq, fingerprint)]

    @property
    def enabled(self) -> bool:
        try:
            return bool(flag("flight_recorder"))
        except Exception:
            return True

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def total_recorded(self) -> int:
        """Monotonic count of events ever recorded (ring eviction does
        not decrement it — matches the dump's ``events_recorded``)."""
        with self._lock:
            return self._seq

    # -- recording -----------------------------------------------------------

    def record(self, kind, **fields):
        """Append one structured event; no-op (None) when disabled.

        Events recorded inside an active trace cite its ``trace_id`` —
        a flight-recorder post-mortem (NaN dump, watchdog trip) can
        name the exact request/step whose trace to pull from
        ``/tracez``, and a trace can be grepped out of a dump."""
        if not self.enabled:
            return None
        ev = {"i": 0, "t": time.time(), "kind": kind}
        ctx = _tracing.current_context()
        if ctx is not None:
            ev["trace_id"] = ctx.trace_id
        ev.update(fields)
        with self._lock:
            ev["i"] = self._seq
            self._seq += 1
            self._buf.append(ev)
        return ev

    def record_collective(self, primitive, group, shape=None, dtype=None,
                          reduce_op=None, traced=False, nbytes=0,
                          sequenced=True):
        """Record one collective call: assigns the group's next monotonic
        sequence number and a ``primitive|shape|dtype|reduce_op``
        fingerprint, and appends both to the group's desync tail.
        Returns the seq (None when disabled).

        Trace-time calls (``traced=True``) and rank-local utilities
        (``sequenced=False`` — e.g. ``wait``, which any single rank may
        legally call alone) land in the event ring but do NOT consume a
        seq or touch the tails: one trace stands for N executions,
        retraces are rank-asymmetric (one rank's jit-cache miss is
        another's hit), and a lone rank timing a step must not read as
        desync. The cross-rank comparison is over *issued* logically-
        collective eager calls only.
        """
        if not self.enabled:
            return None
        shape_s = tuple(int(d) for d in shape) if shape is not None else ()
        fp = f"{primitive}|{shape_s}|{dtype or ''}|{reduce_op or ''}"
        if traced or not sequenced:
            self.record("collective", primitive=primitive, group=group,
                        seq=None, fingerprint=fp, traced=bool(traced),
                        nbytes=int(nbytes))
            return None
        with self._lock:
            seq = self._coll_seq.get(group, 0)
            self._coll_seq[group] = seq + 1
            tail = self._tails.get(group)
            if tail is None:
                tail = self._tails[group] = collections.deque(
                    maxlen=_TAIL_LEN)
            tail.append((seq, fp))
        self.record("collective", primitive=primitive, group=group,
                    seq=seq, fingerprint=fp, traced=False,
                    nbytes=int(nbytes))
        return seq

    # -- reading -------------------------------------------------------------

    def events(self) -> list:
        with self._lock:
            return list(self._buf)

    def collective_tails(self) -> dict:
        """Per-group desync tails: ``{group: [(seq, fingerprint), ...]}``."""
        with self._lock:
            return {g: list(t) for g, t in self._tails.items()}

    def reset(self):
        with self._lock:
            self._buf.clear()
            self._seq = 0
            self._coll_seq.clear()
            self._tails.clear()

    def snapshot(self, reason="snapshot", desync=None) -> dict:
        """The full dump payload as plain data (what every dump trigger
        and the /flightrecorder endpoint serve)."""
        evs = self.events()
        with self._lock:
            total = self._seq
        snap = {
            "reason": reason,
            "time": time.time(),
            "pid": os.getpid(),
            "rank": _safe_rank(),
            "world": _safe_world(),
            "uptime_s": round(time.monotonic() - _t0_monotonic, 3),
            "capacity": self._capacity,
            "events_recorded": total,
            "dropped": max(0, total - len(evs)),
            "events": evs,
            "collective_tails": self.collective_tails(),
            "threads": thread_stacks(),
            "flags": _safe_flags(),
        }
        if desync is not None:
            snap["desync"] = desync
        return snap

    def dump(self, path=None, reason="dump", desync=None) -> str:
        """Write the snapshot as JSON (atomically: tmp + rename, so a
        crash mid-dump never leaves a half-written file that a post-
        mortem tool chokes on). Returns the path."""
        snap = self.snapshot(reason=reason, desync=desync)
        if path is None:
            path = default_dump_path(reason)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=1, default=str)
        os.replace(tmp, path)
        sys.stderr.write(
            f"[flight_recorder] rank {snap['rank']}: dumped "
            f"{len(snap['events'])} events -> {path} (reason: {reason})\n")
        return path


_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _RECORDER


def record_event(kind, **fields):
    return _RECORDER.record(kind, **fields)


def record_collective(primitive, group, **kwargs):
    return _RECORDER.record_collective(primitive, group, **kwargs)


def events() -> list:
    return _RECORDER.events()


def reset_recorder():
    _RECORDER.reset()


def default_dump_path(reason="dump") -> str:
    """``<FLAGS_flight_recorder_dump_dir or tempdir>/paddle_tpu_flight_
    rank<r>_pid<pid>_<reason-slug>.json`` — rank+pid keyed so every
    process of a fleet world dumps without clobbering peers on a shared
    filesystem, and reason-slug keyed so distinct triggers never
    clobber each other (a barrier-failure dump carrying the desync
    report must survive the excepthook dump the re-raised error writes
    moments later). Same-reason re-dumps (a watchdog re-tripping)
    overwrite in place: latest evidence wins, disk use stays bounded."""
    try:
        d = flag("flight_recorder_dump_dir")
    except Exception:
        d = ""
    d = d or tempfile.gettempdir()
    slug = "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(reason))[:48] or "dump"
    return os.path.join(
        d, f"paddle_tpu_flight_rank{_safe_rank()}_pid{os.getpid()}"
           f"_{slug}.json")


def dump_now(reason="request", path=None, desync=None) -> str:
    """Dump the global recorder immediately (the SIGUSR1 handler's body,
    also the programmatic trigger)."""
    return _RECORDER.dump(path=path, reason=reason, desync=desync)


def nan_event_action(where, detail):
    """Shared ``FLAGS_check_nan_inf_action`` policy for every NaN/Inf
    detection site (the executor's post-run scan, the checkify train
    step): validates the flag value, bumps ``debug/nan_events``, records
    the ``nan_inf`` flight event, and performs the non-raising half.

    Returns None when ``action=warn`` consumed the event (the caller
    continues), else the action — the caller must then raise its
    domain-specific error (for ``"dump"`` the snapshot has already been
    written)."""
    from ..flags import flag as _flag

    action = _flag("check_nan_inf_action")
    if action not in ("raise", "warn", "dump"):
        from ..errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"FLAGS_check_nan_inf_action must be raise|warn|dump, "
            f"got {action!r}")
    from . import registry as _registry

    _registry.counter("debug/nan_events").inc()
    record_event("nan_inf", where=str(where), action=action,
                 detail=str(detail)[:300])
    if action == "warn":
        import warnings

        warnings.warn(
            f"check_nan_inf: {detail} (action=warn: continuing; "
            f"debug/nan_events counter bumped)",
            RuntimeWarning, stacklevel=3)
        return None
    if action == "dump":
        dump_now(reason=f"check_nan_inf:{where}")
    return action


# -- progress clock / hang watchdog ------------------------------------------

# [monotonic time of last completed unit of work, what it was]; written
# by the executor (run end), collectives (eager completion), and the PS
# client (reply received) — two plain stores + one clock read, cheap
# enough to ride every completion unconditionally
_last_progress = [time.monotonic(), "startup"]


def notify_progress(what="step"):
    """Feed the watchdog: some unit of forward progress just completed."""
    _last_progress[0] = time.monotonic()
    _last_progress[1] = what


def last_progress_age_s() -> float:
    return time.monotonic() - _last_progress[0]


def last_progress_what() -> str:
    return _last_progress[1]


class HangWatchdog:
    """Daemon thread that trips when the progress clock goes stale.

    On trip: records a ``watchdog_trip`` event, runs the desync exchange
    (if a multi-process side channel exists), and dumps the recorder —
    thread stacks included, so the dump shows *where* every thread is
    parked, not just that nothing moved. The progress clock is re-armed
    after a trip, so a still-hung process re-dumps once per timeout
    period instead of once per poll.
    """

    def __init__(self, timeout_s, recorder=None, poll_interval=None,
                 desync=True, on_trip=None):
        self.timeout_s = float(timeout_s)
        if self.timeout_s <= 0:
            raise ValueError("watchdog timeout must be > 0 (0 disables the "
                             "watchdog — don't construct one)")
        self._recorder = recorder or _RECORDER
        self._poll = (float(poll_interval) if poll_interval
                      else max(0.05, min(self.timeout_s / 4.0, 5.0)))
        self._desync = desync
        self._on_trip = on_trip
        self._stop = threading.Event()
        self._thread = None
        self.trips = 0
        self.last_dump = None

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self):
        if self.alive:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="ptpu-hang-watchdog", daemon=True)
        self._thread.start()
        self._recorder.record("watchdog_start", timeout_s=self.timeout_s)
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self._poll * 4 + 1.0)
        self._thread = None

    def _run(self):
        while not self._stop.wait(self._poll):
            age = last_progress_age_s()
            if age < self.timeout_s:
                continue
            try:
                self._trip(age)
            except Exception as e:  # the watchdog must never kill the run
                sys.stderr.write(f"[flight_recorder] watchdog trip handler "
                                 f"failed: {type(e).__name__}: {e}\n")
            notify_progress("watchdog_rearm")

    def _trip(self, age):
        self.trips += 1
        self._recorder.record(
            "watchdog_trip", age_s=round(age, 3),
            timeout_s=self.timeout_s, trips=self.trips,
            last_progress=last_progress_what())
        desync = None
        if self._desync:
            try:
                # STABLE tag: trip counts are rank-local (a transient
                # first-compile trip on one rank would desynchronize
                # per-trip tags forever, stranding later exchanges on
                # mismatched keys). Every rank always publishes/reads
                # "watchdog"; set() overwrites, so a get returns the
                # peer's latest published tail — possibly from an
                # earlier trip, which for a hung peer is exactly the
                # freshest evidence that exists.
                desync = exchange_and_diagnose(
                    tag="watchdog", recorder=self._recorder)
            except Exception as e:
                desync = {"error": f"{type(e).__name__}: {e}"}
        # stable path (reason varies by age digits): a re-tripping
        # watchdog overwrites its own dump — latest evidence, bounded disk
        self.last_dump = self._recorder.dump(
            path=default_dump_path("watchdog_timeout"),
            reason=f"watchdog_timeout({age:.1f}s > {self.timeout_s:g}s, "
                   f"last progress: {last_progress_what()})",
            desync=desync)
        if self._on_trip is not None:
            self._on_trip(self)


_watchdog = [None]


def watchdog() -> HangWatchdog | None:
    return _watchdog[0]


def start_watchdog(timeout_s=None) -> HangWatchdog | None:
    """Start the global watchdog (idempotent). ``timeout_s`` defaults to
    ``FLAGS_watchdog_timeout_s``; <=0 leaves it off and returns None."""
    if timeout_s is None:
        timeout_s = flag("watchdog_timeout_s")
    if not timeout_s or float(timeout_s) <= 0:
        return None
    wd = _watchdog[0]
    if wd is not None and wd.alive:
        return wd
    notify_progress("watchdog_armed")
    wd = HangWatchdog(float(timeout_s))
    wd.start()
    _watchdog[0] = wd
    return wd


def stop_watchdog():
    wd = _watchdog[0]
    if wd is not None:
        wd.stop()
    _watchdog[0] = None


# -- collective desync detection ---------------------------------------------


def first_divergence(tails_by_rank) -> list:
    """Name the first diverging collective call per group.

    ``tails_by_rank``: ``{rank: {group: [(seq, fingerprint), ...]}}`` —
    each rank's per-group tail as exchanged over the side channel.
    Returns one dict per diverging group::

        {"group": "dp", "seq": 41,
         "fingerprints": {"0": "all_reduce|(1024,)|float32|sum",
                          "1": "all_gather|(1024,)|float32|"},
         "summary": "group 'dp' diverges at seq 41: ..."}

    Comparison happens inside the seq window every rank can still see
    (tails are bounded rings) — a seq evicted on one rank is not
    evidence. A missing fingerprint inside the window (``None``) means
    that rank never issued the call: the skipped-collective case. When
    the common window is fingerprint-identical but ranks stopped at
    different seqs, the first seq past the shortest rank is reported as
    a call-count mismatch (the classic "one rank left the loop early").
    """
    ranks = sorted(tails_by_rank)
    groups = sorted({g for tails in tails_by_rank.values() for g in tails})
    out = []
    for g in groups:
        per = {r: {int(s): f for s, f in tails_by_rank[r].get(g, [])}
               for r in ranks}
        starts = [min(m) for m in per.values() if m]
        ends = [max(m) for m in per.values() if m]
        lo = max(starts) if starts else 0
        hi = max(ends) if ends else -1
        shortest = min(ends) if len(ends) == len(ranks) else -1
        div = None
        for s in range(lo, hi + 1):
            fps = {r: per[r].get(s) for r in ranks}
            if len(set(fps.values())) > 1:
                div = {"group": g, "seq": s,
                       "fingerprints": {str(r): fps[r] for r in ranks}}
                if 0 <= shortest < s:
                    div["note"] = ("call-count mismatch: some ranks "
                                   "stopped issuing collectives earlier")
                break
        if div is not None:
            parts = ", ".join(
                f"rank{r}={div['fingerprints'][str(r)] or 'MISSING'}"
                for r in ranks)
            div["summary"] = (
                f"group {g!r} diverges at seq {div['seq']}: {parts}")
            out.append(div)
    return out


class _JaxKVChannel:
    """The jax.distributed coordination-service KV store — the rendezvous
    side channel every multi-process fleet run already holds open (it is
    what replaced the reference's gloo/gen_nccl_id rendezvous), reused
    here as the desync exchange wire. Values are strings; gets block
    until a peer publishes or the timeout lapses."""

    def __init__(self, client):
        self._client = client

    def set(self, key, value):
        # coordination-service keys are write-once on older jax — a
        # retried exchange (same barrier token failing twice) must
        # overwrite rather than die before any tails are collected
        try:
            self._client.key_value_set(key, value, allow_overwrite=True)
        except TypeError:  # jax without the allow_overwrite kwarg
            self._client.key_value_set(key, value)

    def get(self, key, timeout_s):
        return self._client.blocking_key_value_get(
            key, int(max(timeout_s, 0.001) * 1000))


def _default_channel():
    try:
        from jax._src import distributed as _dist

        client = _dist.global_state.client
        return _JaxKVChannel(client) if client is not None else None
    except Exception:
        return None


def exchange_and_diagnose(tag="trip", timeout_s=15.0, channel=None,
                          rank=None, world=None, recorder=None):
    """Exchange collective tails across ranks and diagnose the first
    divergence (c10d-flight-recorder style).

    Publishes this rank's per-group (seq, fingerprint) tail under
    ``ptpu/flight/<tag>/<rank>`` and collects every peer's, then runs
    :func:`first_divergence`. Returns the report dict, or None when
    there is nothing to exchange (single-process world, or no side
    channel — the eager path must stay harmless). Peers that never
    publish within ``timeout_s`` (crashed before their own trip) are
    listed in ``missing_ranks`` rather than failing the diagnosis —
    a dead peer is itself evidence.

    Every rank that trips calls this with the same ``tag`` (the stable
    ``"watchdog"`` tag, a barrier token), so the keyspace lines up
    without extra coordination; publishes overwrite, so a reused tag
    reads each peer's latest published tail.
    """
    recorder = recorder or _RECORDER
    if rank is None:
        rank = _safe_rank()
    if world is None:
        world = _safe_world()
    if world <= 1:
        return None
    channel = channel or _default_channel()
    if channel is None:
        return None
    tails = recorder.collective_tails()
    payload = json.dumps(
        {g: [[s, f] for s, f in t] for g, t in tails.items()})
    try:
        channel.set(f"ptpu/flight/{tag}/{rank}", payload)
    except Exception as e:
        # best-effort: peers may still have published THEIR tails — a
        # one-sided diagnosis beats none
        recorder.record("desync_publish_failed", tag=str(tag),
                        error=f"{type(e).__name__}: {e}"[:200])
    by_rank = {}
    # ONE shared deadline across all peers: a hung fleet must not pay
    # timeout_s per missing rank (world * timeout_s could hold the
    # watchdog's dump hostage for minutes)
    deadline = time.monotonic() + float(timeout_s)

    def _try_get(r, budget_s):
        try:
            raw = channel.get(f"ptpu/flight/{tag}/{r}",
                              max(budget_s, 0.001))
            if isinstance(raw, bytes):
                raw = raw.decode("utf-8")
            by_rank[r] = {g: [(int(s), f) for s, f in t]
                          for g, t in json.loads(raw).items()}
            return True
        except Exception:
            return False

    # two passes: a quick short-slice sweep first, so one dead LOW rank
    # cannot starve reads of higher ranks whose tails are already
    # published (the dead rank is exactly when cross-rank evidence
    # matters most); whatever deadline remains is then split across the
    # stragglers
    stragglers = [r for r in range(world)
                  if not _try_get(r, min(0.25,
                                         deadline - time.monotonic()))]
    for i, r in enumerate(stragglers):
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        _try_get(r, remaining / (len(stragglers) - i))
    missing = sorted(set(range(world)) - set(by_rank))
    divergences = first_divergence(by_rank)
    report = {
        "tag": str(tag),
        "rank": rank,
        "world": world,
        "missing_ranks": missing,
        "divergences": divergences,
        "tails_by_rank": {str(r): {g: [[s, f] for s, f in t]
                                   for g, t in tails.items()}
                          for r, tails in by_rank.items()},
    }
    recorder.record("desync_report", tag=str(tag),
                    divergences=len(divergences),
                    missing_ranks=missing)
    for d in divergences:
        sys.stderr.write(f"[flight_recorder] rank {rank}: DESYNC "
                         f"{d['summary']}\n")
    return report


# -- crash / signal installation ---------------------------------------------

_installed = {"excepthook": False, "signal": False}


def install(excepthook=True, sig=True):
    """Install the dump triggers that need process-global hooks:

    - unhandled exception: chain onto ``sys.excepthook`` — the dump is
      written *before* the traceback prints, so a crash leaves evidence
      even if stderr is lost;
    - ``SIGUSR1``: faulthandler-style on-demand dump of a live process
      (``kill -USR1 <pid>``) — main-thread only (signal module rule).

    Idempotent; both hooks preserve and call whatever was installed
    before them.
    """
    if excepthook and not _installed["excepthook"]:
        prev_hook = sys.excepthook

        def _dump_excepthook(etype, value, tb):
            try:
                _RECORDER.record("unhandled_exception",
                                 type=etype.__name__,
                                 message=str(value)[:500])
                _RECORDER.dump(reason=f"unhandled_exception:{etype.__name__}")
            except Exception:
                pass
            prev_hook(etype, value, tb)

        sys.excepthook = _dump_excepthook
        _installed["excepthook"] = True

    if (sig and not _installed["signal"] and hasattr(signal, "SIGUSR1")
            and threading.current_thread() is threading.main_thread()):
        prev_handler = signal.getsignal(signal.SIGUSR1)

        def _on_sigusr1(signum, frame):
            try:
                dump_now(reason="SIGUSR1")
            except Exception:
                pass
            if callable(prev_handler):
                prev_handler(signum, frame)

        try:
            signal.signal(signal.SIGUSR1, _on_sigusr1)
            _installed["signal"] = True
        except (ValueError, OSError):
            pass
    return _installed


def install_from_flags():
    """One-call wiring of everything the FLAGS ask for — crash/SIGUSR1
    dumps always, the hang watchdog when ``FLAGS_watchdog_timeout_s``>0,
    and the debug server when ``FLAGS_debug_port``>0 (bound at
    port+rank so a multi-process host serves every rank). Called by
    ``init_parallel_env``; safe to call repeatedly."""
    install()
    wd = start_watchdog()
    server = None
    try:
        port = int(flag("debug_port"))
    except Exception:
        port = 0
    if port > 0:
        from .debug_server import start_debug_server

        try:
            server = start_debug_server(port + _safe_rank())
        except OSError as e:
            sys.stderr.write(
                f"[flight_recorder] debug server bind failed on port "
                f"{port + _safe_rank()}: {e}\n")
            _RECORDER.record("debug_server_bind_failed",
                             port=port + _safe_rank(), error=str(e))
    # cluster metrics publisher (rank-0 /clusterz aggregation feed):
    # multi-process worlds only — a lone process IS its own cluster view
    try:
        interval = float(flag("cluster_metrics_interval_s"))
    except Exception:
        interval = 0.0
    if interval > 0 and _safe_world() > 1:
        from . import cluster as _cluster

        try:
            _cluster.start_publisher(interval)
        except Exception as e:
            _RECORDER.record("cluster_publisher_failed",
                             error=f"{type(e).__name__}: {e}"[:200])
    return wd, server
