"""Telemetry exporters: Prometheus text dump + merged chrome trace.

The reference exposed its StatRegistry through VLOG lines and its
profiler through a chrome trace built from profiler.proto
(device_tracer.cc GenProfile); the two never met in one artifact. Here
both exporters walk the same registry/profiler state:

- :func:`export_prometheus` — text exposition format (the de-facto
  fleet-metrics wire format) over every registered counter/gauge/
  histogram plus the profiler's always-on dispatch counters — including
  the utilization-accounting series (``monitor/<name>/mfu``,
  ``monitor/<name>/hbm_bw_util``, ``cost/<label>/*`` program cost
  gauges, ``cost/executed_*`` ledgers) the cost model feeds.
- :func:`export_merged_chrome_trace` — ONE chrome-trace JSON holding the
  host-side RecordEvent spans and the jax device trace (the
  ``*.trace.json.gz`` files jax.profiler writes), so host dispatch gaps
  line up against device kernel occupancy in the same timeline view.
"""
from __future__ import annotations

import glob
import gzip
import json
import math
import os
import re
import time

from .. import profiler
from . import registry as _reg

__all__ = ["export_prometheus", "export_merged_chrome_trace",
           "prometheus_text", "PROMETHEUS_CONTENT_TYPE"]

# the exposition format's registered media type — scrapers key parsing
# off it, so every HTTP surface serving prometheus_text() (the debug
# server's /metrics) must send exactly this
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4"

# ':' is legal in prometheus names but reserved for recording rules by
# convention — sanitize it away along with '/' and '::'
_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    """Sanitize a registry name into a prometheus metric name."""
    n = _NAME_RE.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return n


def _escape_help(s: str) -> str:
    """Escape a # HELP docstring per the exposition format: backslash
    and newline are the two characters with wire meaning there — an
    unescaped newline would split the help text into a garbage sample
    line that kills the whole scrape."""
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v) -> str:
    # the exposition format defines +Inf/-Inf/NaN literals — a single
    # inf loss-scale sentinel must not crash every later export
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if v != int(v):
            return repr(v)
    return str(int(v))


def _histogram_lines(lines, pname, snap, sel=""):
    """Emit one histogram series (bucket/sum/count). ``sel`` is a
    pre-escaped selector body (``k="v",...`` from format_labels) for a
    labeled child, empty for the bare family."""
    pre = f"{sel}," if sel else ""
    acc = 0
    for le, c in zip(snap["bounds"] + ["+Inf"], snap["buckets"]):
        acc += c
        le_s = le if isinstance(le, str) else repr(float(le))
        lines.append(f'{pname}_bucket{{{pre}le="{le_s}"}} {acc}')
    suffix = f"{{{sel}}}" if sel else ""
    lines.append(f"{pname}_sum{suffix} {_fmt(snap['sum'])}")
    lines.append(f"{pname}_count{suffix} {snap['count']}")


def prometheus_text() -> str:
    """Render the registry + profiler counters in the Prometheus text
    exposition format (one # TYPE line per family, # HELP when the
    metric carries help text).

    Labeled families emit every child series with its label selector
    AND the bare parent series; for counters/histograms the parent is
    the exact aggregate over labels (child updates propagate up in the
    registry), so scrapers that ignore labels keep reading totals.

    Name-collision safety: ``_prom_name`` is lossy ('/' and ':' both
    become '_'), so two distinct registry names can sanitize to the same
    series — emitting both would silently corrupt whichever the scraper
    keeps. That is an error here, naming both originals.
    """
    lines = []
    # sanitized -> source-qualified origin: names are unique within each
    # source, so ANY repeat claim is a duplicate family — including the
    # same raw name living in both the registry and the profiler
    # counters (two '# TYPE x' blocks kill the scrape just as dead as a
    # sanitization clash)
    seen: dict[str, str] = {}

    def _claim(pname, origin):
        prior = seen.get(pname)
        if prior is not None:
            raise ValueError(
                f"prometheus name collision: {origin} and {prior} both "
                f"emit the series {pname!r}; rename one metric")
        seen[pname] = origin

    for name, m in _reg.all_metrics().items():
        pname = _prom_name(name)
        _claim(pname, f"registry metric {name!r}")
        # one snapshot() = one lock acquisition: buckets/sum/count come
        # from the same instant, so a concurrent observe() can never
        # yield a dump where _count disagrees with the +Inf bucket
        snap = m.snapshot()
        if m.help:
            lines.append(f"# HELP {pname} {_escape_help(m.help)}")
        if snap["kind"] == "histogram":
            lines.append(f"# TYPE {pname} histogram")
            _histogram_lines(lines, pname, snap)
            for sel, sub in (snap.get("series") or {}).items():
                _histogram_lines(lines, pname, sub, sel)
        else:
            lines.append(f"# TYPE {pname} {snap['kind']}")
            lines.append(f"{pname} {_fmt(snap['value'])}")
            for sel, sub in (snap.get("series") or {}).items():
                lines.append(f"{pname}{{{sel}}} {_fmt(sub['value'])}")
    # the profiler's always-on dispatch counters live outside the
    # registry (PR 1 predates it); export them under the same roof —
    # collisions with registry names are just as fatal for the scraper
    for name, v in sorted(profiler.counters().items()):
        pname = _prom_name(name)
        _claim(pname, f"profiler counter {name!r}")
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname} {_fmt(v)}")
    return "\n".join(lines) + "\n"


def export_prometheus(path=None) -> str:
    """Write (optional) and return the Prometheus text dump."""
    text = prometheus_text()
    if path:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(text)
    return text


def _device_trace_events(trace_dir):
    """traceEvents from the jax device trace under ``trace_dir``.

    jax.profiler.start_trace writes TensorBoard-layout runs:
    ``<dir>/plugins/profile/<run>/<host>.trace.json.gz`` — each already a
    chrome-trace JSON. Collect every run's events; missing/partial files
    are skipped (the tracer may be unsupported on this backend).
    """
    events = []
    if not trace_dir:
        return events
    pattern = os.path.join(trace_dir, "**", "*.trace.json.gz")
    for fn in sorted(glob.glob(pattern, recursive=True)):
        try:
            with gzip.open(fn, "rt") as f:
                trace = json.load(f)
        except Exception:
            continue
        events.extend(trace.get("traceEvents", []))
    return events


def _align_clock_bases(host, device):
    """Shift device events onto the host span clock.

    Host spans stamp time.perf_counter_ns (arbitrary monotonic epoch);
    the XLA profiler stamps its own base — merged raw, the two tracks
    land as disjoint clusters an enormous offset apart. Both recordings
    start at (approximately) the same instant — start_profiler() starts
    the device trace — so anchoring earliest-to-earliest puts host
    dispatch gaps against device kernel occupancy to within the
    start_trace call latency. Returns the device events shifted in
    place; events without a ts (metadata) pass through untouched.
    """
    host_ts = [e["ts"] for e in host if "ts" in e]
    dev_ts = [e["ts"] for e in device if "ts" in e]
    if not host_ts or not dev_ts:
        return device
    offset = min(host_ts) - min(dev_ts)
    for e in device:
        if "ts" in e:
            e["ts"] = e["ts"] + offset
    return device


def _retained_trace_events(host):
    """Retained per-request traces (monitor.tracing) as chrome events,
    one synthetic thread per trace, re-based onto the host span clock.

    Trace spans stamp epoch time; host spans stamp perf_counter_ns/1e3.
    Unlike the device trace, a retained trace does NOT start when the
    recording starts (a p99 outlier may be retained hours in), so the
    earliest-to-earliest anchoring of ``_align_clock_bases`` would slide
    it to the front of the profile. Both clocks are readable NOW, so one
    paired sample gives the exact offset instead.
    """
    from . import tracing as _tracing

    st = _tracing.store()
    events = []
    for row in st.summaries():
        payload = st.get(row["trace_id"])
        if payload is not None:
            events.extend(_tracing.chrome_events(payload))
    if not host:
        return events  # no host track: epoch timestamps stand alone
    offset_us = time.perf_counter_ns() / 1e3 - time.time() * 1e6
    for e in events:
        if "ts" in e:
            e["ts"] = e["ts"] + offset_us
    return events


def export_merged_chrome_trace(path, device_trace_dir=None) -> str:
    """Write host RecordEvent spans + jax device trace + retained
    request/step traces as one chrome://tracing JSON (device and trace
    clocks re-based onto the host track — see _align_clock_bases).
    ``device_trace_dir`` defaults to the directory of the most recent
    device trace (profiler.device_trace_dir())."""
    if device_trace_dir is None:
        device_trace_dir = profiler.device_trace_dir()
    host = profiler.host_events()
    # label the host track so the merged view reads unambiguously
    events = [{"name": "process_name", "ph": "M", "pid": os.getpid(),
               "args": {"name": "paddle_tpu host"}}]
    events.extend(host)
    events.extend(_align_clock_bases(
        host, _device_trace_events(device_trace_dir)))
    # the tail-sampled traces ride along: a p99 outlier's span tree
    # lands next to the host/device timeline it happened inside
    events.extend(_retained_trace_events(host))
    # goodput phase track (monitor.goodput): same perf_counter clock
    # family as the host spans, so no re-basing — a checkpoint stall or
    # lost-work replay reads directly against dispatch/kernel occupancy
    from . import goodput as _goodput

    events.extend(_goodput.chrome_events())
    # per-op replay tracks (monitor.opprof): one synthetic thread per
    # stored profile, ops laid end-to-end at measured durations —
    # relative layout, so durations/shares/order are the signal, not
    # absolute alignment against the host clock
    from . import opprof as _opprof

    events.extend(_opprof.chrome_events())
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return path
