"""Distributed request tracing: one identity through the whole stack.

The serving and training telemetry built so far is *aggregate* —
``/statz`` quantiles, ``/loadz`` queue depth, merged histograms — and
aggregates cannot answer "why was THIS request slow?". A p99 outlier is
queue wait, or bucket padding, or an unexpected XLA compile, or a router
retry; telling them apart needs a per-request span tree that survives
the router -> backend process hop. This module provides exactly that,
kept deliberately small and always-on-cheap:

- **Trace context** — a contextvar-held current span carrying
  ``(trace_id, span_id)``. Spans nest under it; code that runs outside
  any trace (offline tests, warmup) pays one contextvar read and
  records nothing.
- **Spans** — structured ``{name, trace_id, span_id, parent_id, t,
  dur_ms, attrs, links, error}`` dicts. Hot-path annotation
  (:func:`annotate`) mutates the *current* span so deep layers (the
  executor's plan/jit cache disposition, the cost model's FLOPs) tag
  the request without threading a handle through every signature.
- **W3C-style propagation** — ``traceparent: 00-<trace>-<span>-01``
  headers (:func:`format_traceparent` / :func:`parse_traceparent`).
  The router injects per-attempt headers; ``_BaseHandler`` extracts
  them, so the backend's span tree hangs under the router's attempt
  span: one trace_id, correct parentage, two processes.
- **Tail-sampled trace store** — traces are always *recorded*; only at
  completion does the store decide what to *retain*: every trace that
  erred, missed a deadline, or was retried is kept, plus the slowest-K
  per window (``FLAGS_trace_sample_slowest_k`` /
  ``FLAGS_trace_sample_window_s``); the fast-path bulk is dropped.
  Retention is bounded by ``FLAGS_trace_store_capacity``. This is
  tail-based sampling: the decision happens when the outcome is known,
  so the interesting traces are never the ones sampled away.

Served on ``/tracez`` (debug server and every serving frontend): the
retained list, one trace's span tree by ``?id=``, and a per-trace
chrome-trace view via ``?format=chrome``. ``monitor.export``'s merged
chrome trace embeds the retained traces alongside the host spans.
"""
from __future__ import annotations

import contextvars
import os
import random
import threading
import time
from collections import OrderedDict

from ..flags import flag

__all__ = [
    "TRACEPARENT_HEADER", "SpanContext", "Span", "TraceStore",
    "format_traceparent", "parse_traceparent", "new_trace_id",
    "new_span_id", "enabled", "current_span", "current_context",
    "annotate", "note_status", "start_trace", "start_span", "begin_span",
    "use_span", "record_interval", "record_fanin", "flag_trace",
    "flag_current_trace", "store", "reset_store", "tracez_payload",
    "chrome_events", "parse_query",
]

#: The propagation header (W3C trace-context wire name).
TRACEPARENT_HEADER = "traceparent"

# spans per trace are bounded: a runaway loop inside one request must
# not let a single trace eat the store (generation traces record per
# REQUEST, not per token, so real traces sit far below this)
_MAX_SPANS_PER_TRACE = 512

# stage names the /statz slowest table decomposes a trace into
_STAGE_NAMES = frozenset((
    "queue_wait", "assemble", "dispatch", "slot_admission", "decode",
    "attempt", "run",
))


def enabled() -> bool:
    try:
        return bool(flag("trace_enabled"))
    except Exception:  # flags not bootstrapped yet
        return True


# id generation is on the per-span hot path (bench.py tracing_overhead):
# a per-thread PRNG seeded once from os.urandom replaces a urandom
# syscall per id with ~0.5µs of Mersenne twister — span ids need
# uniqueness, not crypto strength
_ids = threading.local()


def _rng() -> random.Random:
    rng = getattr(_ids, "rng", None)
    if rng is None:
        rng = _ids.rng = random.Random(
            int.from_bytes(os.urandom(16), "big") ^ (os.getpid() << 64))
    return rng


def new_trace_id() -> str:
    """32-hex trace id (all-zero is invalid on the wire, hence ``| 1``)."""
    return f"{_rng().getrandbits(128) | 1:032x}"


def new_span_id() -> str:
    """16-hex span id."""
    return f"{_rng().getrandbits(64) | 1:016x}"


class SpanContext:
    """Immutable (trace_id, span_id) pair — the thing that crosses
    process boundaries and the thing a request handle stores."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"SpanContext({self.trace_id[:8]}…, {self.span_id})"


def format_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


_HEX_DIGITS = frozenset("0123456789abcdefABCDEF")


def _is_hex(s: str) -> bool:
    # strict charset check: int(s, 16) would also accept '0x' prefixes,
    # leading '+', and interior underscores — all W3C-malformed
    return all(c in _HEX_DIGITS for c in s)


def parse_traceparent(header) -> SpanContext | None:
    """Parse a ``traceparent`` header; ``None`` on anything malformed
    (a garbage header from an arbitrary client must never 500 the
    request — it just starts a fresh trace)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if (len(version) != 2 or not _is_hex(version)
            or version.lower() == "ff"):
        return None
    if len(_flags) != 2 or not _is_hex(_flags):
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id) \
            or trace_id == "0" * 32:
        return None
    if len(span_id) != 16 or not _is_hex(span_id) \
            or span_id == "0" * 16:
        return None
    return SpanContext(trace_id.lower(), span_id.lower())


class Span:
    """One timed, attributed operation. ``trace_id`` may be ``None``
    for a *detached* span (:func:`begin_span`): it is timed and
    annotatable but only enters the store through
    :func:`record_fanin`, which rebinds it into member traces."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "links", "error", "root", "t_epoch", "_t0",
                 "duration_ms")

    def __init__(self, name, trace_id=None, parent_id=None, root=False,
                 attrs=None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else {}
        self.links = None
        self.error = None
        self.root = bool(root)
        self.t_epoch = time.time()
        self._t0 = time.monotonic()
        self.duration_ms = None

    def __bool__(self):
        return True

    @property
    def context(self) -> SpanContext | None:
        if self.trace_id is None:
            return None
        return SpanContext(self.trace_id, self.span_id)

    def set_attribute(self, key, value):
        if value is not None:
            self.attrs[key] = value
        return self

    def set_attributes(self, **attrs):
        for k, v in attrs.items():
            if v is not None:
                self.attrs[k] = v
        return self

    def set_error(self, message):
        self.error = str(message)[:300]
        return self

    def end(self):
        if self.duration_ms is None:
            self.duration_ms = (time.monotonic() - self._t0) * 1e3
        return self

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t": self.t_epoch,
            "dur_ms": round(self.duration_ms or 0.0, 3),
            "attrs": dict(self.attrs),
        }
        if self.links:
            d["links"] = list(self.links)
        if self.error is not None:
            d["error"] = self.error
        if self.root:
            d["root"] = True
        return d


class _NullSpan:
    """The disabled/ambient-less span: every method is a no-op, truth
    value is False so callers can gate optional work on ``if span:``."""

    __slots__ = ()

    def __bool__(self):
        return False

    @property
    def context(self):
        return None

    trace_id = None
    span_id = None
    attrs = {}

    def set_attribute(self, key, value):
        return self

    def set_attributes(self, **attrs):
        return self

    def set_error(self, message):
        return self

    def end(self):
        return self


NULL_SPAN = _NullSpan()

_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "ptpu_trace_span", default=None)


def current_span():
    """The active span of this execution context (or None)."""
    return _CURRENT.get()


def current_context() -> SpanContext | None:
    """The active span's (trace_id, span_id) — None when no *bound*
    span is current (detached dispatch spans have no trace yet)."""
    sp = _CURRENT.get()
    if sp is None or sp.trace_id is None:
        return None
    return sp.context


def annotate(**attrs):
    """Set attributes on the current span, wherever the caller sits in
    the stack; no-op without one. This is how the executor tags the
    serving dispatch span with its cache disposition and FLOPs without
    the batcher threading a span handle down to it."""
    sp = _CURRENT.get()
    if sp is not None:
        sp.set_attributes(**attrs)


def note_status(status):
    """Record an HTTP status on the current span; >= 500 marks the span
    (and therefore the trace) errored — the tail sampler keeps it."""
    sp = _CURRENT.get()
    if sp is None or sp.trace_id is None:
        return
    sp.set_attribute("status", int(status))
    if int(status) >= 500:
        sp.set_error(f"http {int(status)}")


class _SpanScope:
    """Context manager binding a span as current; records it into the
    store on exit (and, for local roots, finalizes the trace —
    triggering the tail-sampling retention decision)."""

    __slots__ = ("span", "_token", "_finish")

    def __init__(self, span, finish=False):
        self.span = span
        self._finish = finish
        self._token = None

    def __enter__(self):
        if self.span is not NULL_SPAN:
            self._token = _CURRENT.set(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb):
        if self.span is NULL_SPAN:
            return False
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        sp = self.span.end()
        if exc is not None and sp.error is None:
            sp.set_error(f"{exc_type.__name__}: {exc}")
        st = store()
        st.add_span(sp)
        if self._finish:
            st.finish(sp)
        return False


def start_trace(name, parent=None, **attrs) -> _SpanScope:
    """Open a trace-root span (a LOCAL root: ``parent`` may be a remote
    :class:`SpanContext` from an extracted ``traceparent``, in which
    case this process's tree hangs under the remote span but the trace
    id is preserved). Exiting the scope finalizes the trace and runs
    the retention decision."""
    if not enabled():
        return _SpanScope(NULL_SPAN)
    if isinstance(parent, Span):
        parent = parent.context
    trace_id = parent.trace_id if parent is not None else new_trace_id()
    span = Span(name, trace_id,
                parent.span_id if parent is not None else None,
                root=True, attrs=attrs)
    return _SpanScope(span, finish=True)


def _resolve_parent(parent) -> SpanContext | None:
    if parent is None:
        sp = _CURRENT.get()
        if sp is None or sp.trace_id is None:
            return None
        return sp.context
    if isinstance(parent, Span):
        return parent.context
    return parent if parent.trace_id else None


def start_span(name, parent=None, **attrs) -> _SpanScope:
    """Open a child span under ``parent`` (default: the current span).
    With no trace to attach to this is a no-op scope — ambient
    instrumentation stays free outside requests."""
    if not enabled():
        return _SpanScope(NULL_SPAN)
    ctx = _resolve_parent(parent)
    if ctx is None:
        return _SpanScope(NULL_SPAN)
    span = Span(name, ctx.trace_id, ctx.span_id, attrs=attrs)
    return _SpanScope(span)


def begin_span(name, **attrs):
    """A detached (trace-unbound) span: timed and annotatable now,
    bound into member traces later via :func:`record_fanin` — the shape
    of a batch dispatch, which serves N traces at once."""
    if not enabled():
        return NULL_SPAN
    return Span(name, attrs=attrs)


class use_span:
    """Make ``span`` current for a block WITHOUT recording it on exit
    (pair with :func:`begin_span` + :func:`record_fanin`)."""

    __slots__ = ("_span", "_token")

    def __init__(self, span):
        self._span = span
        self._token = None

    def __enter__(self):
        if self._span is not NULL_SPAN:
            self._token = _CURRENT.set(self._span)
        return self._span

    def __exit__(self, *exc):
        if self._token is not None:
            _CURRENT.reset(self._token)
        return False


def record_fanin(span, members, **extra_attrs) -> int:
    """Record one (ended) span into EVERY member trace: the batch-
    dispatch fan-in. Each copy shares the span's id, is parented under
    that member's own context, and carries ``links`` naming every
    member exactly once — so any one trace shows both its own path and
    the co-batch it rode in."""
    if span is NULL_SPAN or not enabled():
        return 0
    members = [m for m in members if m is not None and m.trace_id]
    seen, uniq = set(), []
    for m in members:
        key = (m.trace_id, m.span_id)
        if key not in seen:
            seen.add(key)
            uniq.append(m)
    if not uniq:
        return 0
    span.end()
    if extra_attrs:
        span.set_attributes(**extra_attrs)
    links = [{"trace_id": m.trace_id, "span_id": m.span_id}
             for m in uniq]
    base = span.to_dict()
    base["links"] = links
    st = store()
    for m in uniq:
        d = dict(base)
        d["trace_id"] = m.trace_id
        d["parent_id"] = m.span_id
        st.add_span_dict(d)
    return len(uniq)


def record_interval(name, parent, t0, t1=None, error=None, **attrs):
    """Record a completed span retroactively from monotonic timestamps
    — queue-wait is only knowable when the request is picked, long
    after it began. ``parent`` is the request's stored context."""
    if not enabled():
        return None
    ctx = _resolve_parent(parent)
    if ctx is None:
        return None
    now = time.monotonic()
    if t1 is None:
        t1 = now
    dur_ms = max(0.0, (t1 - t0)) * 1e3
    d = {
        "name": name,
        "trace_id": ctx.trace_id,
        "span_id": new_span_id(),
        "parent_id": ctx.span_id,
        # reconstruct the epoch start from "how long ago t0 was"
        "t": time.time() - max(0.0, now - t0),
        "dur_ms": round(dur_ms, 3),
        "attrs": {k: v for k, v in attrs.items() if v is not None},
    }
    if error is not None:
        d["error"] = str(error)[:300]
    store().add_span_dict(d)
    return d


def flag_trace(ctx_or_id, reason: str):
    """Mark a trace for unconditional retention (``"deadline"``,
    ``"retry"``, ``"timeout"``, ...). Works before OR after the trace
    finishes."""
    if not enabled() or ctx_or_id is None:
        return
    tid = getattr(ctx_or_id, "trace_id", ctx_or_id)
    if tid:
        store().flag_trace(tid, reason)


def flag_current_trace(reason: str):
    ctx = current_context()
    if ctx is not None:
        flag_trace(ctx, reason)


# ---------------------------------------------------------------------------
# the tail-sampled trace store
# ---------------------------------------------------------------------------


class TraceStore:
    """Bounded in-process trace retention with tail-based sampling.

    Spans accumulate per trace while it is *active*; when the local
    root ends, :meth:`finish` decides retention from the OUTCOME:

    - flagged traces (deadline / retry / timeout / explicit) — kept;
    - any span errored — kept;
    - slowest-K of the current window — kept (a faster window entrant
      evicts the slowest-only trace it outcompeted, so the window holds
      exactly the top K);
    - everything else — dropped.

    Retained traces are a bounded FIFO (``FLAGS_trace_store_capacity``).
    Active (unfinished) traces are bounded too: a trace whose root is
    lost (crashed thread) ages out instead of leaking.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._active: OrderedDict = OrderedDict()
        self._retained: OrderedDict = OrderedDict()
        self._win_t0 = time.monotonic()
        self._win_slow: list = []  # [dur_ms, trace_id] entries
        self.finished_total = 0
        self.retained_total = 0
        self.dropped_total = 0

    # -- knobs (read per call: set_flags takes effect immediately) ----------

    @property
    def capacity(self) -> int:
        try:
            return max(1, int(flag("trace_store_capacity")))
        except Exception:
            return 256

    @property
    def slowest_k(self) -> int:
        try:
            return max(0, int(flag("trace_sample_slowest_k")))
        except Exception:
            return 5

    @property
    def window_s(self) -> float:
        try:
            return max(0.001, float(flag("trace_sample_window_s")))
        except Exception:
            return 30.0

    # -- writing -------------------------------------------------------------

    def add_span(self, span: Span):
        if span.trace_id:
            self.add_span_dict(span.to_dict())

    def add_span_dict(self, d: dict):
        tid = d.get("trace_id")
        if not tid:
            return
        with self._lock:
            kept = self._retained.get(tid)
            if kept is not None:
                # a span landing AFTER the retention decision (a fan-in
                # or retroactive interval racing the root's finish)
                # belongs in the retained payload, not a fresh active
                # entry that would leak until GC
                if len(kept["spans"]) < _MAX_SPANS_PER_TRACE:
                    kept["spans"].append(d)
                return
            ent = self._active.get(tid)
            if ent is None:
                ent = self._active[tid] = {
                    "spans": [], "flags": set(), "t": time.monotonic()}
                # active GC: lost roots must not leak the dict. Evict
                # already-decided lingerers (put-back inner subtrees
                # waiting for a possible co-hosted outer root) before
                # any LIVE trace still accumulating spans
                limit = max(4 * self.capacity, 64)
                if len(self._active) > limit:
                    for t in [t for t, e in self._active.items()
                              if e.get("decided")]:
                        if len(self._active) <= limit:
                            break
                        del self._active[t]
                while len(self._active) > limit:
                    self._active.popitem(last=False)
            else:
                # a trace receiving spans is not a lost root — keep it
                # off the GC's oldest-first end
                self._active.move_to_end(tid)
            if len(ent["spans"]) < _MAX_SPANS_PER_TRACE:
                ent["spans"].append(d)

    def flag_trace(self, tid: str, reason: str):
        with self._lock:
            kept = self._retained.get(tid)
            if kept is not None:
                if reason not in kept["kept"]:
                    kept["kept"] = sorted(set(kept["kept"]) | {reason})
                return
            ent = self._active.get(tid)
            if ent is None:
                ent = self._active[tid] = {
                    "spans": [], "flags": set(), "t": time.monotonic()}
            ent["flags"].add(reason)

    def finish(self, root_span) -> dict | None:
        """Finalize a trace (its local root just ended) and run the
        retention decision. Returns the retained payload or None."""
        d = (root_span.to_dict() if isinstance(root_span, Span)
             else dict(root_span))
        tid = d.get("trace_id")
        if not tid:
            return None
        duration_ms = float(d.get("dur_ms") or 0.0)
        with self._lock:
            kept = self._retained.get(tid)
            if kept is not None:
                # a SECOND local root for an already-retained trace:
                # router + backend co-hosted in one process share this
                # store, so one distributed trace finishes once per
                # local root — merge (span_id-deduped) instead of
                # overwriting, or the first root's subtree would vanish
                ent = self._active.pop(tid, None)
                seen = {s.get("span_id") for s in kept["spans"]}
                for s in (ent["spans"] if ent else []) + [d]:
                    if (s.get("span_id") not in seen
                            and len(kept["spans"]) < _MAX_SPANS_PER_TRACE):
                        seen.add(s.get("span_id"))
                        kept["spans"].append(s)
                reasons = set(ent["flags"]) if ent else set()
                if any(s.get("error") is not None
                       for s in (ent["spans"] if ent else []) + [d]):
                    # an errored outer root must promote the trace to
                    # always-kept — a kept list still == ['slow'] leaves
                    # it evictable by the slowest-K competition
                    reasons.add("error")
                if reasons:
                    kept["kept"] = sorted(set(kept["kept"]) | reasons)
                if d.get("parent_id") is None:
                    # the parentless root is the OUTERMOST (the router
                    # hop): its name/duration describe the whole trace
                    kept["root"] = d.get("name")
                    kept["duration_ms"] = round(duration_ms, 3)
                    kept["t_start"] = d.get("t")
                # the trace was already counted when it was retained —
                # a second local root is the same request, not a new one
                return kept
            ent = self._active.pop(tid, None)
            # a put-back inner root already counted this request when
            # its own retention decision ran — the outer root's finish
            # is the same request, not a new one
            already = bool(ent and ent.get("decided"))
            spans = ent["spans"] if ent else [d]
            reasons = set(ent["flags"]) if ent else set()
            if any(s.get("error") is not None for s in spans):
                reasons.add("error")
            now = time.monotonic()
            if now - self._win_t0 > self.window_s:
                self._win_t0 = now
                self._win_slow = []
            k = self.slowest_k
            if k > 0:
                if len(self._win_slow) < k:
                    self._win_slow.append([duration_ms, tid])
                    reasons.add("slow")
                else:
                    mi = min(range(len(self._win_slow)),
                             key=lambda i: self._win_slow[i][0])
                    if duration_ms > self._win_slow[mi][0]:
                        _, old_tid = self._win_slow[mi]
                        self._win_slow[mi] = [duration_ms, tid]
                        reasons.add("slow")
                        old = self._retained.get(old_tid)
                        if old is not None and old["kept"] == ["slow"]:
                            # outcompeted, and slowness was its ONLY
                            # claim — the window holds exactly top-K
                            del self._retained[old_tid]
            if not already:
                self.finished_total += 1
            if not reasons:
                if not already:
                    self.dropped_total += 1
                if ent is not None and d.get("parent_id") is not None:
                    # an INNER local root (it hangs under a remote/outer
                    # span): a co-hosted outer root may finish this
                    # trace later, and its retention decision must see
                    # this subtree — put the spans back instead of
                    # discarding (the active-table GC bounds the
                    # cross-process case where no outer root ever comes)
                    ent["decided"] = True
                    self._active[tid] = ent
                return None
            payload = {
                "trace_id": tid,
                "root": d.get("name"),
                "t_start": d.get("t"),
                "duration_ms": round(duration_ms, 3),
                "kept": sorted(reasons),
                "spans": spans,
            }
            self._retained[tid] = payload
            self.retained_total += 1
            if already:
                # the inner root's decision counted this request as
                # dropped; the outer root just kept it after all
                self.dropped_total -= 1
            while len(self._retained) > self.capacity:
                old_tid, _ = self._retained.popitem(last=False)
                self._win_slow = [w for w in self._win_slow
                                  if w[1] != old_tid]
            return payload

    # -- reading -------------------------------------------------------------

    def get(self, tid: str) -> dict | None:
        with self._lock:
            p = self._retained.get(tid)
            if p is None:
                return None
            out = dict(p)
            out["spans"] = list(p["spans"])
            return out

    def summaries(self) -> list:
        """Newest-first retained-trace summaries (the /tracez list)."""
        with self._lock:
            rows = [
                {"trace_id": p["trace_id"], "root": p["root"],
                 "duration_ms": p["duration_ms"], "kept": p["kept"],
                 "spans": len(p["spans"]), "t_start": p["t_start"]}
                for p in self._retained.values()
            ]
        rows.reverse()
        return rows

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def stats(self) -> dict:
        with self._lock:
            return {
                "finished": self.finished_total,
                "retained": self.retained_total,
                "dropped": self.dropped_total,
                "held": len(self._retained),
                "active": len(self._active),
            }

    def slowest(self, n=5, root_prefix=None) -> list:
        """Top-``n`` retained traces by root duration (optionally only
        roots starting with ``root_prefix``) with a per-stage time
        breakdown — the /statz ``slowest`` table."""
        with self._lock:
            cands = [p for p in self._retained.values()
                     if root_prefix is None
                     or (p["root"] or "").startswith(root_prefix)]
            cands = sorted(cands, key=lambda p: -p["duration_ms"])[:n]
            rows = []
            for p in cands:
                stages: dict = {}
                bucket = None
                for s in p["spans"]:
                    short = s["name"].rsplit("::", 1)[-1]
                    if short in _STAGE_NAMES:
                        stages[short] = round(
                            stages.get(short, 0.0) + s["dur_ms"], 3)
                    if bucket is None:
                        bucket = s.get("attrs", {}).get("bucket")
                rows.append({
                    "trace_id": p["trace_id"],
                    "duration_ms": p["duration_ms"],
                    "root": p["root"],
                    "kept": p["kept"],
                    "stages": stages,
                    "bucket": bucket,
                })
        return rows

    def reset(self):
        with self._lock:
            self._active.clear()
            self._retained.clear()
            self._win_slow = []
            self._win_t0 = time.monotonic()
            self.finished_total = 0
            self.retained_total = 0
            self.dropped_total = 0


_STORE = TraceStore()


def store() -> TraceStore:
    return _STORE


def reset_store():
    _STORE.reset()


# ---------------------------------------------------------------------------
# /tracez payloads + chrome view
# ---------------------------------------------------------------------------


def parse_query(raw_path: str) -> dict:
    """``/tracez?id=...&format=chrome`` -> {"id": ..., "format": ...}."""
    from urllib.parse import parse_qsl, urlsplit

    return dict(parse_qsl(urlsplit(raw_path).query))


def chrome_events(payload: dict) -> list:
    """One retained trace as chrome-trace events (``ph=X``, epoch-us
    timestamps, span ids/attrs in ``args``)."""
    pid = os.getpid()
    tid = int(payload["trace_id"][:6], 16)
    events = [{
        "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
        "args": {"name": f"trace {payload['trace_id'][:8]}"},
    }]
    for s in payload["spans"]:
        args = {"trace_id": s["trace_id"], "span_id": s["span_id"],
                "parent_id": s.get("parent_id")}
        args.update(s.get("attrs", {}))
        if s.get("links"):
            args["links"] = s["links"]
        if s.get("error") is not None:
            args["error"] = s["error"]
        events.append({
            "name": s["name"], "ph": "X",
            "ts": float(s["t"]) * 1e6,
            "dur": float(s["dur_ms"]) * 1e3,
            "pid": pid, "tid": tid, "args": args,
        })
    return events


def slowest_table(n=5, root_prefix=None) -> list:
    return store().slowest(n, root_prefix=root_prefix)


def tracez_payload(query: dict) -> tuple:
    """The ``/tracez`` response: ``(status, payload)``. No query lists
    the retained traces; ``?id=`` fetches one span tree (404 when the
    sampler dropped it); ``?id=&format=chrome`` renders it as a
    standalone chrome trace."""
    tid = query.get("id")
    st = store()
    if not tid:
        return 200, {
            "retained": st.summaries(),
            "stats": st.stats(),
            "store": {
                "capacity": st.capacity,
                "slowest_k": st.slowest_k,
                "window_s": st.window_s,
            },
        }
    payload = st.get(tid)
    if payload is None:
        return 404, {
            "error": f"trace {tid!r} not retained (dropped by the tail "
                     "sampler, evicted, or never seen)",
            "retained_ids": [r["trace_id"] for r in st.summaries()[:32]],
        }
    if query.get("format") == "chrome":
        return 200, {"traceEvents": chrome_events(payload)}
    return 200, payload
