"""Incubating subsystems (reference: python/paddle/fluid/incubate/)."""
from . import auto_checkpoint  # noqa: F401
from . import hapi_text  # noqa: F401  (incubate/hapi/text surface)
