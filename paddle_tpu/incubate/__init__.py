"""Incubating subsystems (reference: python/paddle/fluid/incubate/)."""
from . import auto_checkpoint  # noqa: F401
