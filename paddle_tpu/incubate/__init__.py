"""Incubating subsystems (reference: python/paddle/fluid/incubate/)."""
from . import auto_checkpoint  # noqa: F401
from . import hapi_text  # noqa: F401  (incubate/hapi/text surface)
# 2.x incubate optimizer-wrapper names (paddle.incubate.ModelAverage /
# LookAhead in later reference versions; fluid/optimizer.py:3102,4822)
from ..optimizer.wrappers import ModelAverage, Lookahead  # noqa: F401

LookAhead = Lookahead
from .model_stat import memory_usage, op_freq_statistic  # noqa: F401
