"""hapi text layers (incubate/hapi/text/text.py parity).

Most of the reference's surface already exists as first-class nn layers
here and is re-exported under the reference names (RNN/LSTM/GRU families
→ nn/rnn.py; MultiHeadAttention/Transformer* → nn/transformer.py). The
pieces implemented in this module are the ones with no prior equivalent:

- Conv1dPoolLayer / CNNEncoder (text.py:1218, :1287): conv1d+pool text
  encoders.
- LinearChainCRF / CRFDecoding (text.py:1344, :1421): the linear-chain
  CRF log-likelihood (forward algorithm over lax.scan — differentiable,
  operators/linear_chain_crf_op.cc semantics incl. the [n+2, n]
  transition layout with start/stop rows) and Viterbi decoding
  (operators/crf_decoding_op.cc).
- SequenceTagging (text.py:1583): embedding + GRU + CRF tagging model
  (pairs with text.Conll05st).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework import autograd
from ..framework.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import Layer
from ..nn.layers import Conv1D, Embedding, Linear
from ..nn.rnn import GRU, LSTM, GRUCell, LSTMCell, SimpleRNN
from ..nn.transformer import (
    MultiHeadAttention,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)

__all__ = [
    # re-exported equivalents (reference names)
    "RNN", "LSTM", "GRU", "BasicLSTMCell", "BasicGRUCell",
    "MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
    "TransformerDecoderLayer", "TransformerDecoder",
    # implemented here
    "Conv1dPoolLayer", "CNNEncoder",
    "LinearChainCRF", "CRFDecoding", "SequenceTagging",
]

RNN = SimpleRNN
BasicLSTMCell = LSTMCell
BasicGRUCell = GRUCell


class Conv1dPoolLayer(Layer):
    """conv1d + max-over-time pooling (text.py:1218)."""

    def __init__(self, num_channels, num_filters, filter_size,
                 pool_type="max"):
        super().__init__()
        self.conv = Conv1D(num_channels, num_filters, filter_size,
                           padding=filter_size // 2)
        self.pool_type = pool_type

    def forward(self, x):
        """x [B, C, T] → [B, num_filters] (pooled over time)."""
        h = F.relu(self.conv(x))
        arr = h._array if isinstance(h, Tensor) else h
        pooled = (jnp.max(arr, axis=-1) if self.pool_type == "max"
                  else jnp.mean(arr, axis=-1))
        return Tensor._from_array(pooled) if isinstance(h, Tensor) else pooled


class CNNEncoder(Layer):
    """Parallel Conv1dPoolLayers over several filter sizes, concatenated
    (text.py:1287 — the classic Kim-CNN text encoder)."""

    def __init__(self, num_channels, num_filters, filter_sizes=(2, 3, 4),
                 pool_type="max"):
        super().__init__()
        self.convs = [
            Conv1dPoolLayer(num_channels, num_filters, fs, pool_type)
            for fs in filter_sizes
        ]
        for i, c in enumerate(self.convs):
            self.add_sublayer(f"conv_pool_{i}", c)

    def forward(self, x):
        from .. import ops

        return ops.concat([c(x) for c in self.convs], axis=-1)


def _crf_scores(emission, labels, transition, lengths):
    """Path score of the gold labels (linear_chain_crf_op.cc Forward's
    gold-score half). transition: [n+2, n], rows 0/1 = start/stop."""
    b, t, n = emission.shape
    start, stop, trans = transition[0], transition[1], transition[2:]
    pos = jnp.arange(t)
    mask = (pos[None, :] < lengths[:, None]).astype(emission.dtype)
    emit = jnp.take_along_axis(emission, labels[..., None],
                               axis=2)[..., 0]          # [B, T]
    score = (emit * mask).sum(1) + start[labels[:, 0]]
    pair = trans[labels[:, :-1], labels[:, 1:]]          # [B, T-1]
    score = score + (pair * mask[:, 1:]).sum(1)
    last = jnp.clip(lengths - 1, 0, t - 1)
    last_lab = jnp.take_along_axis(labels, last[:, None], axis=1)[:, 0]
    return score + stop[last_lab]


def _crf_lognorm(emission, transition, lengths):
    """log Z via the forward algorithm over lax.scan."""
    b, t, n = emission.shape
    start, stop, trans = transition[0], transition[1], transition[2:]
    alpha0 = start + emission[:, 0]                      # [B, n]

    def step(alpha, inp):
        e_t, valid = inp                                 # [B,n], [B]
        nxt = jax.nn.logsumexp(
            alpha[:, :, None] + trans[None], axis=1
        ) + e_t
        return jnp.where(valid[:, None], nxt, alpha), None

    pos = jnp.arange(1, t)
    valid = pos[None, :] < lengths[:, None]              # [B, T-1]
    alpha, _ = lax.scan(
        step, alpha0,
        (jnp.moveaxis(emission[:, 1:], 1, 0), jnp.moveaxis(valid, 1, 0)),
    )
    return jax.nn.logsumexp(alpha + stop[None], axis=1)  # [B]


class LinearChainCRF(Layer):
    """CRF negative log-likelihood layer (text.py:1344 over
    operators/linear_chain_crf_op.cc). forward(emission, labels, lengths)
    → per-sequence NLL [B]."""

    def __init__(self, size, param_attr=None):
        super().__init__()
        self.size = size
        self.transition = self.create_parameter(
            [size + 2, size], attr=param_attr,
            default_initializer=I.Normal(0.0, 0.1),
        )

    def forward(self, emission, labels, lengths):
        def fn(e, tr, lab, ln):
            return _crf_lognorm(e, tr, ln) - _crf_scores(e, lab, tr, ln)

        return autograd.apply_op(
            "linear_chain_crf", fn,
            [_t(emission), self.transition, _t(labels, "int64"),
             _t(lengths, "int64")], {},
        )


class CRFDecoding(Layer):
    """Viterbi decoding sharing a LinearChainCRF's transition
    (text.py:1421 over operators/crf_decoding_op.cc)."""

    def __init__(self, crf: LinearChainCRF):
        super().__init__()
        self.crf = crf

    def forward(self, emission, lengths):
        e = _arr(_t(emission))
        tr = _arr(self.crf.transition)
        ln = _arr(_t(lengths, "int64"))
        b, t, n = e.shape
        start, stop, trans = tr[0], tr[1], tr[2:]

        def step(alpha, inp):
            e_t, valid = inp
            cand = alpha[:, :, None] + trans[None]       # [B, n, n]
            best = jnp.max(cand, axis=1) + e_t
            ptr = jnp.argmax(cand, axis=1)               # [B, n]
            alpha_next = jnp.where(valid[:, None], best, alpha)
            keep = valid[:, None]
            ptr = jnp.where(
                keep, ptr, jnp.arange(n)[None, :]        # identity past end
            )
            return alpha_next, ptr

        alpha0 = start + e[:, 0]
        pos = jnp.arange(1, t)
        valid = pos[None, :] < ln[:, None]
        alpha, ptrs = lax.scan(
            step, alpha0,
            (jnp.moveaxis(e[:, 1:], 1, 0), jnp.moveaxis(valid, 1, 0)),
        )
        last = jnp.argmax(alpha + stop[None], axis=1)    # [B]

        def back(lab, ptr_t):
            prev = jnp.take_along_axis(ptr_t, lab[:, None], axis=1)[:, 0]
            return prev, lab

        # reverse scan: ys[k] = label at position k+1; the final carry is
        # the label at position 0
        first, path = lax.scan(back, last, ptrs, reverse=True)
        path = jnp.concatenate(
            [first[:, None], jnp.moveaxis(path, 0, 1)], axis=1
        )                                                # [B, T]
        return Tensor._from_array(path)


class SequenceTagging(Layer):
    """embedding → GRU → emission → CRF (text.py:1583), the SRL/NER
    tagging composite; decode() runs Viterbi."""

    def __init__(self, vocab_size, num_labels, word_emb_dim=64,
                 hidden_size=64):
        super().__init__()
        self.embedding = Embedding(vocab_size, word_emb_dim)
        self.gru = GRU(word_emb_dim, hidden_size)
        self.emission_fc = Linear(hidden_size, num_labels)
        self.crf = LinearChainCRF(num_labels)
        self.decoder = CRFDecoding(self.crf)

    def _emission(self, word_ids):
        h, _ = self.gru(self.embedding(word_ids))
        return self.emission_fc(h)

    def forward(self, word_ids, labels, lengths):
        """→ mean CRF NLL (training loss)."""
        nll = self.crf(self._emission(word_ids), labels, lengths)
        return nll.mean()

    def decode(self, word_ids, lengths):
        return self.decoder(self._emission(word_ids), lengths)


def _t(v, dtype=None):
    if isinstance(v, Tensor):
        return v
    return Tensor(np.asarray(v), dtype=dtype)


def _arr(v):
    return v._array if isinstance(v, Tensor) else jnp.asarray(v)
