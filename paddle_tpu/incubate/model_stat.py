"""Program analysis utilities (fluid/contrib analysis trio).

Reference parity: python/paddle/fluid/contrib/memory_usage_calc.py:46
(memory_usage), op_frequence.py:23 (op_freq_statistic). The third member,
model_stat.py:1 (FLOPs/param summary), is superseded by
``paddle.summary(net, input_size, cost=True)`` (hapi/model.py) whose
numbers come from XLA's HLO cost analysis instead of hand formulas.
"""
from __future__ import annotations

from collections import Counter, OrderedDict

import numpy as np

from ..static.program import Program

__all__ = ["memory_usage", "op_freq_statistic"]

_DTYPE_SIZE = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
}


def memory_usage(program, batch_size):
    """Estimate activation/parameter memory of a static program.

    Walks every op output var once, sizes it with negative dims bound to
    ``batch_size``, and returns ``(low, high, unit)`` — the reference's
    0.5x/1.5x band around the raw total (memory_usage_calc.py:110): the
    runtime may both reuse buffers (below) and double-buffer (above).
    """
    if not isinstance(program, Program):
        raise TypeError(
            "Calculating Memory Usage requires Program as its parameter, "
            f"but got {type(program).__name__}")
    if batch_size <= 0:
        raise ValueError("The batch size need to be positive.")

    total = 0.0
    seen = set()
    blk = program.global_block()
    for op in blk.ops:
        for names in op.outputs.values():
            for name in names:
                if name in seen or not blk.has_var(name):
                    continue
                seen.add(name)
                var = blk.var(name)
                shape = list(getattr(var, "shape", None) or [])
                count, neg = 1, 0
                for d in shape:
                    if d is None or int(d) < 0:
                        neg += 1
                        if neg > 1:
                            raise ValueError(
                                f"Var {name} has more than one "
                                "negative dim.")
                        count *= batch_size
                    else:
                        count *= int(d)
                total += count * _DTYPE_SIZE.get(
                    str(getattr(var, "dtype", "float32")), 4)

    low, high = total * 0.5, total * 1.5
    unit = "B"
    for u in ("KB", "MB", "GB"):
        if high < 1024:
            break
        low, high, unit = low / 1024, high / 1024, u
    return low, high, unit


def op_freq_statistic(program):
    """Op-type frequency of a program (op_frequence.py:23): returns
    (uni_op_freq, adj_op_freq) ordered most-common-first — single op
    counts and adjacent-pair counts."""
    if not isinstance(program, Program):
        raise TypeError(
            "The input type should be Program, but got "
            f"{type(program).__name__}")
    uni = Counter()
    adj = Counter()
    ops = program.global_block().ops
    for i, op in enumerate(ops):
        uni[op.type] += 1
        if i + 1 < len(ops):
            adj[f"{op.type}->{ops[i + 1].type}"] += 1
    order = lambda c: OrderedDict(c.most_common())  # noqa: E731
    return order(uni), order(adj)
