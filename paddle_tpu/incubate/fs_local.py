"""Default fs for auto-checkpoint (LocalFS; HDFS is gated in fs.py)."""
from __future__ import annotations


def local_fs():
    from ..distributed.fleet.utils.fs import LocalFS

    return LocalFS()
