"""Auto-checkpoint: env-configured periodic training snapshots + resume.

Reference parity: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py
— AutoCheckpointChecker (:71, env config :116-188), train_epoch_range
(resume semantics), checkpoint_saver.py (rotated snapshots over the fs
layer). Environment variables (reference names kept):

    PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT   enable
    PADDLE_EDL_HDFS_CHECKPOINT_PATH=<dir>           checkpoint directory
    PADDLE_JOB_ID=<id>                              namespace inside dir
    PADDLE_EDL_SAVE_CHECKPOINT_INTER=<secs>         min seconds between saves
                                                    (FLAGS_checkpoint_save_inter_s
                                                    >= 0 overrides)

TPU-native: a snapshot is the functional state (model params/buffers +
optimizer accumulators + epoch counter) written to <dir>/<job>/epoch_<n>/
with rotation; there is no program/scope to persist because the compiled
step is rebuilt from the eager objects on resume.

Crash consistency + async (distributed/checkpoint.py underneath): the
eager state is *captured* as immutable array references on the caller
thread (O(1) — training may immediately continue mutating the live
objects), then serialized + fsynced on the background writer thread
(``FLAGS_checkpoint_async``) and published by one atomic tmp→rename
only after a checksummed MANIFEST.json is durable. A process killed
mid-save leaves a manifest-less ``epoch_N.tmp`` that is swept on the
next load; a checksum-failing published snapshot is skipped in favor of
the next-newest — resume never half-loads a torn snapshot.
"""
from __future__ import annotations

import os
import time

__all__ = ["AutoCheckpointChecker", "train_epoch_range", "register",
           "reset_registry", "wait_pending"]


class AutoCheckpointChecker:
    """auto_checkpoint.py:71 — reads the env configuration once."""

    def __init__(self):
        self.running_env = os.getenv("PADDLE_RUNNING_ENV", "")
        self.ckpt_dir = os.getenv("PADDLE_EDL_HDFS_CHECKPOINT_PATH", "")
        self.job_id = os.getenv("PADDLE_JOB_ID", "default_job")
        try:
            self.save_inter = float(
                os.getenv("PADDLE_EDL_SAVE_CHECKPOINT_INTER", "900")
            )
        except ValueError:
            self.save_inter = 900.0
        from ..flags import flag

        # runtime override without touching the environment
        flag_inter = float(flag("checkpoint_save_inter_s"))
        if flag_inter >= 0:
            self.save_inter = flag_inter

    def valid(self) -> bool:
        return (
            self.running_env == "PADDLE_EDL_AUTO_CHECKPOINT"
            and bool(self.ckpt_dir)
        )

    @property
    def job_dir(self):
        return os.path.join(self.ckpt_dir, self.job_id)


# what a snapshot covers: name -> (model, optimizer|None, sync_fn|None)
_REGISTRY: dict[str, tuple] = {}
_NAME_COUNTS: dict[str, int] = {}
_REGISTRY_EPOCH = 0  # bumped by reset_registry; stale claims re-claim


def registry_epoch() -> int:
    return _REGISTRY_EPOCH


def claim_name(prefix: str) -> str:
    """Deterministic registry name: ``prefix-N`` where N counts prior
    claims of the same prefix in this process. Identical restarted
    programs re-derive the same names, so resume finds its snapshot
    files, while two different models in one process stay disjoint.
    Callers caching the claimed name must also cache registry_epoch()
    and re-claim after a reset (see hapi.Model.fit)."""
    n = _NAME_COUNTS.get(prefix, 0)
    _NAME_COUNTS[prefix] = n + 1
    return f"{prefix}-{n}"


def register(model, optimizer=None, name="default", sync_fn=None):
    """Register eager objects whose state the snapshots cover.

    ``sync_fn`` is called before each save — compiled train steps keep
    state on device (framework/jit.py), so the eager objects must be
    synced for state_dict() to see the trained values.
    """
    _REGISTRY[name] = (model, optimizer, sync_fn)


def reset_registry():
    global _REGISTRY_EPOCH
    _REGISTRY.clear()
    _NAME_COUNTS.clear()
    _REGISTRY_EPOCH += 1


def wait_pending(timeout=None, raise_errors=True):
    """Drain in-flight async snapshot writes (durable or failed-loudly)."""
    from ..distributed import checkpoint as _ckpt

    return _ckpt.wait_pending(timeout=timeout, raise_errors=raise_errors)


def _snapshot_path(checker, epoch):
    return os.path.join(checker.job_dir, f"epoch_{epoch}")


def _capture_registry():
    """O(1) capture of every registered object's state: sync the device
    step back into the eager objects, then grab the (immutable) array
    references out of the live Tensors. The background writer reads the
    captured arrays — training mutating the live objects afterwards
    rebinds NEW arrays and never races the write."""
    from ..distributed import checkpoint as _ckpt

    entries = []
    for name, (model, optimizer, sync_fn) in _REGISTRY.items():
        if sync_fn is not None:
            sync_fn()
        params = _ckpt.detach_refs(model.state_dict())
        opt = (_ckpt.detach_refs(optimizer.state_dict())
               if optimizer is not None else None)
        entries.append((name, params, opt))
    return entries


def _save_snapshot(checker, epoch, fs, async_=None):
    """Capture now; serialize + publish inline or on the writer thread."""
    import functools

    from ..distributed import checkpoint as _ckpt
    from ..flags import flag

    if async_ is None:
        async_ = bool(flag("checkpoint_async"))
    entries = _capture_registry()
    final = _snapshot_path(checker, epoch)
    job = functools.partial(_write_epoch_snapshot, checker.job_dir, final,
                            entries, int(epoch), fs)
    if async_:
        from ..monitor import registry as _reg

        _reg.counter("checkpoint/async_saves").inc()
        return _ckpt.submit(job, label=final)
    job()
    return None


def _write_epoch_snapshot(job_dir, final, entries, epoch, fs):
    """Writer body: data files -> checksummed manifest -> atomic rename
    -> rotation. FLAGS_fault_injection's ``mid_save`` point sits between
    the data files and the manifest — the torn window crash-consistent
    rotation must survive."""
    from ..distributed import chaos
    from ..distributed import checkpoint as _ckpt
    from ..framework.serialization import dumps
    from ..flags import flag
    from ..monitor import flight_recorder as _flight
    from ..monitor import registry as _reg
    from ..profiler import RecordEvent

    t0 = time.perf_counter()
    tmp = final + ".tmp"
    fs.delete(tmp)
    fs.mkdirs(tmp)
    files = {}
    with RecordEvent("checkpoint::serialize"):
        for name, params, opt in entries:
            fname = f"{name}.pdparams"
            crc, size = _ckpt.write_bytes(
                os.path.join(tmp, fname), dumps(params))
            files[fname] = {"crc32": crc, "size": size}
            chaos.inject("mid_save")
            if opt is not None:
                fname = f"{name}.pdopt"
                crc, size = _ckpt.write_bytes(
                    os.path.join(tmp, fname), dumps(opt))
                files[fname] = {"crc32": crc, "size": size}
    _ckpt.write_manifest(tmp, files, epoch=epoch, time=time.time())
    with RecordEvent("checkpoint::publish"):
        fs.delete(final)
        fs.rename(tmp, final)  # atomic publish
        _ckpt._fsync_dir(os.path.dirname(final) or ".")
    _reg.counter("checkpoint/saves").inc()
    _flight.record_event(
        "checkpoint_saved", path=final, step=epoch,
        ms=round((time.perf_counter() - t0) * 1e3, 3))
    # rotation: drop oldest INTACT snapshots beyond FLAGS_checkpoint_keep
    checker_like = _PathChecker(job_dir)
    found = _list_snapshots(checker_like, fs)
    for old in found[:-max(int(flag("checkpoint_keep")), 1)]:
        fs.delete(_snapshot_path(checker_like, old))


class _PathChecker:
    """Minimal checker stand-in for writer-thread rotation (the real
    AutoCheckpointChecker reads env, which may have changed mid-run)."""

    def __init__(self, job_dir):
        self.job_dir = job_dir


def _list_snapshots(checker, fs):
    dirs, _ = fs.ls_dir(checker.job_dir)
    epochs = []
    for d in dirs:
        if d.startswith("epoch_") and not d.endswith(".tmp"):
            try:
                epochs.append(int(d[len("epoch_"):]))
            except ValueError:
                continue
    return sorted(epochs)


def _load_latest(checker, fs):
    """Restore registered objects from the newest *intact* snapshot;
    returns the epoch it covered, or -1.

    Startup hygiene + fallback: stale ``epoch_N.tmp`` dirs (a writer
    died mid-save) are swept first; a published snapshot whose manifest
    is missing or whose files fail their checksums is skipped — with a
    flight-recorder event + counter — in favor of the next-newest."""
    from ..distributed import checkpoint as _ckpt
    from ..framework.serialization import load
    from ..monitor import flight_recorder as _flight
    from ..monitor import registry as _reg

    # an in-process restart (elastic_run) may arrive while the writer
    # thread still holds queued snapshots — drain first so resume sees
    # everything that was captured before the crash (writer errors were
    # already recorded; the fallback scan below handles their absence)
    _ckpt.wait_pending(raise_errors=False)
    _ckpt.sweep_tmp(checker.job_dir)
    found = _list_snapshots(checker, fs)
    for epoch in reversed(found):
        path = _snapshot_path(checker, epoch)
        try:
            _ckpt.validate(path)
        except _ckpt.CheckpointCorruptError as e:
            # legacy (pre-manifest) snapshots wrote a `meta` epoch file
            # and no MANIFEST.json; they published atomically, so a
            # manifest-less dir WITH meta is an intact old-format
            # snapshot — an upgraded job must resume from it, not
            # silently restart at epoch 0. Anything else is torn.
            if not fs.is_file(os.path.join(path, "meta")):
                _reg.counter("checkpoint/corrupt_skipped").inc()
                _flight.record_event("checkpoint_skipped_corrupt",
                                     path=path, error=str(e)[:200])
                continue
            _flight.record_event("checkpoint_legacy_snapshot", path=path)
        for name, (model, optimizer, _sync) in _REGISTRY.items():
            params_file = os.path.join(path, f"{name}.pdparams")
            if not fs.is_file(params_file):
                # registered after this snapshot was written (e.g. a second
                # Model.fit in the same process): nothing to restore for it
                continue
            model.set_state_dict(load(params_file))
            opt_file = os.path.join(path, f"{name}.pdopt")
            if optimizer is not None and fs.is_file(opt_file):
                optimizer.set_state_dict(load(opt_file))
        _reg.counter("checkpoint/restores").inc()
        _flight.record_event("checkpoint_restored", path=path, step=epoch)
        return epoch
    return -1


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None):
    """Resumable epoch loop (auto_checkpoint.py train_epoch_range).

    Yields epoch indices. With the auto-checkpoint env configured, the
    registered model/optimizer are restored from the newest intact
    snapshot and completed epochs are skipped; a snapshot is written
    when at least ``save_checkpoint_inter`` seconds (env/flag default)
    elapsed since the last one, and always at the final epoch. Saves
    run off the epoch path on the background writer
    (``FLAGS_checkpoint_async``); the loop drains them before returning
    so a completed run's final snapshot is durable.
    """
    from .fs_local import local_fs

    checker = AutoCheckpointChecker()
    if not checker.valid():
        yield from range(max_epoch_num)
        return

    fs = local_fs()
    inter = (checker.save_inter if save_checkpoint_inter is None
             else float(save_checkpoint_inter))
    start = _load_latest(checker, fs) + 1
    last_save = time.monotonic()
    for epoch in range(start, max_epoch_num):
        yield epoch
        now = time.monotonic()
        if now - last_save >= inter or epoch == max_epoch_num - 1:
            _save_snapshot(checker, epoch, fs)
            last_save = now
    # normal completion: make the async snapshots durable before the
    # caller moves on (a crash after this point resumes past max_epoch)
    wait_pending()
