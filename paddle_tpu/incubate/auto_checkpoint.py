"""Auto-checkpoint: env-configured periodic training snapshots + resume.

Reference parity: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py
— AutoCheckpointChecker (:71, env config :116-188), train_epoch_range
(resume semantics), checkpoint_saver.py (rotated snapshots over the fs
layer). Environment variables (reference names kept):

    PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT   enable
    PADDLE_EDL_HDFS_CHECKPOINT_PATH=<dir>           checkpoint directory
    PADDLE_JOB_ID=<id>                              namespace inside dir
    PADDLE_EDL_SAVE_CHECKPOINT_INTER=<secs>         min seconds between saves

TPU-native: a snapshot is the functional state (model params/buffers +
optimizer accumulators + epoch counter) written atomically via
paddle.save to <dir>/<job>/epoch_<n>/ with rotation; there is no
program/scope to persist because the compiled step is rebuilt from the
eager objects on resume.
"""
from __future__ import annotations

import os
import time

__all__ = ["AutoCheckpointChecker", "train_epoch_range", "register",
           "reset_registry"]


class AutoCheckpointChecker:
    """auto_checkpoint.py:71 — reads the env configuration once."""

    def __init__(self):
        self.running_env = os.getenv("PADDLE_RUNNING_ENV", "")
        self.ckpt_dir = os.getenv("PADDLE_EDL_HDFS_CHECKPOINT_PATH", "")
        self.job_id = os.getenv("PADDLE_JOB_ID", "default_job")
        try:
            self.save_inter = float(
                os.getenv("PADDLE_EDL_SAVE_CHECKPOINT_INTER", "900")
            )
        except ValueError:
            self.save_inter = 900.0

    def valid(self) -> bool:
        return (
            self.running_env == "PADDLE_EDL_AUTO_CHECKPOINT"
            and bool(self.ckpt_dir)
        )

    @property
    def job_dir(self):
        return os.path.join(self.ckpt_dir, self.job_id)


# what a snapshot covers: name -> (model, optimizer|None, sync_fn|None)
_REGISTRY: dict[str, tuple] = {}
_MAX_KEPT = 2  # checkpoint_saver.py max_num_checkpoints
_NAME_COUNTS: dict[str, int] = {}
_REGISTRY_EPOCH = 0  # bumped by reset_registry; stale claims re-claim


def registry_epoch() -> int:
    return _REGISTRY_EPOCH


def claim_name(prefix: str) -> str:
    """Deterministic registry name: ``prefix-N`` where N counts prior
    claims of the same prefix in this process. Identical restarted
    programs re-derive the same names, so resume finds its snapshot
    files, while two different models in one process stay disjoint.
    Callers caching the claimed name must also cache registry_epoch()
    and re-claim after a reset (see hapi.Model.fit)."""
    n = _NAME_COUNTS.get(prefix, 0)
    _NAME_COUNTS[prefix] = n + 1
    return f"{prefix}-{n}"


def register(model, optimizer=None, name="default", sync_fn=None):
    """Register eager objects whose state the snapshots cover.

    ``sync_fn`` is called before each save — compiled train steps keep
    state on device (framework/jit.py), so the eager objects must be
    synced for state_dict() to see the trained values.
    """
    _REGISTRY[name] = (model, optimizer, sync_fn)


def reset_registry():
    global _REGISTRY_EPOCH
    _REGISTRY.clear()
    _NAME_COUNTS.clear()
    _REGISTRY_EPOCH += 1


def _snapshot_path(checker, epoch):
    return os.path.join(checker.job_dir, f"epoch_{epoch}")


def _save_snapshot(checker, epoch, fs):
    from ..framework.serialization import save

    final = _snapshot_path(checker, epoch)
    tmp = final + ".tmp"
    fs.delete(tmp)
    fs.mkdirs(tmp)
    for name, (model, optimizer, sync_fn) in _REGISTRY.items():
        if sync_fn is not None:
            sync_fn()
        save(model.state_dict(), os.path.join(tmp, f"{name}.pdparams"))
        if optimizer is not None:
            save(optimizer.state_dict(), os.path.join(tmp, f"{name}.pdopt"))
    with open(os.path.join(tmp, "meta"), "w") as f:
        f.write(str(epoch))
    fs.delete(final)
    fs.rename(tmp, final)  # atomic publish
    # rotation: drop oldest beyond _MAX_KEPT
    found = _list_snapshots(checker, fs)
    for old in found[:-_MAX_KEPT]:
        fs.delete(_snapshot_path(checker, old))


def _list_snapshots(checker, fs):
    dirs, _ = fs.ls_dir(checker.job_dir)
    epochs = []
    for d in dirs:
        if d.startswith("epoch_") and not d.endswith(".tmp"):
            try:
                epochs.append(int(d[len("epoch_"):]))
            except ValueError:
                continue
    return sorted(epochs)


def _load_latest(checker, fs):
    """Restore registered objects from the newest snapshot; returns the
    epoch it covered, or -1."""
    from ..framework.serialization import load

    found = _list_snapshots(checker, fs)
    if not found:
        return -1
    epoch = found[-1]
    path = _snapshot_path(checker, epoch)
    for name, (model, optimizer, _sync) in _REGISTRY.items():
        params_file = os.path.join(path, f"{name}.pdparams")
        if not fs.is_file(params_file):
            # registered after this snapshot was written (e.g. a second
            # Model.fit in the same process): nothing to restore for it
            continue
        model.set_state_dict(load(params_file))
        opt_file = os.path.join(path, f"{name}.pdopt")
        if optimizer is not None and fs.is_file(opt_file):
            optimizer.set_state_dict(load(opt_file))
    return epoch


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None):
    """Resumable epoch loop (auto_checkpoint.py train_epoch_range).

    Yields epoch indices. With the auto-checkpoint env configured, the
    registered model/optimizer are restored from the newest snapshot and
    completed epochs are skipped; a snapshot is written when at least
    ``save_checkpoint_inter`` seconds (env default) elapsed since the
    last one, and always at the final epoch.
    """
    from .fs_local import local_fs

    checker = AutoCheckpointChecker()
    if not checker.valid():
        yield from range(max_epoch_num)
        return

    fs = local_fs()
    inter = (checker.save_inter if save_checkpoint_inter is None
             else float(save_checkpoint_inter))
    start = _load_latest(checker, fs) + 1
    last_save = time.monotonic()
    for epoch in range(start, max_epoch_num):
        yield epoch
        now = time.monotonic()
        if now - last_save >= inter or epoch == max_epoch_num - 1:
            _save_snapshot(checker, epoch, fs)
            last_save = now
