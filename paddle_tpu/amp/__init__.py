"""Automatic mixed precision.

Reference parity:
- dygraph autocast: fluid/dygraph/amp/auto_cast.py + C++ hook
  imperative/amp_auto_cast.cc (white/black op lists, cast-at-dispatch)
- loss scaling: fluid/dygraph/amp/loss_scaler.py:27 (AmpScaler) over
  operators/amp/amp_check_finite_and_scale_op
- static decorator: fluid/contrib/mixed_precision/decorator.py + fp16_lists.py

TPU-native: the autocast dtype is bfloat16 — same exponent range as fp32,
so loss scaling is numerically unnecessary (GradScaler defaults to
enabled=False on bf16 but keeps the fp16 API for parity). The cast hook
runs at eager op dispatch (framework/autograd.py _amp_hook) and therefore
also inside functionalized/jitted train steps, where XLA folds the casts
into fused matmul epilogues.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp
import numpy as np

from ..framework import autograd
from ..framework.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "GradScaler", "AmpScaler", "decorate",
           "WHITE_LIST", "BLACK_LIST"]

# fp16_lists.py white list: matmul-class ops that benefit from MXU dtype.
# "linear" is the workhorse: every nn.Linear dispatches it, and leaving
# it off the list silently ran all transformer MLPs in f32 (caught by
# tools/bert_dots.py: 225 of 300 BERT-step dots were f32).
WHITE_LIST = {
    "matmul", "mul", "bmm", "addmm", "einsum", "linear",
    "conv1d", "conv2d", "conv2d_transpose", "conv3d",
}
# fp16_lists.py black list: numerically sensitive reductions/normalizations.
# TPU divergence from the reference's fp16 lists: batch_norm and layer_norm
# are NOT black-listed — their kernels internally accumulate statistics in
# f32 while carrying the activation dtype (ops/kernels.py), which is the
# TPU-native bf16 recipe. Black-listing them would round-trip every
# activation through an f32 HBM buffer and make conv nets memory-bound
# (measured 2x step time on ResNet-50, see COVERAGE.md).
BLACK_LIST = {
    "softmax_with_cross_entropy", "cross_entropy", "softmax", "log_softmax",
    "group_norm", "instance_norm",
    "exp", "log", "log2", "log10", "log1p", "logsumexp",
    "reduce_mean", "reduce_sum", "mean", "sum", "cumsum",
    "sigmoid", "erf", "pow", "rsqrt", "sqrt", "square",
}

_state = threading.local()


def _enabled():
    return getattr(_state, "amp", None)


def _hook(op_type, arrays):
    """Cast arrays at op dispatch per the active autocast scope."""
    scope = _enabled()
    if scope is None:
        return arrays
    dtype, white, black = scope
    if op_type in white:
        return [
            a.astype(dtype)
            if hasattr(a, "dtype") and a.dtype == jnp.float32
            else a
            for a in arrays
        ]
    if op_type in black:
        return [
            a.astype(jnp.float32)
            if hasattr(a, "dtype") and a.dtype == jnp.dtype(dtype)
            else a
            for a in arrays
        ]
    return arrays


autograd.set_amp_hook(_hook)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast — scope in which white-listed ops run in
    bf16/fp16."""
    if not enable:
        yield
        return
    white = set(WHITE_LIST) | set(custom_white_list or ())
    black = (set(BLACK_LIST) | set(custom_black_list or ())) - set(
        custom_white_list or ()
    )
    if level == "O2":
        # O2: everything except the black list
        white = None  # sentinel: cast-all handled below
    prev = _enabled()
    jdtype = jnp.dtype(dtype)
    if white is None:
        scope = (jdtype, _CastAll(black), black)
    else:
        scope = (jdtype, white, black)
    _state.amp = scope
    try:
        yield
    finally:
        _state.amp = prev


class _CastAll:
    """O2 'white list': every op except the black list."""

    def __init__(self, black):
        self.black = black

    def __contains__(self, op):
        return op not in self.black


amp_guard = auto_cast  # fluid.dygraph.amp.amp_guard alias


class GradScaler:
    """Dynamic loss scaler (AmpScaler, fluid/dygraph/amp/loss_scaler.py:27).

    On bf16 (TPU default) scaling is a no-op unless explicitly enabled;
    the fp16 semantics (scale, unscale, inf check, dynamic adjustment)
    are implemented exactly for API and numeric parity.
    """

    def __init__(self, enable=True, init_loss_scaling=32768.0,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        from .. import ops

        return ops.scale(var, scale=self._scale)

    def unscale_(self, optimizer):
        """Divide grads by the scale; record found_inf
        (amp_check_finite_and_scale semantics)."""
        if not self._enable:
            self._found_inf = False
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._array * inv
            finite = bool(jnp.all(jnp.isfinite(g)))
            found = found or not finite
            p.grad = Tensor._from_array(g)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        optimizer.clear_grad()

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale

    def set_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


AmpScaler = GradScaler


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate — O2: cast model parameters to the AMP dtype.

    Master weights: the functionalized optimizer keeps its accumulators in
    the original param dtype; with master_weight=True params stay fp32 and
    only compute autocasts (equivalent to O1 + cast-all)."""
    if level not in ("O1", "O2"):
        raise ValueError("level must be O1 or O2")
    if level == "O2" and models is not None and not master_weight:
        target = jnp.dtype(dtype)
        model_list = models if isinstance(models, (list, tuple)) else [models]
        for m in model_list:
            for _, p in m.named_parameters():
                if p._array.dtype == jnp.float32:
                    p._array = p._array.astype(target)
    if optimizers is None:
        return models
    return models, optimizers
