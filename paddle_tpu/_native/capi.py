"""Build helper for the C inference API (capi.cpp).

Reference parity: inference/capi/ builds libpaddle_fluid_c.so; here
build_capi() compiles libpaddle_tpu_capi.so (embedding CPython) into
the native cache and returns its path. C hosts link against it and the
Python shared library:

    g++ main.c -o app -L<cache> -lpaddle_tpu_capi \
        -L$(python3-config --prefix)/lib -lpython3.12

PYTHONPATH must reach paddle_tpu at runtime (the embedded interpreter
imports it on PD_Init).
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import sysconfig

from . import _CACHE, _HERE


def build_capi() -> str:
    """Compile capi.cpp → cached libpaddle_tpu_capi.so; returns the path."""
    src_path = os.path.join(_HERE, "capi.cpp")
    with open(src_path, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    os.makedirs(_CACHE, exist_ok=True)
    so_path = os.path.join(_CACHE, f"libpaddle_tpu_capi-{digest}.so")
    if os.path.exists(so_path):
        return so_path
    include = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ldver = sysconfig.get_config_var("LDVERSION")
    tmp = so_path + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", src_path, "-o", tmp,
        f"-L{libdir}", f"-lpython{ldver}", f"-Wl,-rpath,{libdir}",
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so_path)
    return so_path
