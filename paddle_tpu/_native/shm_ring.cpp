// Shared-memory ring buffer for DataLoader worker->trainer batch transport.
//
// Reference parity: paddle/fluid/memory/allocation/mmap_allocator.cc +
// paddle/fluid/pybind/reader_py.cc — the reference moves LoDTensors between
// DataLoader worker processes and the trainer through shared memory to avoid
// pickling through a pipe. This is the TPU-framework equivalent: a
// single-producer single-consumer byte ring in POSIX shm (one ring per
// worker), length-framed records, lock-free via C11 atomics.
//
// Built at first import by paddle_tpu/_native/__init__.py (g++ -shared);
// accessed via ctypes. No Python.h dependency (pybind11 is not available in
// this image — see repo build notes).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct RingHeader {
  std::atomic<uint64_t> head;  // write cursor (bytes, monotonically grows)
  std::atomic<uint64_t> tail;  // read cursor
  uint64_t capacity;           // data region size in bytes
};

struct Ring {
  RingHeader* hdr;
  uint8_t* data;
  uint64_t map_size;
  int fd;
};

inline uint64_t ring_free(const RingHeader* h) {
  return h->capacity -
         (h->head.load(std::memory_order_acquire) -
          h->tail.load(std::memory_order_acquire));
}

inline uint64_t ring_used(const RingHeader* h) {
  return h->head.load(std::memory_order_acquire) -
         h->tail.load(std::memory_order_acquire);
}

void copy_in(Ring* r, uint64_t pos, const uint8_t* src, uint64_t n) {
  uint64_t off = pos % r->hdr->capacity;
  uint64_t first = n < (r->hdr->capacity - off) ? n : (r->hdr->capacity - off);
  std::memcpy(r->data + off, src, first);
  if (n > first) std::memcpy(r->data, src + first, n - first);
}

void copy_out(Ring* r, uint64_t pos, uint8_t* dst, uint64_t n) {
  uint64_t off = pos % r->hdr->capacity;
  uint64_t first = n < (r->hdr->capacity - off) ? n : (r->hdr->capacity - off);
  std::memcpy(dst, r->data + off, first);
  if (n > first) std::memcpy(dst + first, r->data, n - first);
}

}  // namespace

extern "C" {

// Create (owner=1) or attach (owner=0) a ring of `capacity` data bytes.
// Returns an opaque handle or null.
void* shmring_open(const char* name, uint64_t capacity, int owner) {
  int flags = owner ? (O_CREAT | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  uint64_t map_size = sizeof(RingHeader) + capacity;
  if (owner) {
    if (ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0 || static_cast<uint64_t>(st.st_size) < map_size) {
      close(fd);
      return nullptr;
    }
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Ring* r = new Ring();
  r->hdr = static_cast<RingHeader*>(mem);
  r->data = reinterpret_cast<uint8_t*>(mem) + sizeof(RingHeader);
  r->map_size = map_size;
  r->fd = fd;
  if (owner) {
    r->hdr->head.store(0, std::memory_order_relaxed);
    r->hdr->tail.store(0, std::memory_order_relaxed);
    r->hdr->capacity = capacity;
  }
  return r;
}

// Push one length-framed record. Returns 0 on success, -1 if it does not
// fit right now (caller retries), -2 if it can never fit.
int shmring_push(void* handle, const uint8_t* buf, uint64_t n) {
  Ring* r = static_cast<Ring*>(handle);
  uint64_t need = n + sizeof(uint64_t);
  if (need > r->hdr->capacity) return -2;
  if (ring_free(r->hdr) < need) return -1;
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  copy_in(r, head, reinterpret_cast<const uint8_t*>(&n), sizeof(uint64_t));
  copy_in(r, head + sizeof(uint64_t), buf, n);
  r->hdr->head.store(head + need, std::memory_order_release);
  return 0;
}

// Size of the next record, or -1 if empty.
int64_t shmring_next_size(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  if (ring_used(r->hdr) < sizeof(uint64_t)) return -1;
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  uint64_t n;
  copy_out(r, tail, reinterpret_cast<uint8_t*>(&n), sizeof(uint64_t));
  return static_cast<int64_t>(n);
}

// Pop the next record into out (must hold shmring_next_size bytes).
// Returns bytes written, or -1 if empty.
int64_t shmring_pop(void* handle, uint8_t* out, uint64_t max) {
  Ring* r = static_cast<Ring*>(handle);
  int64_t n = shmring_next_size(handle);
  if (n < 0 || static_cast<uint64_t>(n) > max) return -1;
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  copy_out(r, tail + sizeof(uint64_t), out, static_cast<uint64_t>(n));
  r->hdr->tail.store(tail + sizeof(uint64_t) + static_cast<uint64_t>(n),
                     std::memory_order_release);
  return n;
}

uint64_t shmring_used(void* handle) {
  return ring_used(static_cast<Ring*>(handle)->hdr);
}

void shmring_close(void* handle, const char* name, int unlink_it) {
  Ring* r = static_cast<Ring*>(handle);
  munmap(r->hdr, r->map_size);
  close(r->fd);
  if (unlink_it) shm_unlink(name);
  delete r;
}

}  // extern "C"
