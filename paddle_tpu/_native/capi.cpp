// C inference API (reference parity: paddle/fluid/inference/capi/
// paddle_c_api.h + c_api.cc — a C ABI over the AnalysisPredictor so
// non-C++ hosts can run inference).
//
// TPU-native design: the predictor itself is the XLA-compiled static
// executor driven from Python; this library embeds the CPython
// interpreter (the inverse of the reference's pybind direction) and
// exposes the same create/set-input/run/fetch surface as C symbols.
//
// Threading contract: every exported entry point acquires the GIL via
// PyGILState_Ensure/Release, and PD_Init releases the GIL it acquired
// by initializing the interpreter (PyEval_SaveThread) — so PD_* calls
// are safe from any host thread; they serialize on the GIL.
// Name-pointer lifetime: the const char* returned by PD_GetInputName /
// PD_GetOutputName stays valid until the NEXT call to the same pair of
// functions from any thread; copy it out if you need it longer.
#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

PyObject* g_helpers = nullptr;  // module dict with the helper functions
std::mutex g_init_mutex;        // serializes first-time interpreter init
std::mutex g_error_mutex;       // guards g_last_error (readable GIL-less)
std::string g_last_error;
std::string g_name_scratch;  // PD_Get{Input,Output}Name return pointers here

// Acquire the GIL for the scope of one exported call.
struct GilGuard {
  PyGILState_STATE st;
  GilGuard() : st(PyGILState_Ensure()) {}
  ~GilGuard() { PyGILState_Release(st); }
};

// The documented fetch sequence is ndim -> shape -> copy; each used to
// re-run the device->host transfer. Cache the last fetched output per
// (pred, name) and invalidate on PD_Run / PD_DeletePredictor.
struct OutputCache {
  void* pred = nullptr;
  std::string name;
  std::string bytes;
  std::vector<long long> shape;
  std::string dtype;
  bool valid = false;
};
OutputCache g_out_cache;

const char kHelperSrc[] = R"PY(
import numpy as np
import paddle_tpu
from paddle_tpu.inference import Config, create_predictor

def _create(model_dir):
    return create_predictor(Config(model_dir))

def _input_names(pred):
    return pred.get_input_names()

def _output_names(pred):
    return pred.get_output_names()

def _set_input(pred, name, data, shape, dtype):
    arr = np.frombuffer(data, dtype=dtype).reshape(shape)
    pred.get_input_handle(name).copy_from_cpu(arr)

def _run(pred):
    pred.run()

def _get_output(pred, name):
    out = np.ascontiguousarray(pred.get_output_handle(name).copy_to_cpu())
    return out.tobytes(), list(out.shape), str(out.dtype)
)PY";

void set_error(const std::string& msg) {
  std::lock_guard<std::mutex> lock(g_error_mutex);
  g_last_error = msg;
}

void set_error_from_python() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyObject* s = value ? PyObject_Str(value) : nullptr;
  // PyUnicode_AsUTF8 can itself fail (lone surrogates) and return NULL
  const char* msg = s ? PyUnicode_AsUTF8(s) : nullptr;
  if (!msg) PyErr_Clear();
  set_error(msg ? msg : "unknown python error");
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject* helper(const char* name) {
  return PyDict_GetItemString(g_helpers, name);  // borrowed
}

// Must be called with the GIL held.
int init_helpers_locked() {
  if (g_helpers) return 0;
  PyObject* mod = PyModule_New("paddle_tpu_capi_helpers");
  PyObject* dict = PyModule_GetDict(mod);
  PyDict_SetItemString(dict, "__builtins__", PyEval_GetBuiltins());
  PyObject* res = PyRun_String(kHelperSrc, Py_file_input, dict, dict);
  if (!res) {
    set_error_from_python();
    Py_DECREF(mod);
    return -1;
  }
  Py_DECREF(res);
  g_helpers = dict;
  Py_INCREF(g_helpers);
  return 0;
}

// Fetch (or reuse) an output; returns the cache entry or nullptr.
// GIL must be held.
const OutputCache* get_output_locked(void* pred, const char* name) {
  if (g_out_cache.valid && g_out_cache.pred == pred &&
      g_out_cache.name == name) {
    return &g_out_cache;
  }
  PyObject* out = PyObject_CallFunction(
      helper("_get_output"), "Os", static_cast<PyObject*>(pred), name);
  if (!out) {
    set_error_from_python();
    return nullptr;
  }
  PyObject* bytes = PyTuple_GetItem(out, 0);
  PyObject* shp = PyTuple_GetItem(out, 1);
  g_out_cache.pred = pred;
  g_out_cache.name = name;
  g_out_cache.bytes.assign(PyBytes_AsString(bytes),
                           static_cast<size_t>(PyBytes_Size(bytes)));
  g_out_cache.shape.clear();
  for (Py_ssize_t d = 0; d < PyList_Size(shp); ++d) {
    g_out_cache.shape.push_back(
        PyLong_AsLongLong(PyList_GetItem(shp, d)));
  }
  const char* dtype = PyUnicode_AsUTF8(PyTuple_GetItem(out, 2));
  if (!dtype) {  // encoding failure: don't construct string from NULL
    PyErr_Clear();
    set_error("output dtype string is not UTF-8 decodable");
    Py_DECREF(out);
    return nullptr;
  }
  g_out_cache.dtype = dtype;
  g_out_cache.valid = true;
  Py_DECREF(out);
  return &g_out_cache;
}

void invalidate_output_cache(void* pred) {
  // full reset, not just the flag: the byte buffer may be huge and must
  // not stay resident after PD_Run/PD_DeletePredictor
  if (g_out_cache.pred == pred) g_out_cache = OutputCache();
}

}  // namespace

extern "C" {

// All functions return 0 on success, -1 on error (PD_GetLastError tells).

const char* PD_GetLastError() {
  // copy under the mutex into thread-local storage: another thread's
  // failing call may reassign g_last_error while the caller reads
  static thread_local std::string tls_error;
  std::lock_guard<std::mutex> lock(g_error_mutex);
  tls_error = g_last_error;
  return tls_error.c_str();
}

int PD_Init() {
  // g_init_mutex: two threads racing here on a fresh process would both
  // see Py_IsInitialized()==false; the loser would then run the helper
  // setup without the GIL and release a GIL it never held.
  std::lock_guard<std::mutex> lock(g_init_mutex);
  if (g_helpers) return 0;
  if (!Py_IsInitialized()) {
    Py_Initialize();
    // Py_Initialize leaves this thread holding the GIL. Do the one-time
    // setup, then hand the GIL back so other host threads can enter via
    // PyGILState_Ensure.
    int rc = init_helpers_locked();
    PyEval_SaveThread();
    return rc;
  }
  GilGuard gil;
  return init_helpers_locked();
}

void* PD_CreatePredictor(const char* model_dir) {
  if (PD_Init() != 0) return nullptr;
  GilGuard gil;
  PyObject* out = PyObject_CallFunction(helper("_create"), "s", model_dir);
  if (!out) {
    set_error_from_python();
    return nullptr;
  }
  return out;  // owned handle
}

void PD_DeletePredictor(void* pred) {
  GilGuard gil;
  invalidate_output_cache(pred);
  Py_XDECREF(static_cast<PyObject*>(pred));
}

// GIL must be held by the caller.
static int name_at_locked(const char* fn, void* pred, int i,
                          const char** out) {
  PyObject* names = PyObject_CallFunction(
      helper(fn), "O", static_cast<PyObject*>(pred));
  if (!names) {
    set_error_from_python();
    return -1;
  }
  Py_ssize_t n = PyList_Size(names);
  if (i < 0 || i >= n) {
    set_error("index out of range");
    Py_DECREF(names);
    return -1;
  }
  const char* name = PyUnicode_AsUTF8(PyList_GetItem(names, i));
  if (!name) {
    PyErr_Clear();
    set_error("tensor name is not UTF-8 decodable");
    Py_DECREF(names);
    return -1;
  }
  g_name_scratch = name;
  Py_DECREF(names);
  *out = g_name_scratch.c_str();
  return 0;
}

int PD_GetInputNum(void* pred) {
  GilGuard gil;
  PyObject* names = PyObject_CallFunction(
      helper("_input_names"), "O", static_cast<PyObject*>(pred));
  if (!names) {
    set_error_from_python();
    return -1;
  }
  int n = static_cast<int>(PyList_Size(names));
  Py_DECREF(names);
  return n;
}

int PD_GetOutputNum(void* pred) {
  GilGuard gil;
  PyObject* names = PyObject_CallFunction(
      helper("_output_names"), "O", static_cast<PyObject*>(pred));
  if (!names) {
    set_error_from_python();
    return -1;
  }
  int n = static_cast<int>(PyList_Size(names));
  Py_DECREF(names);
  return n;
}

const char* PD_GetInputName(void* pred, int i) {
  GilGuard gil;
  const char* out = nullptr;
  return name_at_locked("_input_names", pred, i, &out) == 0 ? out : nullptr;
}

const char* PD_GetOutputName(void* pred, int i) {
  GilGuard gil;
  const char* out = nullptr;
  return name_at_locked("_output_names", pred, i, &out) == 0 ? out : nullptr;
}

// GIL must be held by the caller.
static int set_input_locked(void* pred, const char* name, const void* data,
                            size_t bytes, const long long* shape, int ndim,
                            const char* dtype) {
  PyObject* shp = PyList_New(ndim);
  for (int d = 0; d < ndim; ++d) {
    PyList_SetItem(shp, d, PyLong_FromLongLong(shape[d]));
  }
  PyObject* buf = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(bytes));
  PyObject* res = PyObject_CallFunction(
      helper("_set_input"), "OsOOs", static_cast<PyObject*>(pred), name,
      buf, shp, dtype);
  Py_DECREF(shp);
  Py_DECREF(buf);
  if (!res) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int PD_SetInputFloat(void* pred, const char* name, const float* data,
                     const long long* shape, int ndim) {
  GilGuard gil;
  size_t numel = 1;
  for (int d = 0; d < ndim; ++d) numel *= static_cast<size_t>(shape[d]);
  return set_input_locked(pred, name, data, numel * sizeof(float), shape,
                          ndim, "float32");
}

int PD_SetInputInt64(void* pred, const char* name, const long long* data,
                     const long long* shape, int ndim) {
  GilGuard gil;
  size_t numel = 1;
  for (int d = 0; d < ndim; ++d) numel *= static_cast<size_t>(shape[d]);
  return set_input_locked(pred, name, data, numel * sizeof(long long),
                          shape, ndim, "int64");
}

int PD_Run(void* pred) {
  GilGuard gil;
  invalidate_output_cache(pred);  // outputs change after a run
  PyObject* res = PyObject_CallFunction(
      helper("_run"), "O", static_cast<PyObject*>(pred));
  if (!res) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

// Fetch: query ndim/shape first, then copy the flat float data. The
// device->host transfer happens once; ndim/shape/copy share the cache.
int PD_GetOutputNdim(void* pred, const char* name) {
  GilGuard gil;
  const OutputCache* c = get_output_locked(pred, name);
  return c ? static_cast<int>(c->shape.size()) : -1;
}

int PD_GetOutputShape(void* pred, const char* name, long long* shape_out) {
  GilGuard gil;
  const OutputCache* c = get_output_locked(pred, name);
  if (!c) return -1;
  for (size_t d = 0; d < c->shape.size(); ++d) shape_out[d] = c->shape[d];
  return 0;
}

int PD_CopyOutputFloat(void* pred, const char* name, float* buf,
                       long long numel) {
  GilGuard gil;
  const OutputCache* c = get_output_locked(pred, name);
  if (!c) return -1;
  if (c->dtype != "float32") {
    set_error("output dtype is " + c->dtype +
              ", use the matching PD_CopyOutput*");
    return -1;
  }
  size_t want = static_cast<size_t>(numel) * sizeof(float);
  if (c->bytes.size() != want) {
    set_error("output size mismatch");
    return -1;
  }
  std::memcpy(buf, c->bytes.data(), want);
  return 0;
}

void PD_Finalize() {
  GilGuard gil;
  g_out_cache = OutputCache();
  Py_XDECREF(g_helpers);
  g_helpers = nullptr;
  // the interpreter stays up: other predictors/embedders may share it
}

}  // extern "C"
