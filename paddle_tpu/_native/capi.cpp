// C inference API (reference parity: paddle/fluid/inference/capi/
// paddle_c_api.h + c_api.cc — a C ABI over the AnalysisPredictor so
// non-C++ hosts can run inference).
//
// TPU-native design: the predictor itself is the XLA-compiled static
// executor driven from Python; this library embeds the CPython
// interpreter (the inverse of the reference's pybind direction) and
// exposes the same create/set-input/run/fetch surface as C symbols.
// One interpreter serves all predictors; calls are GIL-serialized so
// the ABI is thread-safe for independent handles.
#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

PyObject* g_helpers = nullptr;  // module dict with the helper functions
std::string g_last_error;
std::string g_scratch;  // returned const char*s point here

const char kHelperSrc[] = R"PY(
import numpy as np
import paddle_tpu
from paddle_tpu.inference import Config, create_predictor

def _create(model_dir):
    return create_predictor(Config(model_dir))

def _input_names(pred):
    return pred.get_input_names()

def _output_names(pred):
    return pred.get_output_names()

def _set_input(pred, name, data, shape, dtype):
    arr = np.frombuffer(data, dtype=dtype).reshape(shape)
    pred.get_input_handle(name).copy_from_cpu(arr)

def _run(pred):
    pred.run()

def _get_output(pred, name):
    out = np.ascontiguousarray(pred.get_output_handle(name).copy_to_cpu())
    return out.tobytes(), list(out.shape), str(out.dtype)
)PY";

void set_error_from_python() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyObject* s = value ? PyObject_Str(value) : nullptr;
  g_last_error = s ? PyUnicode_AsUTF8(s) : "unknown python error";
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject* helper(const char* name) {
  return PyDict_GetItemString(g_helpers, name);  // borrowed
}

}  // namespace

extern "C" {

// All functions return 0 on success, -1 on error (PD_GetLastError tells).

const char* PD_GetLastError() { return g_last_error.c_str(); }

int PD_Init() {
  if (g_helpers) return 0;
  if (!Py_IsInitialized()) Py_Initialize();
  PyObject* mod = PyModule_New("paddle_tpu_capi_helpers");
  PyObject* dict = PyModule_GetDict(mod);
  PyDict_SetItemString(dict, "__builtins__", PyEval_GetBuiltins());
  PyObject* res =
      PyRun_String(kHelperSrc, Py_file_input, dict, dict);
  if (!res) {
    set_error_from_python();
    Py_DECREF(mod);
    return -1;
  }
  Py_DECREF(res);
  g_helpers = dict;
  Py_INCREF(g_helpers);
  return 0;
}

void* PD_CreatePredictor(const char* model_dir) {
  if (PD_Init() != 0) return nullptr;
  PyObject* out = PyObject_CallFunction(helper("_create"), "s", model_dir);
  if (!out) {
    set_error_from_python();
    return nullptr;
  }
  return out;  // owned handle
}

void PD_DeletePredictor(void* pred) {
  Py_XDECREF(static_cast<PyObject*>(pred));
}

static int name_at(const char* fn, void* pred, int i, const char** out) {
  PyObject* names = PyObject_CallFunction(
      helper(fn), "O", static_cast<PyObject*>(pred));
  if (!names) {
    set_error_from_python();
    return -1;
  }
  Py_ssize_t n = PyList_Size(names);
  if (i < 0 || i >= n) {
    g_last_error = "index out of range";
    Py_DECREF(names);
    return -1;
  }
  g_scratch = PyUnicode_AsUTF8(PyList_GetItem(names, i));
  Py_DECREF(names);
  *out = g_scratch.c_str();
  return 0;
}

int PD_GetInputNum(void* pred) {
  PyObject* names = PyObject_CallFunction(
      helper("_input_names"), "O", static_cast<PyObject*>(pred));
  if (!names) {
    set_error_from_python();
    return -1;
  }
  int n = static_cast<int>(PyList_Size(names));
  Py_DECREF(names);
  return n;
}

int PD_GetOutputNum(void* pred) {
  PyObject* names = PyObject_CallFunction(
      helper("_output_names"), "O", static_cast<PyObject*>(pred));
  if (!names) {
    set_error_from_python();
    return -1;
  }
  int n = static_cast<int>(PyList_Size(names));
  Py_DECREF(names);
  return n;
}

const char* PD_GetInputName(void* pred, int i) {
  const char* out = nullptr;
  return name_at("_input_names", pred, i, &out) == 0 ? out : nullptr;
}

const char* PD_GetOutputName(void* pred, int i) {
  const char* out = nullptr;
  return name_at("_output_names", pred, i, &out) == 0 ? out : nullptr;
}

static int set_input(void* pred, const char* name, const void* data,
                     size_t bytes, const long long* shape, int ndim,
                     const char* dtype) {
  PyObject* shp = PyList_New(ndim);
  for (int d = 0; d < ndim; ++d) {
    PyList_SetItem(shp, d, PyLong_FromLongLong(shape[d]));
  }
  PyObject* buf = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(bytes));
  PyObject* res = PyObject_CallFunction(
      helper("_set_input"), "OsOOs", static_cast<PyObject*>(pred), name,
      buf, shp, dtype);
  Py_DECREF(shp);
  Py_DECREF(buf);
  if (!res) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int PD_SetInputFloat(void* pred, const char* name, const float* data,
                     const long long* shape, int ndim) {
  size_t numel = 1;
  for (int d = 0; d < ndim; ++d) numel *= static_cast<size_t>(shape[d]);
  return set_input(pred, name, data, numel * sizeof(float), shape, ndim,
                   "float32");
}

int PD_SetInputInt64(void* pred, const char* name, const long long* data,
                     const long long* shape, int ndim) {
  size_t numel = 1;
  for (int d = 0; d < ndim; ++d) numel *= static_cast<size_t>(shape[d]);
  return set_input(pred, name, data, numel * sizeof(long long), shape,
                   ndim, "int64");
}

int PD_Run(void* pred) {
  PyObject* res = PyObject_CallFunction(
      helper("_run"), "O", static_cast<PyObject*>(pred));
  if (!res) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

// Fetch: query ndim/shape first, then copy the flat float data.
int PD_GetOutputNdim(void* pred, const char* name) {
  PyObject* out = PyObject_CallFunction(
      helper("_get_output"), "Os", static_cast<PyObject*>(pred), name);
  if (!out) {
    set_error_from_python();
    return -1;
  }
  int ndim = static_cast<int>(PyList_Size(PyTuple_GetItem(out, 1)));
  Py_DECREF(out);
  return ndim;
}

int PD_GetOutputShape(void* pred, const char* name, long long* shape_out) {
  PyObject* out = PyObject_CallFunction(
      helper("_get_output"), "Os", static_cast<PyObject*>(pred), name);
  if (!out) {
    set_error_from_python();
    return -1;
  }
  PyObject* shp = PyTuple_GetItem(out, 1);
  for (Py_ssize_t d = 0; d < PyList_Size(shp); ++d) {
    shape_out[d] = PyLong_AsLongLong(PyList_GetItem(shp, d));
  }
  Py_DECREF(out);
  return 0;
}

int PD_CopyOutputFloat(void* pred, const char* name, float* buf,
                       long long numel) {
  PyObject* out = PyObject_CallFunction(
      helper("_get_output"), "Os", static_cast<PyObject*>(pred), name);
  if (!out) {
    set_error_from_python();
    return -1;
  }
  PyObject* bytes = PyTuple_GetItem(out, 0);
  const char* dtype = PyUnicode_AsUTF8(PyTuple_GetItem(out, 2));
  if (std::strcmp(dtype, "float32") != 0) {
    g_last_error = std::string("output dtype is ") + dtype +
                   ", use the matching PD_CopyOutput*";
    Py_DECREF(out);
    return -1;
  }
  Py_ssize_t have = PyBytes_Size(bytes);
  size_t want = static_cast<size_t>(numel) * sizeof(float);
  if (static_cast<size_t>(have) != want) {
    g_last_error = "output size mismatch";
    Py_DECREF(out);
    return -1;
  }
  std::memcpy(buf, PyBytes_AsString(bytes), want);
  Py_DECREF(out);
  return 0;
}

void PD_Finalize() {
  Py_XDECREF(g_helpers);
  g_helpers = nullptr;
  // the interpreter stays up: other predictors/embedders may share it
}

}  // extern "C"
