"""Native (C++) runtime components.

Reference parity: the reference implements its performance-critical runtime
pieces in C++ (SURVEY.md §2.1); the pieces that survive on TPU (where XLA
owns device memory and kernels) are the host-side ones:

- shm_ring: shared-memory DataLoader transport
  (memory/allocation/mmap_allocator.cc + pybind/reader_py.cc equivalent)

Modules are compiled on first import with g++ into a per-user cache and
loaded via ctypes (pybind11 is not available in this image; the C ABI +
ctypes pattern mirrors the reference's C ABI plugin surface,
framework/c/c_api.h). Import failures degrade gracefully — callers fall
back to pure-python transports.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import pickle
import subprocess
import tempfile
import time

_HERE = os.path.dirname(__file__)
_CACHE = os.path.expanduser(
    os.environ.get("PADDLE_TPU_NATIVE_CACHE", "~/.cache/paddle_tpu/native")
)


def _build(name: str, src_file: str) -> str:
    """Compile a .cpp into a cached shared object; returns the .so path."""
    src_path = os.path.join(_HERE, src_file)
    with open(src_path, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    os.makedirs(_CACHE, exist_ok=True)
    so_path = os.path.join(_CACHE, f"{name}-{digest}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = so_path + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        src_path, "-o", tmp, "-lrt", "-pthread",
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so_path)
    return so_path


class ShmRing:
    """SPSC shared-memory record ring (one per DataLoader worker)."""

    _lib = None

    @classmethod
    def _load(cls):
        if cls._lib is None:
            lib = ctypes.CDLL(_build("shm_ring", "shm_ring.cpp"))
            lib.shmring_open.restype = ctypes.c_void_p
            lib.shmring_open.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
            ]
            lib.shmring_push.restype = ctypes.c_int
            lib.shmring_push.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ]
            lib.shmring_next_size.restype = ctypes.c_int64
            lib.shmring_next_size.argtypes = [ctypes.c_void_p]
            lib.shmring_pop.restype = ctypes.c_int64
            lib.shmring_pop.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ]
            lib.shmring_used.restype = ctypes.c_uint64
            lib.shmring_used.argtypes = [ctypes.c_void_p]
            lib.shmring_close.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ]
            cls._lib = lib
        return cls._lib

    def __init__(self, name=None, capacity=64 << 20, owner=True):
        lib = self._load()
        self.name = name or f"/ptpu_ring_{os.getpid()}_{id(self) & 0xFFFF}"
        self.capacity = capacity
        self._owner = owner
        self._handle = lib.shmring_open(
            self.name.encode(), capacity, 1 if owner else 0
        )
        if not self._handle:
            raise OSError(f"shmring_open({self.name}) failed")

    # -- raw bytes ----------------------------------------------------------
    def push_bytes(self, payload: bytes, timeout=30.0):
        lib = self._lib
        deadline = time.monotonic() + timeout
        while True:
            rc = lib.shmring_push(self._handle, payload, len(payload))
            if rc == 0:
                return
            if rc == -2:
                raise ValueError(
                    f"record of {len(payload)} bytes exceeds ring capacity "
                    f"{self.capacity}"
                )
            if time.monotonic() > deadline:
                raise TimeoutError("shm ring full")
            time.sleep(0.0005)

    def pop_bytes(self, timeout=30.0):
        lib = self._lib
        deadline = time.monotonic() + timeout
        while True:
            n = lib.shmring_next_size(self._handle)
            if n >= 0:
                buf = ctypes.create_string_buffer(n)
                got = lib.shmring_pop(self._handle, buf, n)
                if got == n:
                    return buf.raw
            if time.monotonic() > deadline:
                raise TimeoutError("shm ring empty")
            time.sleep(0.0005)

    # -- pickled objects ----------------------------------------------------
    def put(self, obj, timeout=30.0):
        self.push_bytes(pickle.dumps(obj, protocol=4), timeout)

    def get(self, timeout=30.0):
        return pickle.loads(self.pop_bytes(timeout))

    def empty(self):
        return self._lib.shmring_used(self._handle) == 0

    def close(self, unlink=None):
        if self._handle:
            self._lib.shmring_close(
                self._handle, self.name.encode(),
                1 if (self._owner if unlink is None else unlink) else 0,
            )
            self._handle = None

    def __del__(self):
        try:
            self.close(unlink=False)
        except Exception:
            pass


def available() -> bool:
    try:
        ShmRing._load()
        return True
    except Exception:
        return False


_datafeed_lib = [None]


def _load_datafeed():
    if _datafeed_lib[0] is None:
        lib = ctypes.CDLL(_build("datafeed", "datafeed.cpp"))
        LL = ctypes.c_longlong
        lib.pt_multislot_parse.restype = LL
        lib.pt_multislot_parse.argtypes = [
            ctypes.c_char_p, LL,
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.POINTER(LL), LL,
            ctypes.POINTER(LL), LL,
            ctypes.POINTER(ctypes.c_float), LL,
            ctypes.POINTER(LL), ctypes.POINTER(LL),
        ]
        _datafeed_lib[0] = lib
    return _datafeed_lib[0]


def multislot_parse(buf: bytes, slot_is_float):
    """Parse MultiSlot text (data_feed.cc format) via the native parser.

    Returns (counts[n_inst, n_slots] int64, ints int64[], floats float32[]).
    Raises ValueError on malformed input (with the byte offset).
    """
    import numpy as np

    lib = _load_datafeed()
    LL = ctypes.c_longlong
    n_slots = len(slot_is_float)
    sif = (ctypes.c_int * n_slots)(*[1 if f else 0 for f in slot_is_float])
    ti, tf = LL(0), LL(0)
    # pass 1: size
    n_inst = lib.pt_multislot_parse(
        buf, len(buf), sif, n_slots,
        None, 0, None, 0, None, 0,
        ctypes.byref(ti), ctypes.byref(tf),
    )
    if n_inst < 0:
        raise ValueError(
            f"malformed MultiSlot record near byte {-(n_inst + 1)}"
        )
    counts = np.zeros(n_inst * n_slots, np.int64)
    ints = np.zeros(max(1, ti.value), np.int64)
    floats = np.zeros(max(1, tf.value), np.float32)
    rc = lib.pt_multislot_parse(
        buf, len(buf), sif, n_slots,
        counts.ctypes.data_as(ctypes.POINTER(LL)), counts.size,
        ints.ctypes.data_as(ctypes.POINTER(LL)), ints.size,
        floats.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), floats.size,
        ctypes.byref(ti), ctypes.byref(tf),
    )
    if rc != n_inst:
        raise ValueError("MultiSlot parse pass mismatch")
    return (counts.reshape(n_inst, n_slots), ints[:ti.value],
            floats[:tf.value])


def datafeed_available() -> bool:
    try:
        _load_datafeed()
        return True
    except Exception:
        return False
