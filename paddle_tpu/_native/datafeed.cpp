// MultiSlot text-format parser — the hot loop of the Dataset ingestion
// path.
//
// Reference parity: paddle/fluid/framework/data_feed.cc
// (MultiSlotDataFeed::ParseOneInstance) — each line is one instance; for
// each slot in declared order: a count token followed by that many value
// tokens (int64 ids for sparse slots, float32 for dense slots).
//
// Exposed as a C ABI consumed via ctypes (same pattern as shm_ring.cpp).
// Two-pass use: call with null pools to size, then with buffers to fill.
#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Returns the number of instances parsed, or -(1 + byte_offset) on a
// malformed line. counts[inst * n_slots + s] = value count of slot s.
// When ints/floats are null the pass only tallies: *total_ints /
// *total_floats / return value are still filled (counts too when
// non-null). slot_is_float[s]: 1 -> float32 slot, 0 -> int64 slot.
long long pt_multislot_parse(const char* buf, long long len,
                             const int* slot_is_float, int n_slots,
                             long long* counts, long long counts_cap,
                             long long* ints, long long ints_cap,
                             float* floats, long long floats_cap,
                             long long* total_ints,
                             long long* total_floats) {
  long long pos = 0, n_inst = 0, n_int = 0, n_float = 0;
  while (pos < len) {
    // skip blank lines
    while (pos < len && (buf[pos] == '\n' || buf[pos] == '\r')) pos++;
    if (pos >= len) break;
    for (int s = 0; s < n_slots; s++) {
      // parse the count token; '\r' = truncated CRLF line, and '\f'/'\v'
      // would be silently eaten by strtoll's own isspace() skip (possibly
      // across the newline) — all are malformed here
      while (pos < len && (buf[pos] == ' ' || buf[pos] == '\t')) pos++;
      if (pos >= len || buf[pos] == '\n' || buf[pos] == '\r' ||
          buf[pos] == '\f' || buf[pos] == '\v')
        return -(1 + pos);
      char* end = nullptr;
      long long cnt = strtoll(buf + pos, &end, 10);
      if (end == buf + pos || cnt < 0) return -(1 + pos);
      pos = end - buf;
      if (counts) {
        if (n_inst * n_slots + s >= counts_cap) return -(1 + pos);
        counts[n_inst * n_slots + s] = cnt;
      }
      for (long long v = 0; v < cnt; v++) {
        while (pos < len && (buf[pos] == ' ' || buf[pos] == '\t')) pos++;
        if (pos >= len || buf[pos] == '\n' || buf[pos] == '\r' ||
            buf[pos] == '\f' || buf[pos] == '\v')
          return -(1 + pos);
        if (slot_is_float[s]) {
          float val = strtof(buf + pos, &end);
          if (end == buf + pos) return -(1 + pos);
          if (floats) {
            if (n_float >= floats_cap) return -(1 + pos);
            floats[n_float] = val;
          }
          n_float++;
        } else {
          long long val = strtoll(buf + pos, &end, 10);
          if (end == buf + pos) return -(1 + pos);
          if (ints) {
            if (n_int >= ints_cap) return -(1 + pos);
            ints[n_int] = val;
          }
          n_int++;
        }
        pos = end - buf;
      }
    }
    // consume to end of line
    while (pos < len && buf[pos] != '\n') {
      if (buf[pos] != ' ' && buf[pos] != '\t' && buf[pos] != '\r')
        return -(1 + pos);  // trailing garbage = malformed instance
      pos++;
    }
    n_inst++;
  }
  if (total_ints) *total_ints = n_int;
  if (total_floats) *total_floats = n_float;
  return n_inst;
}

}  // extern "C"
