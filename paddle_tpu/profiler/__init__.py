"""Profiler.

Reference parity: paddle/fluid/platform/profiler.h (RAII RecordEvent :126,
EnableProfiler/DisableProfiler :208, chrome-trace export via
device_tracer.cc + profiler.proto) and python/paddle/fluid/profiler.py
context managers.

TPU-native: host-side RAII events feed a chrome-trace JSON directly;
device timelines come from jax.profiler (XPlane/perfetto) started and
stopped by the same switch — start_profiler/stop_profiler wrap both so
one API yields the merged picture the reference's CUPTI tracer gave.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

__all__ = [
    "RecordEvent",
    "record_event",
    "start_profiler",
    "stop_profiler",
    "profiler",
    "reset_profiler",
    "export_chrome_tracing",
    "bump_counter",
    "counters",
    "reset_counters",
    "device_trace_dir",
    "host_events",
]

_state = threading.local()
_events = []
_events_lock = threading.Lock()
_enabled = [False]
_device_trace_dir = [None]
# survives stop_profiler so monitor.export_merged_chrome_trace can find
# the device-side files the run just wrote
_last_device_trace_dir = [None]


def device_trace_dir():
    """Directory of the most recent jax device trace (None if the run
    never started one — e.g. state='CPU' profiling)."""
    return _last_device_trace_dir[0]

# -- dispatch counters --------------------------------------------------------
# Always-on monotonic counters (unlike timed events, which only record while
# the profiler is enabled): the executor's plan-cache hit/miss, jit-cache
# hit/miss, and donation accounting are cheap integer bumps that tests and
# bench.py read directly — the role of the reference's STAT_* registry
# (platform/monitor.h) rather than the timeline.
_counters: dict[str, int] = {}
_counters_lock = threading.Lock()


def bump_counter(name: str, n: int = 1) -> None:
    """Increment a named monotonic counter."""
    with _counters_lock:
        _counters[name] = _counters.get(name, 0) + n


def counters() -> dict:
    """Snapshot of all counters."""
    with _counters_lock:
        return dict(_counters)


def reset_counters() -> None:
    with _counters_lock:
        _counters.clear()


def _now_us():
    return time.perf_counter_ns() / 1e3


class RecordEvent:
    """RAII named range (platform/profiler.h:126). Usable as context
    manager or begin()/end() pair."""

    def __init__(self, name):
        self.name = name
        self._begin = None
        self._began_enabled = False

    def begin(self):
        # capture enabled-state NOW: the span's fate is decided here, so
        # (a) a span in flight when stop_profiler() lands (the executor's
        # last dispatch, a dataloader wait) is still recorded — losing
        # boundary spans silently skews stop-adjacent aggregates — and
        # (b) the disabled path never touches the clock: spans ride every
        # dispatch hot path always-on, so the off cost must be a boolean
        self._began_enabled = _enabled[0]
        if self._began_enabled:
            self._begin = _now_us()
        return self

    def end(self):
        if not self._began_enabled or self._begin is None:
            return
        ev = {
            "name": self.name,
            "ph": "X",
            "ts": self._begin,
            "dur": _now_us() - self._begin,
            "pid": os.getpid(),
            "tid": threading.get_ident() % 100000,
        }
        with _events_lock:
            _events.append(ev)
        self._begin = None

    def __enter__(self):
        return self.begin()

    def __exit__(self, *exc):
        self.end()
        return False


@contextlib.contextmanager
def record_event(name):
    with RecordEvent(name):
        yield


def _note_double_start(**fields):
    bump_counter("profiler::double_start")
    try:
        from ..monitor import flight_recorder as _flight

        _flight.record_event("profiler_double_start", **fields)
    except Exception:
        pass


def start_profiler(state="All", tracer_option="Default", trace_dir=None):
    """EnableProfiler equivalent. state: CPU | GPU | All (accepted for
    compat; device tracing starts whenever state != CPU).

    Idempotent under a live trace: a second start used to let
    ``jax.profiler.start_trace`` raise out of the training loop (and the
    blanket except then wiped the live dir, orphaning the first trace so
    ``stop_profiler`` could never close it). Now a double start is a
    no-op flagged with a ``profiler_double_start`` flight event +
    ``profiler::double_start`` counter, and the original trace keeps its
    owner."""
    _enabled[0] = True
    if state == "CPU":
        return
    import jax

    if _device_trace_dir[0] is not None:
        _note_double_start(trace_dir=_device_trace_dir[0])
        return
    d = trace_dir or "/tmp/paddle_tpu_trace"
    os.makedirs(d, exist_ok=True)
    try:
        jax.profiler.start_trace(d)
        _device_trace_dir[0] = d
        _last_device_trace_dir[0] = d
    except RuntimeError:
        # a trace this module does not own is live (e.g. opprof's replay
        # trace, or user code driving jax.profiler directly): same no-op
        # contract, and never raise out of the training loop
        _note_double_start(trace_dir=d, owner="external")
    except Exception:
        _device_trace_dir[0] = None  # device tracing unsupported


def stop_profiler(sorted_key=None, profile_path=None, file=None):
    """DisableProfiler equivalent; writes chrome trace to profile_path.

    When ``sorted_key`` is given, prints the per-event aggregate table the
    reference's DisableProfiler emits (platform/profiler.h:208 /
    python/paddle/fluid/profiler.py) — Calls / Total / Min / Max / Ave /
    Ratio per event name, sorted by the requested key.
    """
    _enabled[0] = False
    if _device_trace_dir[0] is not None:
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _device_trace_dir[0] = None
    if profile_path:
        export_chrome_tracing(profile_path)
    if sorted_key is not None:
        print_summary(sorted_key=sorted_key, file=file)


def summary_records():
    """Aggregate collected events: name -> dict(calls,total,min,max,ave) in ms."""
    with _events_lock:
        evs = list(_events)
    agg = {}
    for ev in evs:
        rec = agg.setdefault(
            ev["name"], {"calls": 0, "total": 0.0, "min": float("inf"), "max": 0.0}
        )
        dur_ms = ev["dur"] / 1e3
        rec["calls"] += 1
        rec["total"] += dur_ms
        rec["min"] = min(rec["min"], dur_ms)
        rec["max"] = max(rec["max"], dur_ms)
    for rec in agg.values():
        rec["ave"] = rec["total"] / rec["calls"]
    return agg


_SORT_KEYS = {
    "default": None,
    "calls": "calls",
    "total": "total",
    "max": "max",
    "min": "min",
    "ave": "ave",
}


def print_summary(sorted_key="total", file=None):
    """Reference-style event summary table (profiler.py print_profiler)."""
    if sorted_key not in _SORT_KEYS:
        raise ValueError(
            f"sorted_key must be one of {sorted(_SORT_KEYS)}, got {sorted_key!r}"
        )
    agg = summary_records()
    if not agg:
        print("No profiler events recorded.", file=file)
        # counters are always-on (no start_profiler needed): still show them
        _print_counters(file)
        return
    grand_total = sum(r["total"] for r in agg.values()) or 1.0
    key = _SORT_KEYS[sorted_key]
    # "min" sorts ascending (reference profiler.py: the cheapest events
    # lead); every other key leads with the most expensive/most called
    ascending = key == "min"
    items = sorted(
        agg.items(), key=(lambda kv: kv[1][key]) if key else (lambda kv: kv[0]),
        reverse=key is not None and not ascending,
    )
    name_w = max(10, min(50, max(len(n) for n in agg)))
    header = (
        f"{'Event':<{name_w}}  {'Calls':>8}  {'Total(ms)':>12}  "
        f"{'Min(ms)':>10}  {'Max(ms)':>10}  {'Ave(ms)':>10}  {'Ratio':>7}"
    )
    bar = "-" * len(header)
    print("\n------------------------->     Profiling Report     "
          "<-------------------------\n", file=file)
    order = "ascending" if ascending else "descending"
    print(f"Sorted by {sorted_key} in {order} order"
          if key else "Sorted by event name", file=file)
    print(bar, file=file)
    print(header, file=file)
    print(bar, file=file)
    for name, r in items:
        print(
            f"{name[:name_w]:<{name_w}}  {r['calls']:>8}  {r['total']:>12.4f}  "
            f"{r['min']:>10.4f}  {r['max']:>10.4f}  {r['ave']:>10.4f}  "
            f"{r['total'] / grand_total:>7.4f}",
            file=file,
        )
    print(bar, file=file)
    _print_counters(file, name_w, footer_bar=bar)


def _print_counters(file=None, name_w=40, footer_bar=None):
    snap = counters()
    if not snap:
        return
    print("Counters:", file=file)
    for name in sorted(snap):
        print(f"  {name:<{name_w}}  {snap[name]:>10}", file=file)
    if footer_bar:
        print(footer_bar, file=file)


def host_events():
    """Snapshot of the collected host spans (chrome-trace dict events)."""
    with _events_lock:
        return list(_events)


def reset_profiler():
    with _events_lock:
        _events.clear()


def export_chrome_tracing(path):
    """Write collected host events as a chrome://tracing JSON file."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with _events_lock:
        trace = {"traceEvents": list(_events)}
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None):
    """fluid.profiler.profiler context manager."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
