"""Quantization-aware layers.

Reference parity: fluid/contrib/slim/quantization/imperative/quant_nn.py
— QuantizedLinear/QuantizedConv2D wrap the fp layer, fake-quantizing the
weight (per-channel abs-max) and the input activation (EMA abs-max with
persisted scale/state/accum), so training sees int8 rounding while the
MXU still computes in bf16/f32 (QAT on TPU).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..nn import functional as F
from ..nn.layer_base import Layer
from ..ops.registry import kernel


class _ActQuant:
    """EMA activation quant-dequant over layer buffers."""

    def __init__(self, layer: Layer, prefix: str, moving_rate=0.9,
                 bit_length=8):
        self._layer = layer
        self._prefix = prefix
        self._rate = moving_rate
        self._bits = bit_length
        z = lambda v: Tensor(np.asarray(v, np.float32))
        layer.register_buffer(f"{prefix}_scale", z(0.0))
        layer.register_buffer(f"{prefix}_state", z(0.0))
        layer.register_buffer(f"{prefix}_accum", z(0.0))

    def __call__(self, x: Tensor) -> Tensor:
        lyr, p = self._layer, self._prefix
        scale = getattr(lyr, f"{p}_scale")
        state = getattr(lyr, f"{p}_state")
        accum = getattr(lyr, f"{p}_accum")
        out, s, st, ac = kernel(
            "fake_quantize_dequantize_moving_average_abs_max"
        )(
            x._array, scale._array, state._array, accum._array,
            bit_length=self._bits, moving_rate=self._rate,
            is_test=not lyr.training,
        )
        scale._array = s
        state._array = st
        accum._array = ac
        return Tensor._from_array(out, stop_gradient=x.stop_gradient)


def _quant_weight(w: Tensor, quant_axis: int, bits: int) -> Tensor:
    out, _ = kernel("fake_channel_wise_quantize_dequantize_abs_max")(
        w._array, bit_length=bits, quant_axis=quant_axis
    )
    return Tensor._from_array(out, stop_gradient=w.stop_gradient)


class QuantizedLinear(Layer):
    """quant_nn.py QuantizedLinear: shares the wrapped layer's parameters
    (training updates the original fp weights)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self._inner = layer
        self._wbits = weight_bits
        self._act = _ActQuant(self, "in", moving_rate, activation_bits)

    def forward(self, x):
        x = self._act(x)
        w = _quant_weight(self._inner.weight, 1, self._wbits)
        return F.linear(x, w, self._inner.bias)

    def weight_scales(self):
        _, s = kernel("fake_channel_wise_quantize_abs_max")(
            self._inner.weight._array, bit_length=self._wbits, quant_axis=1
        )
        return np.asarray(s)


class QuantizedConv2D(Layer):
    """quant_nn.py QuantizedConv2D (per-output-channel weight scales)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self._inner = layer
        self._wbits = weight_bits
        self._act = _ActQuant(self, "in", moving_rate, activation_bits)

    def forward(self, x):
        x = self._act(x)
        w = _quant_weight(self._inner.weight, 0, self._wbits)
        return F.conv2d(
            x, w, self._inner.bias, data_format=self._inner.data_format,
            **self._inner._attrs,
        )

    def weight_scales(self):
        _, s = kernel("fake_channel_wise_quantize_abs_max")(
            self._inner.weight._array, bit_length=self._wbits, quant_axis=0
        )
        return np.asarray(s)
